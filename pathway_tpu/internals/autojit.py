"""Auto-jit execution tier: traceable UDF chains → one vectorized dispatch.

PR 2's shard checker classifies every sync ``pw.udf`` as jit-traceable /
vmappable / host-only and records the class on the expression
(``expr._shard_class``) "for future auto-jit" — this module cashes that in.
When the expression compiler assembles a map program, every output
expression whose tree is built from numeric columns, exact arithmetic and
traceable UDFs is *fused* into a single batched program: one dispatch per
engine batch for the whole chain, instead of one Python call per row per
UDF (the framework-vs-raw throughput tax, VERDICT #5).

Execution backends, strongest first:

- ``xla``  — the fused tree under ``jax.jit`` (x64 so Python float/int
  semantics carry over), with batch sizes padded to power-of-two buckets
  so streaming tick sizes never cause per-shape recompiles (the Ragged
  Paged Attention lesson: variable-shape work without a compile zoo).
  Operators hosting an XLA-backed program are marked ``device_bound`` so
  they ride the scheduler's pipelined device leg (engine/device_bridge.py).
- ``numpy`` — the same tree broadcast over numpy arrays. Bit-exact with
  the interpreter by construction (numpy elementwise IEEE ops are the
  same ops CPython uses), still one dispatch per batch.
- ``interp`` — the per-row interpreted path (the fallback fns the
  expression compiler builds anyway). Ground truth.

**Byte-identity with the interpreter is the invariant** — auto-jit may
never change results, only make them faster. Three mechanisms enforce it:

1. *Static exactness gating.* XLA CPU contracts ``a*b+c`` into an FMA
   (measured: 1-ulp divergence; no DebugOptions flag disables it), so any
   tree with compounding float arithmetic — or a UDF body we cannot prove
   free of it — is statically barred from the ``xla`` backend and runs on
   the ``numpy`` backend instead. Division inside UDF bodies likewise
   (XLA int division by zero is UB; Python raises → per-cell ERROR).
2. *Per-batch input guards.* Rows whose cells are not exactly the static
   dtype (Python ``int``/``float``/``bool``; no bigints past ±2^31, no
   ERROR/None) are split out and evaluated on the interpreted path, then
   spliced back — the fast path never sees a value it could mangle.
3. *Verify-then-trust.* A program's first live dispatch on each backend
   is compared cell-for-cell (type and value) against the interpreter; a
   mismatch demotes to the next backend, loudly, once. A UDF that fails
   tracing at execution time (data-dependent control flow the AST pass
   could not see) demotes the same way — ``PATHWAY_AUTO_JIT`` can
   therefore never change a pipeline's output, only its speed.

The tier is on by default; ``PATHWAY_AUTO_JIT=0`` disables it everywhere
(compilation, the PWT110 diagnostic wording, warmup, metrics report it
as disabled).
"""

from __future__ import annotations

import ast
import logging
import os
import threading
import weakref
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.error import ERROR

log = logging.getLogger("pathway_tpu.autojit")

# below this many clean rows a batch stays interpreted: array setup beats
# the per-row savings only past a handful of rows (same threshold as the
# compiler's numeric fast paths)
MIN_ROWS = 8
# |int| bound for fast-path cells: products of two guarded ints stay well
# inside int64, so a single multiply can never wrap (deeper int chains are
# bounded by the static op scan — see _body_traits)
INT_GUARD = 1 << 31
_BUCKET_MIN = 8

_ENABLE_VALUES_OFF = ("0", "false", "off", "no")


def autojit_enabled() -> bool:
    """The ``PATHWAY_AUTO_JIT`` escape hatch, honored everywhere (default
    on)."""
    return os.environ.get("PATHWAY_AUTO_JIT", "1").lower() \
        not in _ENABLE_VALUES_OFF


# ---------------------------------------------------------------------------
# tier-wide instrumentation (exported on /metrics + /status, shown by the
# StatsMonitor pipelining panel, reported by bench.py's framework leg)
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
_STATS = {
    "programs": 0,            # fused programs built this process
    "compiles": 0,            # XLA bucket compiles (distinct shapes walked)
    "demotions": 0,           # backend demotions (xla→numpy→interp)
    "device_dispatches": 0,   # batches dispatched through the XLA backend
    "vector_dispatches": 0,   # batches dispatched through the numpy backend
    "fallback_batches": 0,    # batches that fell back to the interpreter
}

# live fused programs, for pw.warmup() bucket walking and /status
_REGISTRY: "weakref.WeakSet[FusedProgram]" = weakref.WeakSet()


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def autojit_stats() -> dict:
    """Snapshot of the tier's counters plus the live-program backend mix."""
    with _STATS_LOCK:
        snap = dict(_STATS)
    backends: dict[str, int] = {}
    buckets = 0
    for prog in list(_REGISTRY):
        backends[prog.backend] = backends.get(prog.backend, 0) + 1
        buckets += len(prog._buckets)
    snap["enabled"] = autojit_enabled()
    snap["live_programs"] = backends
    snap["bucket_count"] = buckets
    return snap


def reset_stats() -> None:
    """Test hook: zero the counters (the registry drains by gc)."""
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


# ---------------------------------------------------------------------------
# UDF classification + body traits
# ---------------------------------------------------------------------------

def _classification(expr: ex.ApplyExpression):
    """The recorded shard-checker class, computed lazily when the static
    check did not run (same attribute, so the two paths share the cache)."""
    cls = getattr(expr, "_shard_class", None)
    if cls is None:
        from pathway_tpu.internals.static_check.shard_check import classify_udf

        cls = classify_udf(expr._fn)
        expr._shard_class = cls
    return cls


def _body_traits(fn) -> dict:
    """Static scan of a UDF body for exactness hazards the classifier does
    not track: division (XLA int div-by-zero is UB; float differs from
    Python's raise), pow (libm vs XLA approximations), compounding float
    arithmetic (XLA CPU FMA contraction), numpy usage (numpy ufuncs
    reject tracers, so the body is host-vectorizable but not XLA-traceable),
    and truthiness constructs (``and``/``or``/chained comparisons return
    an OPERAND per Python semantics — arrays cannot reproduce that, and
    ``bool(array)`` raises, so they are barred rather than demoted noisily
    at runtime). ``opaque=True`` (no source) assumes every hazard."""
    from pathway_tpu.internals.static_check.shard_check import _function_node

    try:
        node = _function_node(fn)
    except Exception:
        node = None
    if node is None:
        return {"opaque": True, "division": True, "pow": True,
                "arith_ops": 99, "numpy": True, "math": True,
                "math_attrs": set(), "truthy": True, "node": None}
    division = pow_ = False
    arith = 0
    uses_np = uses_math = truthy = False
    math_attrs: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, (ast.BinOp, ast.AugAssign)):
            op = n.op
            if isinstance(op, (ast.Div, ast.FloorDiv, ast.Mod)):
                division = True
            elif isinstance(op, ast.Pow):
                pow_ = True
            if isinstance(op, (ast.Add, ast.Sub, ast.Mult, ast.Div,
                               ast.FloorDiv, ast.Mod, ast.Pow)):
                arith += 1
        elif isinstance(n, ast.Name) and n.id in ("np", "numpy"):
            uses_np = True
        elif isinstance(n, ast.Name) and n.id == "math":
            uses_math = True
        elif isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id == "math":
            math_attrs.add(n.attr)
        elif isinstance(n, ast.BoolOp):
            truthy = True
        elif isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
            truthy = True  # `not arr` calls bool(arr) — raises on arrays
        elif isinstance(n, ast.Compare) and len(n.ops) > 1:
            truthy = True  # a < b < c lowers to `and` on arrays
    return {"opaque": False, "division": division, "pow": pow_,
            "arith_ops": arith, "numpy": uses_np, "math": uses_math,
            "math_attrs": math_attrs, "truthy": truthy, "node": node}


# ---------------------------------------------------------------------------
# int-overflow bit bounds
# ---------------------------------------------------------------------------
# The interpreter computes on Python bigints; the fused path on int64.
# Byte-identity therefore requires a PROOF that no intermediate can leave
# int64 — verify-then-trust only sees the first batch, and a later batch
# overflowing silently (numpy int64 wraps without warning, XLA likewise)
# would be exactly the wrong-but-plausible failure the invariant exists to
# prevent. Bits here bound magnitude: value v has "b bits" iff |v| < 2^b.
# Guarded leaf cells are < 2^31 (INT_GUARD); every arithmetic node
# combines bounds (add/sub: max+1, mult: sum, floordiv/mod: left) and any
# node past 63 bits — or any construct whose bound is unknowable — bars
# the tree from fusing.

_INT_BITS_MAX = 63  # int64 holds |v| < 2^63


class _BitsUnknown(Exception):
    """Raised by the body walker at any construct it cannot bound."""


# float64 represents ints exactly only below 2^53: any int operand past
# that mixed with a float (arith OR comparison) diverges from Python's
# exact int/float semantics once promoted to float64
_FLOAT_EXACT_BITS = 53


def _check_float_mix(lk, lb, rk, rb) -> None:
    """Bar int/float mixing whose int side may exceed float64's exact
    integer range (Python converts/compares exactly; numpy/XLA round)."""
    if lk == "i" and rk == "f" and lb is not None \
            and lb > _FLOAT_EXACT_BITS:
        raise _BitsUnknown(f"int operand up to {lb} bits mixed with float")
    if rk == "i" and lk == "f" and rb is not None \
            and rb > _FLOAT_EXACT_BITS:
        raise _BitsUnknown(f"int operand up to {rb} bits mixed with float")


def _body_int_bits(node, params: dict) -> int | None:
    """Max int bits over every intermediate of a UDF body AST, or None
    when unprovable. ``params`` maps parameter names to the
    ``(kind, bits)`` of the argument tree feeding them."""
    seen_max = 0

    def mark(b: int) -> int:
        nonlocal seen_max
        seen_max = max(seen_max, b)
        if b > _INT_BITS_MAX:
            raise _BitsUnknown(f"intermediate needs {b} bits")
        return b

    def expr(n, env) -> tuple[str, int | None]:
        """(kind, bits): kind i/f/b; bits only for i."""
        if isinstance(n, ast.Constant):
            v = n.value
            if isinstance(v, bool):
                return "b", None
            if isinstance(v, int):
                return "i", mark(max(1, v.bit_length()))
            if isinstance(v, float):
                return "f", None
            raise _BitsUnknown(f"constant {type(v).__name__}")
        if isinstance(n, ast.Name):
            if n.id in env:
                k, b = env[n.id]
                return k, b
            raise _BitsUnknown(f"free name {n.id!r}")
        if isinstance(n, ast.BinOp):
            lk, lb = expr(n.left, env)
            rk, rb = expr(n.right, env)
            op = n.op
            if isinstance(op, ast.Div):
                _check_float_mix(lk, lb, rk, rb)
                return "f", None
            if "f" in (lk, rk):
                if isinstance(op, (ast.Add, ast.Sub, ast.Mult,
                                   ast.FloorDiv, ast.Mod)):
                    _check_float_mix(lk, lb, rk, rb)
                    return "f", None
                raise _BitsUnknown("float op")
            if lk != "i" or rk != "i":
                raise _BitsUnknown("non-numeric operand")
            if isinstance(op, (ast.Add, ast.Sub)):
                return "i", mark(max(lb, rb) + 1)
            if isinstance(op, ast.Mult):
                return "i", mark(lb + rb)
            if isinstance(op, ast.FloorDiv):
                # |a // b| <= |a| for |b| >= 1 (b == 0 raises -> fallback)
                return "i", mark(lb)
            if isinstance(op, ast.Mod):
                # |a % b| < |b| — bounded by the RIGHT operand; the left
                # bound would "prove" (-1 % (y*y)) * x safe at 33 bits
                # when it really needs ~93
                return "i", mark(rb)
            # NO bitwise ops: two's-complement breaks every magnitude
            # bound on negative operands (-1 & v == v, -8 ^ 8 == -16),
            # and a negative shift count raises in Python but is C-UB
            # vectorized — the sign is not tracked here, so none of
            # them can be bounded soundly
            raise _BitsUnknown(type(op).__name__)
        if isinstance(n, ast.UnaryOp):
            if isinstance(n.op, (ast.USub, ast.UAdd)):
                k, b = expr(n.operand, env)
                return k, (mark(b + 1) if k == "i" else b)
            if isinstance(n.op, ast.Not):
                expr(n.operand, env)
                return "b", None
            raise _BitsUnknown("invert")
        if isinstance(n, ast.IfExp):
            expr(n.test, env)
            tk, tb = expr(n.body, env)
            ek, eb = expr(n.orelse, env)
            if tk != ek:
                raise _BitsUnknown("mixed-kind conditional")
            if tk == "i":
                return "i", mark(max(tb, eb))
            return tk, None
        if isinstance(n, ast.Compare):
            lk, lb = expr(n.left, env)
            for c in n.comparators:
                rk, rb = expr(c, env)
                # Python compares int-vs-float EXACTLY; numpy/XLA promote
                # int64 to float64, which rounds past 2^53
                _check_float_mix(lk, lb, rk, rb)
                lk, lb = rk, rb
            return "b", None
        if isinstance(n, ast.Call):
            fname = None
            if isinstance(n.func, ast.Name):
                fname = n.func.id
            elif isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == "math":
                fname = f"math.{n.func.attr}"
            args = [expr(a, env) for a in n.args]
            if fname == "abs" and args:
                return args[0]
            if fname == "float":
                return "f", None
            if fname == "int":
                # the guarded _pw_int cast raises past 2^62 (per-batch
                # fallback), so its RESULT is bounded even though its
                # float input is not
                return "i", mark(_INT_BITS_MAX - 1)
            if fname in ("math.sqrt", "math.fabs"):
                return "f", None
            raise _BitsUnknown(fname or "call")
        raise _BitsUnknown(type(n).__name__)

    env = dict(params)
    try:
        if isinstance(node, ast.Lambda):
            expr(node.body, env)
            return seen_max
        for stmt in node.body:
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    expr(stmt.value, env)
                return seen_max
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                env[stmt.targets[0].id] = expr(stmt.value, env)
            elif isinstance(stmt, ast.AugAssign) and \
                    isinstance(stmt.target, ast.Name):
                synthetic = ast.BinOp(
                    left=ast.Name(id=stmt.target.id, ctx=ast.Load()),
                    op=stmt.op, right=stmt.value)
                env[stmt.target.id] = expr(synthetic, env)
            elif isinstance(stmt, ast.Expr):
                continue  # docstring / bare expression
            else:
                raise _BitsUnknown(type(stmt).__name__)
        return seen_max
    except (_BitsUnknown, RecursionError):
        return None


# ---------------------------------------------------------------------------
# row-wise rewrite (the "vmap" arm): IfExp → where, math.* → exact xp.*
# ---------------------------------------------------------------------------

# math functions whose numpy/XLA counterparts are IEEE-exact matches of
# CPython's (sqrt is correctly rounded everywhere; fabs is a sign op).
# exp/log/sin/... are approximated differently per backend and would break
# byte-identity silently, so they are NOT mapped — bodies using them stay
# interpreted.
_EXACT_MATH = {"sqrt": "sqrt", "fabs": "_pw_fabs"}
_REWRITE_BUILTINS = {"abs", "float", "int"}


class _RowwiseRewriter(ast.NodeTransformer):
    """Rewrites the restricted per-scalar forms the classifier admits as
    "vmappable" into array-safe code over an ``xp`` namespace: scalar
    conditionals become ``_pw_where`` (with a trace-time branch-dtype
    equality check, since ``where`` promotes where Python picks per-row),
    ``math.sqrt``/``math.fabs`` become exact ``xp`` calls, ``float``/
    ``int`` casts become exact dtype casts. Anything else untranslatable
    marks the rewrite failed."""

    def __init__(self):
        self.ok = True
        # int() lowers to the range-guarded _pw_int, whose bounds check
        # cannot trace under jit (and an unguarded trunc-to-int64 of an
        # unbounded float would silently wrap) — numpy backend only
        self.no_xla = False

    def visit_IfExp(self, node):
        node = self.generic_visit(node)
        return ast.copy_location(
            ast.Call(func=ast.Name(id="_pw_where", ctx=ast.Load()),
                     args=[node.test, node.body, node.orelse], keywords=[]),
            node)

    def visit_Call(self, node):
        node = self.generic_visit(node)
        func = node.func
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "math":
            target = _EXACT_MATH.get(func.attr)
            if target is None:
                self.ok = False
                return node
            if target.startswith("_pw"):
                name = ast.Name(id=target, ctx=ast.Load())
            else:
                name = ast.Attribute(
                    value=ast.Name(id="xp", ctx=ast.Load()),
                    attr=target, ctx=ast.Load())
            return ast.copy_location(
                ast.Call(func=name, args=node.args, keywords=node.keywords),
                node)
        if isinstance(func, ast.Name):
            if func.id == "float":
                return ast.copy_location(
                    ast.Call(func=ast.Name(id="_pw_float", ctx=ast.Load()),
                             args=node.args, keywords=node.keywords), node)
            if func.id == "int":
                self.no_xla = True
                return ast.copy_location(
                    ast.Call(func=ast.Name(id="_pw_int", ctx=ast.Load()),
                             args=node.args, keywords=node.keywords), node)
            if func.id == "abs":
                return node  # __abs__ works on arrays and tracers alike
            self.ok = False
        return node


def body_fusable(fn) -> bool:
    """Cheap static screen for the DIAGNOSTICS (PWT110 wording): False
    when the body carries a hazard the tier will definitely refuse —
    opaque source, truthiness over operands, ``math.*`` without an
    IEEE-exact vector counterpart, ``pow``. The compiler applies the
    stricter dtype/int-overflow gates on top, so True means "expected to
    fuse", never a guarantee — the wording stays hedged accordingly."""
    try:
        traits = _body_traits(fn)
    except Exception:
        return False
    if traits["opaque"] or traits["truthy"] or traits["pow"]:
        return False
    if traits["math_attrs"] - set(_EXACT_MATH):
        return False
    return True


def _rewrite_namespace(xp) -> dict:
    """The helper namespace rewritten bodies run in. ``_pw_where`` rejects
    mixed-dtype branches at trace/broadcast time (Python's conditional is
    type-preserving per row; ``where`` would promote) — the rejection
    surfaces as a demotion, never a wrong value."""

    def _pw_where(c, a, b):
        aa, bb = xp.asarray(a), xp.asarray(b)
        if aa.dtype != bb.dtype:
            raise TypeError(
                "auto-jit: conditional branches have different dtypes "
                f"({aa.dtype} vs {bb.dtype}) — per-row type preservation "
                "cannot be vectorized")
        return xp.where(c, aa, bb)

    def _pw_float(x):
        return xp.asarray(x).astype(xp.float64)

    def _pw_int(x):
        # Python's int(float) is exact at any magnitude; int64 is not.
        # Out-of-range (or non-finite) inputs raise FloatingPointError so
        # the dispatcher falls back to the interpreter for THIS batch
        # without demoting the tier — same contract as a zero divisor.
        arr = xp.asarray(x)
        if bool(np.any(~np.isfinite(arr) | (np.abs(arr) >= float(1 << 62)))):
            raise FloatingPointError(
                "auto-jit: int() cast outside int64-exact range")
        return xp.trunc(arr).astype(xp.int64)

    def _pw_fabs(x):
        return xp.abs(xp.asarray(x).astype(xp.float64))

    return {"xp": xp, "_pw_where": _pw_where, "_pw_float": _pw_float,
            "_pw_int": _pw_int, "_pw_fabs": _pw_fabs}


def _rewrite_rowwise(fn) -> tuple[Callable[[Any], Callable], bool] | None:
    """``(build(xp) -> batched fn, no_xla)`` for a vmappable body, or
    None. The rewritten function is elementwise, so broadcasting the
    arrays through it IS the vmap of the scalar original (the admitted
    forms are straight-line scalar code — no shape-dependent behavior to
    diverge)."""
    from pathway_tpu.internals.static_check.shard_check import _function_node

    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    closure_modules: dict[str, Any] = {}
    if code.co_freevars:
        # closure cells do not survive re-compilation, and freezing a
        # mutable cell would silently diverge from the live interpreter
        # path — EXCEPT cells holding module objects (a UDF defined
        # inside a function whose enclosing scope did `import math`):
        # modules are process singletons, so binding them is exact
        import types

        for name, cell in zip(code.co_freevars, fn.__closure__ or ()):
            try:
                val = cell.cell_contents
            except ValueError:  # empty cell
                return None
            if not isinstance(val, types.ModuleType):
                return None
            closure_modules[name] = val
    node = _function_node(fn)
    if node is None:
        return None
    rewriter = _RowwiseRewriter()
    if isinstance(node, ast.Lambda):
        new = rewriter.visit(
            ast.Expression(body=ast.Lambda(args=node.args, body=node.body)))
        if not rewriter.ok:
            return None
        mode, tree = "eval", new
    else:
        fndef = ast.FunctionDef(
            name=node.name, args=node.args, body=node.body,
            decorator_list=[], returns=None, type_params=[])
        new = rewriter.visit(ast.Module(body=[fndef], type_ignores=[]))
        if not rewriter.ok:
            return None
        mode, tree = "exec", new
    ast.fix_missing_locations(tree)
    try:
        compiled = compile(tree, f"<autojit:{code.co_filename}>", mode)
    except (SyntaxError, TypeError, ValueError):
        return None
    fn_globals = getattr(fn, "__globals__", {})

    def build(xp):
        ns = dict(fn_globals)
        ns.update(closure_modules)
        ns.update(_rewrite_namespace(xp))
        if mode == "eval":
            return eval(compiled, ns)  # noqa: S307 — our own rewritten AST
        exec(compiled, ns)  # noqa: S102
        return ns[node.name]

    return build, rewriter.no_xla


# ---------------------------------------------------------------------------
# expression-tree emitter
# ---------------------------------------------------------------------------

_KIND_BY_DTYPE = None  # {dtype: numpy kind char}, populated lazily


def _leaf_kind(dtype) -> str | None:
    global _KIND_BY_DTYPE
    if _KIND_BY_DTYPE is None:
        _KIND_BY_DTYPE = {dt.INT: "i", dt.FLOAT: "f", dt.BOOL: "b"}
    return _KIND_BY_DTYPE.get(dt.unoptionalize(dtype))


_NP_DTYPE = {"i": np.int64, "f": np.float64, "b": np.bool_}

# expression-level binary ops with IEEE-exact vector semantics. The
# division family is deliberately absent: a zero divisor raises in Python
# (→ per-cell ERROR) but yields inf/0 vectorized, and the interpreter's
# numeric fast path already owns those guards.
_BIN_ARITH = {"+", "-", "*"}
_BIN_CMP = {"<", "<=", ">", ">=", "==", "!="}


class _Tree:
    """One emitted output expression: ``build(xp) -> f(env) -> array`` over
    the group's leaf environment, plus the exactness metadata the backend
    gate needs."""

    __slots__ = ("build", "kind", "fdepth", "xla_ok", "has_udf", "labels",
                 "ibits")

    def __init__(self, build, kind, fdepth=0, xla_ok=True, has_udf=False,
                 labels=(), ibits=None):
        self.build = build
        self.kind = kind          # result numpy kind: i / f / b
        self.fdepth = fdepth      # chained float-arith depth (FMA risk at 2)
        self.xla_ok = xla_ok
        self.has_udf = has_udf
        self.labels = tuple(labels)
        # int-magnitude bound: |value| < 2^ibits, proven statically (None
        # for f/b results). The guard that keeps int64 from wrapping where
        # the interpreter would have promoted to bigint.
        self.ibits = ibits if kind == "i" else None


class _LeafMap:
    """Assigns stable env slots to column references (deduped by column)."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.slots: dict[tuple[int, str], int] = {}
        self.refs: list[ex.ColumnReference] = []
        self.kinds: list[str] = []

    def slot(self, ref: ex.ColumnReference, kind: str) -> int:
        key = (id(ref.table), ref.name)
        pos = self.slots.get(key)
        if pos is None:
            pos = len(self.refs)
            self.slots[key] = pos
            self.refs.append(ref)
            self.kinds.append(kind)
        return pos

    def positions(self) -> list[int]:
        return [self.ctx.position(r) for r in self.refs]


def _emit(expr, leaves: _LeafMap) -> _Tree | None:
    """Recursive tree build; None marks the subtree non-fusable."""
    from pathway_tpu.internals.type_inference import infer_dtype

    if isinstance(expr, ex.IdExpression):
        return None
    if type(expr) is ex.ColumnReference:
        try:
            kind = _leaf_kind(infer_dtype(expr))
        except Exception:
            return None
        if kind is None:
            return None
        pos = leaves.slot(expr, kind)
        return _Tree(lambda xp, _p=pos: (lambda env: env[_p]), kind,
                     ibits=31)  # cells guarded to |v| < 2^31 at dispatch
    if isinstance(expr, ex.ConstExpression):
        v = expr._value
        tv = type(v)
        if tv is bool:
            kind = "b"
        elif tv is int:
            if not (-INT_GUARD < v < INT_GUARD):
                return None
            kind = "i"
        elif tv is float:
            kind = "f"
        else:
            return None
        return _Tree(lambda xp, _v=v: (lambda env: _v), kind,
                     ibits=max(1, v.bit_length()) if kind == "i" else None)
    if isinstance(expr, ex.UnaryExpression) and expr._op == "-":
        arg = _emit(expr._arg, leaves)
        if arg is None or arg.kind not in "if":
            return None
        return _Tree(
            # true negation, NOT `0 - x`: subtraction-from-zero turns
            # -0.0 into +0.0 where Python's unary minus keeps the sign
            lambda xp, _a=arg.build: (
                lambda env, _f=_a(xp): -_f(env)),
            arg.kind, arg.fdepth, arg.xla_ok, arg.has_udf, arg.labels,
            ibits=arg.ibits)
    if isinstance(expr, ex.BinaryExpression):
        op = expr._op
        if op not in _BIN_ARITH and op not in _BIN_CMP:
            return None
        lt = _emit(expr._left, leaves)
        rt = _emit(expr._right, leaves)
        if lt is None or rt is None:
            return None
        if lt.kind not in "if" or rt.kind not in "if":
            return None
        import operator

        py_op = {"+": operator.add, "-": operator.sub, "*": operator.mul,
                 "<": operator.lt, "<=": operator.le, ">": operator.gt,
                 ">=": operator.ge, "==": operator.eq,
                 "!=": operator.ne}[op]

        def build(xp, _l=lt.build, _r=rt.build, _o=py_op):
            lf, rf = _l(xp), _r(xp)
            return lambda env: _o(lf(env), rf(env))

        xla_ok = lt.xla_ok and rt.xla_ok
        ibits = None
        if {lt.kind, rt.kind} == {"i", "f"}:
            # int/float mixing (arith or comparison): Python converts and
            # compares EXACTLY; float64 promotion rounds past 2^53
            int_side = lt if lt.kind == "i" else rt
            if int_side.ibits is None or int_side.ibits > _FLOAT_EXACT_BITS:
                return None
        if op in _BIN_ARITH:
            kind = "f" if "f" in (lt.kind, rt.kind) else "i"
            fdepth = (max(lt.fdepth, rt.fdepth) + 1) if kind == "f" else 0
            if fdepth >= 2:
                xla_ok = False  # XLA CPU FMA contraction (1-ulp divergence)
            if kind == "i":
                ibits = (lt.ibits + rt.ibits if op == "*"
                         else max(lt.ibits, rt.ibits) + 1)
                if ibits > _INT_BITS_MAX:
                    return None  # could leave int64 where Python promotes
        else:
            kind, fdepth = "b", 0
        return _Tree(build, kind, fdepth, xla_ok,
                     lt.has_udf or rt.has_udf, lt.labels + rt.labels,
                     ibits=ibits)
    if isinstance(expr, ex.IfElseExpression):
        ct = _emit(expr._if, leaves)
        tt = _emit(expr._then, leaves)
        et = _emit(expr._else, leaves)
        if ct is None or tt is None or et is None or ct.kind != "b" \
                or tt.kind != et.kind or tt.kind not in "if":
            return None

        def build(xp, _c=ct.build, _t=tt.build, _e=et.build):
            cf, tf, ef = _c(xp), _t(xp), _e(xp)
            return lambda env: xp.where(cf(env), tf(env), ef(env))

        return _Tree(build, tt.kind, max(tt.fdepth, et.fdepth),
                     ct.xla_ok and tt.xla_ok and et.xla_ok,
                     ct.has_udf or tt.has_udf or et.has_udf,
                     ct.labels + tt.labels + et.labels,
                     ibits=(max(tt.ibits, et.ibits)
                            if tt.kind == "i" else None))
    if type(expr) is ex.ApplyExpression:  # excludes the async subclasses
        return _emit_apply(expr, leaves)
    return None


def _globals_fusable(fn, node) -> bool:
    """True iff every name the body loads resolves to a parameter, a
    local assignment, a builtin, or a MODULE global. Non-module globals
    (a tunable ``SCALE = 2.0``) are refused: the fused program would
    freeze them (globals-dict copy for rewritten bodies, trace-time
    baking under jit) while the interpreter fallback reads them live —
    and the classifier admits such bodies as traceable, so without this
    gate a mid-run mutation silently diverges. Modules are process
    singletons; attribute lookups on them stay live in the rewritten
    namespace."""
    if node is None:
        return False
    import builtins
    import types

    bound: set[str] = set()
    arg_obj = node.args
    for a in (list(arg_obj.posonlyargs) + list(arg_obj.args)
              + list(arg_obj.kwonlyargs)):
        bound.add(a.arg)
    for v in (arg_obj.vararg, arg_obj.kwarg):
        if v is not None:
            bound.add(v.arg)
    # only the BODY executes per call — decorators (`@pw.udf`) and
    # annotations resolve at def time, and a decorator name imported in
    # an enclosing function scope is invisible to fn.__globals__ without
    # being a runtime read at all
    body = node.body if isinstance(node.body, list) else [node.body]
    body_nodes = [x for stmt in body for x in ast.walk(stmt)]
    for n in body_nodes:
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            bound.add(n.id)
    fn_globals = getattr(fn, "__globals__", {}) or {}
    closure_names = set(getattr(fn, "__code__", None).co_freevars
                        if getattr(fn, "__code__", None) else ())
    for n in body_nodes:
        if not (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)):
            continue
        name = n.id
        if name in bound:
            continue
        if name in closure_names:
            continue  # module-only, enforced by _rewrite_rowwise / below
        if name in fn_globals:
            if not isinstance(fn_globals[name], types.ModuleType):
                return False
        elif not hasattr(builtins, name):
            return False
    if closure_names:
        # non-rewrite path: closure cells must also be module-valued
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                if not isinstance(cell.cell_contents, types.ModuleType):
                    return False
            except ValueError:
                return False
    return True


def _emit_apply(expr: ex.ApplyExpression, leaves: _LeafMap) -> _Tree | None:
    from pathway_tpu.internals.type_inference import infer_dtype

    if getattr(expr, "_batch", False):
        return None  # batch UDFs already amortize dispatch (PR 4 path)
    cls = _classification(expr)
    if not cls.jit_eligible:
        return None
    args = [_emit(a, leaves) for a in expr._args]
    kwargs = {k: _emit(v, leaves) for k, v in expr._kwargs.items()}
    if any(a is None for a in args) or any(v is None for v in kwargs.values()):
        return None
    try:
        ret_kind = _leaf_kind(infer_dtype(expr))
    except Exception:
        ret_kind = None
    if ret_kind is None:
        # no declared return type (plain pw.apply): predict from the arg
        # kinds — arithmetic preserves kind, and a misprediction only
        # tightens a parent's gating or trips the dtype checks/verify,
        # never a silent wrong value
        arg_kinds = [t.kind for t in args] + [t.kind for t in
                                              kwargs.values()]
        if not arg_kinds:
            return None
        ret_kind = "f" if "f" in arg_kinds else (
            "i" if "i" in arg_kinds else "b")
    fn = expr._fn
    traits = _body_traits(fn)
    if traits["truthy"]:
        # and/or/chained-compare return an OPERAND per Python truthiness;
        # arrays cannot reproduce that (bool(array) raises) — interpreted
        return None
    if traits["pow"] and ret_kind == "i":
        return None  # int ** int grows past int64 unboundedly
    needs_rewrite = cls.kind == "vmappable" or (
        not traits["opaque"] and traits["math"])
    rewrite_no_xla = False
    if needs_rewrite:
        rewritten = _rewrite_rowwise(fn)
        if rewritten is None:
            return None
        body_build, rewrite_no_xla = rewritten
    else:
        def body_build(xp, _fn=fn):
            return _fn
    if not traits["opaque"] and not _globals_fusable(fn, traits["node"]):
        # the body reads a module-level name that is NOT a module: the
        # fused program would snapshot/bake its value while the
        # interpreter fallback reads it live — a mid-run mutation would
        # split a batch between stale and live values, and the
        # DeterministicMapOperator replay cache this fusion elides exists
        # precisely for such unverified-deterministic bodies
        return None
    # int-overflow proof (see _body_int_bits): the interpreter promotes to
    # bigint, int64 wraps — any int-involved body must bound every
    # intermediate within int64 or stay interpreted. An int() cast
    # ANYWHERE in the body forces the proof too: a predicted-float return
    # kind would otherwise skip it while _pw_int mints int64 values up to
    # 2^62 whose products wrap silently
    body_has_int_cast = traits["node"] is not None and any(
        isinstance(c, ast.Call) and isinstance(c.func, ast.Name)
        and c.func.id == "int" for c in ast.walk(traits["node"]))
    int_involved = ret_kind == "i" or body_has_int_cast or any(
        t.kind == "i" for t in args) or any(
        t.kind == "i" for t in kwargs.values())
    ibits = None
    if int_involved:
        node = traits["node"]
        if node is None:
            return None  # opaque body: unprovable
        arg_objs = list(node.args.posonlyargs) + list(node.args.args)
        if node.args.vararg or node.args.kwarg or len(arg_objs) < len(args):
            return None
        params = {a.arg: (t.kind, t.ibits)
                  for a, t in zip(arg_objs, args)}
        for k, t in kwargs.items():
            params[k] = (t.kind, t.ibits)
        ibits = _body_int_bits(node, params)
        if ibits is None:
            return None
        ibits = max(ibits, 1)
    # backend exactness gate for the body (see module doc): division/pow/
    # compounding-float-arith/numpy/math-use bar the XLA backend
    float_involved = ret_kind == "f" or any(
        t.kind == "f" for t in args) or any(
        t.kind == "f" for t in kwargs.values())
    xla_ok = not traits["division"] and not traits["pow"] \
        and not traits["numpy"] and not traits["math"] \
        and not rewrite_no_xla \
        and not (float_involved and traits["arith_ops"] >= 2)
    xla_ok = xla_ok and all(t.xla_ok for t in args) and all(
        t.xla_ok for t in kwargs.values())
    name = getattr(fn, "__name__", "<udf>")

    def build(xp, _args=tuple(args), _kwargs=dict(kwargs), _bb=body_build):
        f = _bb(xp)
        arg_fns = [t.build(xp) for t in _args]
        kw_fns = {k: t.build(xp) for k, t in _kwargs.items()}

        def run(env):
            return f(*[g(env) for g in arg_fns],
                     **{k: g(env) for k, g in kw_fns.items()})

        return run

    labels = (name,) + tuple(
        x for t in args for x in t.labels) + tuple(
        x for t in kwargs.values() for x in t.labels)
    return _Tree(build, ret_kind,
                 2 if (float_involved and traits["arith_ops"]) else 0,
                 xla_ok, True, labels,
                 ibits=ibits if ret_kind == "i" else None)


# ---------------------------------------------------------------------------
# the fused program
# ---------------------------------------------------------------------------

def _bucket(n: int) -> int:
    return max(_BUCKET_MIN, 1 << (n - 1).bit_length())


def _cells_equal(a, b) -> bool:
    if a is b:
        return True
    if type(a) is not type(b):
        return False
    try:
        if a == b:
            # == calls -0.0 equal to 0.0; byte-identity does not
            if type(a) is float and a == 0.0:
                import math as _math

                return _math.copysign(1.0, a) == _math.copysign(1.0, b)
            return True
        return a != a and b != b  # NaN == NaN for identity purposes
    except Exception:
        return False


class FusedProgram:
    """One map program's fused output expressions (see module doc).

    ``dispatch(keys, rows, fallback_fns)`` returns the fused columns (in
    ``expr_idx`` order) or None when the whole batch must stay on the
    interpreted path. Rows whose cells fail the input guards are evaluated
    through ``fallback_fns`` (the interpreter) and spliced back, so a
    partially-dirty batch still vectorizes its clean majority.

    One program holds ALL the fusable expressions of a map — the leaf
    columns are extracted and guard-validated ONCE per batch, shared by
    both execution partitions: trees the exactness gate admits to XLA run
    under one ``jax.jit`` (one device dispatch per batch, the ``xla``
    partition), trees it bars (compounding float arithmetic, division
    bodies — see module doc) run broadcast over the same arrays on the
    ``numpy`` partition. A demotion collapses xla → numpy → interp for
    the whole program, loudly-once.
    """

    def __init__(self, expr_idx: list[int], trees: list[_Tree],
                 leaves: _LeafMap, label: str):
        self.expr_idx = list(expr_idx)
        self.leaf_pos = leaves.positions()
        self.leaf_kinds = list(leaves.kinds)
        self.label = label
        self._xla_part = [i for i, t in enumerate(trees) if t.xla_ok]
        self._np_part = [i for i, t in enumerate(trees) if not t.xla_ok]
        self.xla_ok = bool(self._xla_part)
        self._np_fn = self._build(np, trees)
        self._np_sub_fn = (self._build(np, [trees[i] for i in self._np_part])
                           if self._np_part else None)
        self._jit = None
        self._buckets: set[int] = set()
        self.backend = "numpy"
        self.verified = False
        self.dispatches = 0
        if self.xla_ok and autojit_enabled():
            self._arm_xla([trees[i] for i in self._xla_part])
        _REGISTRY.add(self)
        _bump("programs")

    @staticmethod
    def _build(xp, trees):
        fns = [t.build(xp) for t in trees]

        def fused(*arrays):
            return tuple(f(arrays) for f in fns)

        return fused

    def _arm_xla(self, xla_trees) -> None:
        """Probe the XLA partition under an abstract x64 trace; arm the
        jit only when the probe passes AND every output lands on a 64-bit
        dtype (a body casting to float32 would change cell values)."""
        try:
            import jax
            from jax.experimental import enable_x64

            fused = self._build(jax.numpy, xla_trees)
            specs = [jax.ShapeDtypeStruct((_BUCKET_MIN,),
                                          _NP_DTYPE[k])
                     for k in self.leaf_kinds]
            with enable_x64():
                out = jax.eval_shape(fused, *specs)
            if any(np.dtype(o.dtype) not in
                   (np.dtype(np.int64), np.dtype(np.float64),
                    np.dtype(np.bool_)) for o in out):
                raise TypeError(
                    f"non-64-bit output dtypes {[o.dtype for o in out]}")
            self._jit = jax.jit(fused)
            self.backend = "xla"
        except Exception as e:  # probe failure → numpy tier, recorded
            self._demote("numpy", f"XLA trace probe failed: {e!r}",
                         level=logging.INFO)

    # ------------------------------------------------------------------
    def _demote(self, to: str, reason: str,
                level: int = logging.WARNING) -> None:
        log.log(level,
                "auto-jit: program %s demoted %s -> %s: %s (results are "
                "unaffected — the slower tier takes over)",
                self.label, self.backend, to, reason)
        self.backend = to
        self.verified = False
        self._jit = None if to != "xla" else self._jit
        _bump("demotions")

    # ------------------------------------------------------------------
    @staticmethod
    def _clean_col(col: list, k: str):
        """Typed array for an all-clean column, else None. The common case
        (homogeneous, in-range cells) validates at C speed — set(map(type))
        and ndarray reductions — with no per-row Python loop."""
        types = set(map(type, col))
        if k == "i":
            if types != {int}:
                return None
            try:
                arr = np.asarray(col, np.int64)
            except OverflowError:  # a bigint cell slipped past int64
                return None
            # min/max, not abs: np.abs(-2**63) wraps to itself (negative)
            # and would sneak the worst possible cell past the guard
            if int(arr.max(initial=0)) >= INT_GUARD \
                    or int(arr.min(initial=0)) <= -INT_GUARD:
                return None
            return arr
        if k == "f":
            return np.asarray(col, np.float64) if types == {float} else None
        return np.asarray(col, np.bool_) if types == {bool} else None

    def _split_rows(self, rows):
        """(live_idx, dead_idx, arrays): live_idx None means every row is
        clean (fast path, no index lists materialized); arrays is None
        when too few rows survive the cell guards."""
        cols = [[r[p] for r in rows] for p in self.leaf_pos]
        n = len(rows)
        kinds = self.leaf_kinds
        arrays = []
        for col, k in zip(cols, kinds):
            arr = self._clean_col(col, k)
            if arr is None:
                break
            arrays.append(arr)
        else:
            return None, (), arrays
        # a dirty column: per-row scan splits the batch so the clean
        # majority still vectorizes
        live: list[int] = []
        dead: list[int] = []
        for i in range(n):
            ok = True
            for col, k in zip(cols, kinds):
                v = col[i]
                tv = type(v)
                if k == "f":
                    if tv is not float:
                        ok = False
                        break
                elif k == "i":
                    if tv is not int or not (-INT_GUARD < v < INT_GUARD):
                        ok = False
                        break
                elif tv is not bool:
                    ok = False
                    break
            (live if ok else dead).append(i)
        if len(live) < MIN_ROWS:
            return live, dead, None
        try:
            arrays = [np.asarray([c[i] for i in live], _NP_DTYPE[k])
                      for c, k in zip(cols, kinds)]
        except Exception:
            return live, dead, None
        return live, dead, arrays

    def _run_backend(self, arrays, n_live: int, warm: bool = False):
        """Raw fused outputs as numpy arrays of length ``n_live``, in tree
        order. On the ``xla`` backend the two partitions share the SAME
        guarded arrays: one jitted device dispatch for the xla trees, one
        broadcast pass for the numpy-only trees."""
        if self.backend == "xla":
            from jax.experimental import enable_x64

            b = _bucket(n_live)
            padded = arrays
            if b != n_live:
                padded = [np.pad(a, (0, b - n_live), mode="edge")
                          for a in arrays]
            if b not in self._buckets:
                self._buckets.add(b)
                _bump("compiles")
            with enable_x64():
                xla_outs = self._jit(*padded)
            if not warm:
                _bump("device_dispatches")
            merged: list = [None] * (len(self._xla_part)
                                     + len(self._np_part))
            for i, o in zip(self._xla_part, xla_outs):
                merged[i] = (np.asarray(o)[:n_live] if getattr(o, "ndim", 0)
                             else np.full(n_live, np.asarray(o)[()]))
            if self._np_sub_fn is not None:
                with np.errstate(divide="raise", over="raise",
                                 invalid="raise"):
                    np_outs = self._np_sub_fn(*arrays)
                if not warm:
                    _bump("vector_dispatches")
                for i, o in zip(self._np_part, np_outs):
                    merged[i] = (np.asarray(o) if getattr(o, "ndim", 0)
                                 else np.full(n_live, o))
            return merged
        with np.errstate(divide="raise", over="raise", invalid="raise"):
            outs = self._np_fn(*arrays)
        if not warm:
            _bump("vector_dispatches")
        return [np.asarray(o) if getattr(o, "ndim", 0)
                else np.full(n_live, o) for o in outs]

    def dispatch(self, keys, rows, fallback_fns):
        if self.backend == "interp" or not autojit_enabled():
            return None
        n = len(keys)
        if n < MIN_ROWS:
            return None
        live, dead, arrays = self._split_rows(rows)
        if arrays is None:
            _bump("fallback_batches")
            return None
        n_live = n if live is None else len(live)
        try:
            outs = self._run_backend(arrays, n_live)
            out_cols = [o.tolist() for o in outs]
        except FloatingPointError:
            # data-dependent (zero divisor / overflow in THIS batch):
            # interpret the batch, keep the tier armed
            _bump("fallback_batches")
            return None
        except Exception as e:
            # the runtime safety net: tracing/execution failed on real
            # data — demote loudly-once, results come from the fallback
            self._demote("numpy" if self.backend == "xla" else "interp",
                         f"dispatch failed: {e!r}")
            _bump("fallback_batches")
            return None
        if not self.verified:
            # verify-then-trust: the first live dispatch on each backend
            # is checked cell-for-cell against the interpreter
            if live is None:
                live_keys, live_rows = keys, rows
            else:
                live_keys = [keys[i] for i in live]
                live_rows = [rows[i] for i in live]
            expected = [fb(live_keys, live_rows) for fb in fallback_fns]
            for got_col, want_col in zip(out_cols, expected):
                for g, w in zip(got_col, want_col):
                    if not _cells_equal(g, w):
                        self._demote(
                            "numpy" if self.backend == "xla" else "interp",
                            f"first-batch verify mismatch: {g!r} != {w!r}")
                        _bump("fallback_batches")
                        return None
            self.verified = True
        self.dispatches += 1
        if not dead:
            return out_cols
        dead_keys = [keys[i] for i in dead]
        dead_rows = [rows[i] for i in dead]
        spliced = []
        for col, fb in zip(out_cols, fallback_fns):
            full: list = [None] * n
            fb_col = fb(dead_keys, dead_rows)
            for j, i in enumerate(live):
                full[i] = col[j]
            for j, i in enumerate(dead):
                full[i] = fb_col[j]
            spliced.append(full)
        return spliced

    # ------------------------------------------------------------------
    def warm(self, max_bucket: int | None = None) -> list[tuple]:
        """Walk the power-of-two buckets so no first-tick compile lands in
        serving latency (pw.warmup). Only the XLA backend compiles."""
        if self.backend != "xla" or self._jit is None:
            return []
        if max_bucket is None:
            try:
                max_bucket = int(os.environ.get(
                    "PATHWAY_AUTO_JIT_WARM_MAX", str(2048)))
            except ValueError:
                max_bucket = 2048
        out = []
        b = _BUCKET_MIN
        while b <= max_bucket:
            arrays = [np.ones(b, _NP_DTYPE[k]) for k in self.leaf_kinds]
            try:
                self._run_backend(arrays, b, warm=True)
            except FloatingPointError:
                pass  # data-dependent (ones hit a guard) — bucket compiled
            except Exception as e:
                self._demote("numpy", f"warmup dispatch failed: {e!r}")
                return out
            out.append(("autojit", (self.label, b)))
            b <<= 1
        return out


# ---------------------------------------------------------------------------
# compiler entry points
# ---------------------------------------------------------------------------

def fuse_program(exprs: list, ctx) -> list[FusedProgram]:
    """Fuse the traceable-UDF output expressions of one map program into
    ONE batched dispatch. Returns [] when the tier is off or nothing
    qualifies (a program with no eligible UDF keeps the interpreter's
    per-expression numeric fast paths — they already vectorize plain
    arithmetic).

    All fusable trees share one program — leaf extraction and the input
    guard run once per batch — with XLA-exact trees and numpy-only trees
    (compounding float arithmetic, division-bearing bodies — see the
    module doc) split into internal PARTITIONS, so one float chain cannot
    drag the whole program off the device tier."""
    if not autojit_enabled():
        return []
    leaves = _LeafMap(ctx)
    idx: list[int] = []
    trees: list[_Tree] = []
    for i, e in enumerate(exprs):
        if not isinstance(e, ex.ColumnExpression):
            continue
        try:
            t = _emit(e, leaves)
        except Exception:
            t = None
        if t is not None and t.has_udf:
            idx.append(i)
            trees.append(t)
    if not idx:
        return []
    # re-emit over a fresh leaf map so only the FUSED trees' columns are
    # extracted at dispatch (the probe map may have collected leaves of
    # trees that did not qualify)
    final = _LeafMap(ctx)
    trees = [_emit(exprs[i], final) for i in idx]
    if any(t is None for t in trees) or not final.refs:
        return []
    label = "+".join(sorted({x for t in trees for x in t.labels})
                     or {"<expr>"})
    try:
        return [FusedProgram(idx, trees, final, label)]
    except Exception as e:  # never let the tier break compilation
        log.info("auto-jit: fusing %s failed at build (%r) — "
                 "interpreted path keeps the program", label, e)
        return []


def discard_programs(programs) -> None:
    """Back out FusedPrograms built by a lowering path that then bailed
    (runner._lower_map_split): drop them from the warmup registry and the
    ``programs`` counter so /metrics counts only programs that can ever
    dispatch."""
    for prog in programs or ():
        _REGISTRY.discard(prog)
        _bump("programs", -1)


def _contains_host_udf(expr) -> bool:
    stack = [expr]
    while stack:
        e = stack.pop()
        if type(e) is ex.ApplyExpression and not getattr(e, "_batch", False):
            if _classification(e).kind == "host":
                return True
        stack.extend(getattr(e, "_deps", ()))
    return False


def split_map_exprs(exprs: list) -> tuple[list[int], list[int]] | None:
    """WindVE-style host/device split for a map program: when a select
    carries BOTH fusable-UDF expressions and host-only-UDF expressions,
    return (device_idx, host_idx) so the lowering can split them into two
    operators — the device part rides the pipelined bridge leg while the
    host part steps on the host thread, overlapping host-only UDF time
    with device time instead of serializing it. None = keep one operator.
    """
    if not autojit_enabled():
        return None
    leaves = _LeafMap(_NullCtx())
    device_idx: list[int] = []
    host_idx: list[int] = []
    host_udf_seen = False
    for i, e in enumerate(exprs):
        t = None
        if isinstance(e, ex.ColumnExpression):
            try:
                t = _emit(e, leaves)
            except Exception:
                t = None
        if t is not None and t.has_udf:
            device_idx.append(i)
        else:
            host_idx.append(i)
            if isinstance(e, ex.ColumnExpression) and _contains_host_udf(e):
                host_udf_seen = True
    if not device_idx or not host_idx or not host_udf_seen:
        return None
    return device_idx, host_idx


class _NullCtx:
    """Position-free stand-in so split_map_exprs can emit without a
    compile context (positions are only needed at dispatch time)."""

    def position(self, ref):  # pragma: no cover — never dispatched
        return 0


# ---------------------------------------------------------------------------
# warmup hook
# ---------------------------------------------------------------------------

def warm_registered(max_bucket: int | None = None) -> list[tuple]:
    """Walk every live fused program's bucket ladder (pw.warmup)."""
    if not autojit_enabled():
        return []
    out: list[tuple] = []
    for prog in list(_REGISTRY):
        out.extend(prog.warm(max_bucket))
    return out
