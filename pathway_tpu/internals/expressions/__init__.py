from pathway_tpu.internals.expressions.date_time import DateTimeNamespace
from pathway_tpu.internals.expressions.numerical import NumericalNamespace
from pathway_tpu.internals.expressions.string import StringNamespace

__all__ = ["DateTimeNamespace", "NumericalNamespace", "StringNamespace"]
