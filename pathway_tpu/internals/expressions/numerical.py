"""`.num` expression namespace
(reference: python/pathway/internals/expressions/numerical.py)."""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnNamespace, MethodCallExpression


class NumericalNamespace(ColumnNamespace):
    def __init__(self, expr):
        self._expr = expr

    def _m(self, name, *args, **kwargs):
        return MethodCallExpression(f"num.{name}", self._expr, *args, **kwargs)

    def abs(self):
        return self._m("abs")

    def round(self, decimals=0):
        return self._m("round", decimals)

    def fill_na(self, default_value):
        return self._m("fill_na", default_value)
