"""`.str` expression namespace
(reference: python/pathway/internals/expressions/string.py)."""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnNamespace, MethodCallExpression


class StringNamespace(ColumnNamespace):
    def __init__(self, expr):
        self._expr = expr

    def _m(self, name, *args, **kwargs):
        return MethodCallExpression(f"str.{name}", self._expr, *args, **kwargs)

    def lower(self):
        return self._m("lower")

    def upper(self):
        return self._m("upper")

    def reversed(self):
        return self._m("reversed")

    def len(self):
        return self._m("len")

    def strip(self, chars=None):
        return self._m("strip", chars)

    def lstrip(self, chars=None):
        return self._m("lstrip", chars)

    def rstrip(self, chars=None):
        return self._m("rstrip", chars)

    def startswith(self, prefix):
        return self._m("startswith", prefix)

    def endswith(self, suffix):
        return self._m("endswith", suffix)

    def swap_case(self):
        return self._m("swapcase")

    def title(self):
        return self._m("title")

    def capitalize(self):
        return self._m("capitalize")

    def casefold(self):
        return self._m("casefold")

    def count(self, sub, start=None, end=None):
        return self._m("count", sub, start, end)

    def find(self, sub, start=None, end=None):
        return self._m("find", sub, start, end)

    def rfind(self, sub, start=None, end=None):
        return self._m("rfind", sub, start, end)

    def removeprefix(self, prefix):
        return self._m("removeprefix", prefix)

    def removesuffix(self, suffix):
        return self._m("removesuffix", suffix)

    def replace(self, old, new, count=-1):
        return self._m("replace", old, new, count)

    def split(self, sep=None, maxsplit=-1):
        return self._m("split", sep, maxsplit=maxsplit)

    def rsplit(self, sep=None, maxsplit=-1):
        return self._m("rsplit", sep, maxsplit=maxsplit)

    def slice(self, start, end):
        return self._m("slice", start, end)

    def parse_int(self, optional: bool = False):
        return self._m("parse_int", optional=optional)

    def parse_float(self, optional: bool = False):
        return self._m("parse_float", optional=optional)

    def parse_bool(self, true_values=("on", "true", "yes", "1"),
                   false_values=("off", "false", "no", "0"),
                   optional: bool = False):
        return self._m("parse_bool", true_values=tuple(true_values),
                       false_values=tuple(false_values), optional=optional)
