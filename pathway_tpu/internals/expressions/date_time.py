"""`.dt` expression namespace — datetime/duration methods
(reference: python/pathway/internals/expressions/date_time.py).

Datetimes are pandas Timestamps (naive or tz-aware) host-side; durations are
pandas Timedelta. Columnar vectorization via pandas when batches are large.
"""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnNamespace, MethodCallExpression


class DateTimeNamespace(ColumnNamespace):
    def __init__(self, expr):
        self._expr = expr

    def _m(self, name, *args, **kwargs):
        return MethodCallExpression(f"dt.{name}", self._expr, *args, **kwargs)

    # components
    def nanosecond(self):
        return self._m("nanosecond")

    def microsecond(self):
        return self._m("microsecond")

    def millisecond(self):
        return self._m("millisecond")

    def second(self):
        return self._m("second")

    def minute(self):
        return self._m("minute")

    def hour(self):
        return self._m("hour")

    def day(self):
        return self._m("day")

    def month(self):
        return self._m("month")

    def year(self):
        return self._m("year")

    def weekday(self):
        return self._m("weekday")

    def timestamp(self, unit: str = "ns"):
        return self._m("timestamp", unit=unit)

    # formatting / parsing
    def strftime(self, fmt):
        return self._m("strftime", fmt)

    def strptime(self, fmt, contains_timezone: bool = False):
        return self._m("strptime", fmt, contains_timezone=contains_timezone)

    def to_utc(self, from_timezone: str):
        return self._m("to_utc", from_timezone)

    def to_naive_in_timezone(self, timezone: str):
        return self._m("to_naive_in_timezone", timezone)

    def utc_from_timestamp(self, unit: str = "ns"):
        return self._m("utc_from_timestamp", unit=unit)

    def from_timestamp(self, unit: str = "ns"):
        return self._m("from_timestamp", unit=unit)

    # rounding
    def round(self, duration):
        return self._m("round", duration)

    def floor(self, duration):
        return self._m("floor", duration)

    # duration accessors
    def nanoseconds(self):
        return self._m("nanoseconds")

    def microseconds(self):
        return self._m("microseconds")

    def milliseconds(self):
        return self._m("milliseconds")

    def seconds(self):
        return self._m("seconds")

    def minutes(self):
        return self._m("minutes")

    def hours(self):
        return self._m("hours")

    def days(self):
        return self._m("days")

    def weeks(self):
        return self._m("weeks")

    def add_duration_in_timezone(self, duration, timezone: str):
        return self._m("add_duration_in_timezone", duration, timezone)

    def subtract_duration_in_timezone(self, duration, timezone: str):
        return self._m("subtract_duration_in_timezone", duration, timezone)

    def subtract_date_time_in_timezone(self, other, timezone: str):
        return self._m("subtract_date_time_in_timezone", other, timezone)
