"""Generic expression tree transformation."""

from __future__ import annotations

from typing import Callable, Optional

from pathway_tpu.internals import expression as ex


def map_expression(expr, mapper: Callable[[ex.ColumnExpression], Optional[ex.ColumnExpression]]):
    """Bottom-up-less rewrite: mapper(e) returns a replacement or None to
    recurse into children."""
    if not isinstance(expr, ex.ColumnExpression):
        return expr
    replacement = mapper(expr)
    if replacement is not None:
        return replacement
    if not expr._deps:
        return expr
    new = object.__new__(type(expr))
    new.__dict__ = dict(expr.__dict__)
    for attr, val in list(new.__dict__.items()):
        if isinstance(val, ex.ColumnExpression):
            new.__dict__[attr] = map_expression(val, mapper)
        elif isinstance(val, tuple) and any(isinstance(v, ex.ColumnExpression) for v in val):
            new.__dict__[attr] = tuple(
                map_expression(v, mapper) if isinstance(v, ex.ColumnExpression) else v
                for v in val
            )
        elif isinstance(val, dict) and any(
            isinstance(v, ex.ColumnExpression) for v in val.values()
        ):
            new.__dict__[attr] = {
                k: map_expression(v, mapper) if isinstance(v, ex.ColumnExpression) else v
                for k, v in val.items()
            }
    return new


def collect(expr, pred) -> list:
    out = []

    def walk(e):
        if pred(e):
            out.append(e)
            return
        for d in e._deps:
            walk(d)

    walk(expr)
    return out
