"""Static device-path perf analysis — the PWT4xx diagnostic family.

PWT0xx/1xx fence semantics, PWT2xx threads, PWT3xx crash recovery; this
pass fences the contract the ROADMAP's MFU line lives or dies by:
**device-path discipline on the serving hot path**. A recompile zoo, a
hidden ``.item()`` sync, or a per-row dispatch loop lands silently today
and only surfaces as bench noise three PRs later — exactly the failure
modes Ragged Paged Attention's shape-bucket design and WindVE's
host/device-overlap split exist to avoid (PAPERS.md). Each is mechanical
enough for an AST pass to catch at authoring time.

Like its PWT2xx/3xx siblings it analyzes **source files** — the four
device-leg directories (``engine/``, ``ops/``, ``models/``,
``parallel/``) — parsed, never imported, over the same corpus model
(durability_check.build_corpus) and waiver machinery (``pwt-ok``
comments, ``check --list-waivers``).

====== ======================================================== =========
code   finding                                                  severity
====== ======================================================== =========
PWT401 jit dispatch with unbucketed data-dependent shape        error
PWT402 host-device sync point on a per-batch path               error
PWT403 per-row device dispatch in a loop; batched kernel exists warning
PWT404 numpy operand fed to jit with no device residency        warning
PWT405 float64/weak-type promotion reaching kernel code         error
PWT406 donated buffer read after donation                       error
PWT407 jitted serving entry point absent from warmup registry   warning
PWT408 blocking host I/O inside a device-leg function           warning
====== ======================================================== =========

**Hot paths.** Every check except PWT405/406/407 is scoped to the
*per-batch/per-tick* reachability set: methods whose names carry a hot
token (``search``, ``ingest``, ``step``, ``drain``, ``encode`` …) plus
everything they reach through ``self`` calls and same-module function
calls, minus cold-named slow paths (``__init__``, ``_grow``,
``snapshot``/``restore``, ``warmup``) and instrumentation modules
(flight recorder, request tracker, metrics exposition) — a sync inside
a post-mortem dump is the tool working, not a footgun.

**Device residency.** Locals assigned from ``jnp.*`` / jitted calls /
``device_put`` — and attrs assigned one anywhere in their class, or
named like device state (``_dev_vectors``) — are device-resident; a
sync construct only fires on a device-resident operand, so the host-side
``slots.tolist()`` bookkeeping the slab index does every batch stays
silent. PWT402 *supersedes and widens* PWT105's narrower sync list
(which missed ``.tolist()`` and ``int()``/``float()`` casts): when both
families run in one ``check --all`` invocation, PWT105 defers to this
family for any UDF defined in a file this pass scanned.

The runtime twin is the device sanitizer (engine/device_sanitizer.py,
``PATHWAY_DEVICE_SANITIZER=1``): what this pass proves about the source
— no post-warmup compile, no implicit transfer — the sanitizer asserts
about the execution, tick by tick, once ``pw.warmup()`` declares steady
state.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

from pathway_tpu.internals.static_check.concurrency_check import _waived
from pathway_tpu.internals.static_check.diagnostics import Diagnostic
from pathway_tpu.internals.static_check.durability_check import (
    _ClassInfo, _Corpus, _ModuleInfo, _self_attr, _units, _walk_unit,
    build_corpus)
from pathway_tpu.internals.trace import Trace

# -- hot-path model ----------------------------------------------------------
# name segments that seed the per-batch/per-tick reachability set
_HOT_TOKENS = {
    "search", "query", "queries", "ingest", "encode", "embed", "forward",
    "dispatch", "drain", "tick", "scatter", "establish", "score", "lookup",
    "step", "serve", "batch", "flush", "submit", "apply", "exchange",
}
# name segments that mark a unit cold even when reached from a hot one:
# construction, growth/realloc, recovery, warmup and teardown run outside
# the steady-state serving window
_COLD_TOKENS = {
    "init", "grow", "snapshot", "restore", "warmup", "warm", "reserve",
    "close", "shutdown", "stop", "rebuild", "hydrate", "recover",
}
# modules whose entire job is measurement/post-mortem — a sync there is
# the instrument working, not a hot-path footgun
_INSTRUMENTATION_STEMS = {
    "flight_recorder", "request_tracker", "http_server", "telemetry",
    "fleet_observability", "locking", "snapshot_sanitizer",
    "device_sanitizer", "qos", "threads", "supervisor",
}
# function-name fragments that mark instrumentation/debug units inside
# otherwise-hot modules
_INSTRUMENTATION_FN_RE = re.compile(
    r"metric|trace|dump|summary|beacon|post_mortem|probe|debug|repr|"
    r"status|describe|mortem")

# -- sync / residency vocabulary ---------------------------------------------
_SYNC_METHOD_ATTRS = {"item", "tolist", "numpy", "copy_to_host_async"}
_SYNC_NP_FNS = {"asarray", "array", "ascontiguousarray", "frombuffer"}
_CAST_BUILTINS = {"int", "float", "bool"}
_DEVICE_NS = {"jnp", "lax"}
_DEVICE_ATTR_RE = re.compile(r"(^|_)dev(ice)?(_|$)")
_HOST_ATTR_RE = re.compile(r"(^|_)host(_|$)")
# evidence that a function disciplines its dispatch shapes: any call whose
# name mentions bucketing/padding/power-of-two rounding
_BUCKET_EVIDENCE_RE = re.compile(
    r"bucket|pad|pow2|power_of_two|next_pow|round_up|ladder")
# PWT407: a jit definition whose name carries one of these segments is a
# serving entry point and must appear in pw.warmup's bucket registry
_SERVING_ENTRY_TOKENS = {"search", "encode", "ingest", "scatter", "score",
                         "lookup", "extent"}
# PWT408: blocking host I/O constructs
_BLOCKING_IO_ATTRS = {"fsync", "sendall", "send_bytes", "recv_bytes",
                      "flush"}
_BLOCKING_IO_RECV_RE = re.compile(r"file|fh|sock|log|handle|stream|conn")


def _name_tokens(name: str) -> set[str]:
    return {t for t in name.lower().strip("_").split("_") if t}


def _is_hot_name(name: str) -> bool:
    toks = _name_tokens(name)
    return bool(toks & _HOT_TOKENS) and not (toks & _COLD_TOKENS)


def _is_cold_name(name: str) -> bool:
    return bool(_name_tokens(name) & _COLD_TOKENS) \
        or name.startswith("__")


def _is_instrumentation(mod: _ModuleInfo, fn_name: str) -> bool:
    return mod.stem in _INSTRUMENTATION_STEMS \
        or bool(_INSTRUMENTATION_FN_RE.search(fn_name.lower()))


# -- jit inventory -----------------------------------------------------------

@dataclass(frozen=True)
class JitDef:
    """One jitted callable: a decorated def or a ``X = jax.jit(...)``
    assignment. ``donate`` holds absolute positional indices from
    ``donate_argnums`` (empty = nothing donated)."""

    name: str           # callable name at the call site (attr or local)
    file: str
    line: int
    donate: tuple[int, ...] = ()
    wrapped: str | None = None   # jax.jit(fn) target name, if a plain Name


def _donate_from_call(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


def _is_jit_ref(node: ast.expr) -> bool:
    """``jax.jit`` / bare ``jit`` reference."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return isinstance(node, ast.Attribute) and node.attr == "jit" \
        and isinstance(node.value, ast.Name) and node.value.id == "jax"


def _jit_call_info(value: ast.expr) -> tuple[bool, tuple[int, ...],
                                             str | None]:
    """(is_jit, donate_argnums, wrapped fn name) for a value expression
    ``jax.jit(f, ...)`` / ``functools.partial(jax.jit, ...)(f)`` — or the
    partial itself when used as a decorator."""
    if not isinstance(value, ast.Call):
        return False, (), None
    if _is_jit_ref(value.func):
        wrapped = value.args[0].id if value.args \
            and isinstance(value.args[0], ast.Name) else None
        return True, _donate_from_call(value), wrapped
    # functools.partial(jax.jit, donate_argnums=..., static_argnames=...)
    fn = value.func
    is_partial = (isinstance(fn, ast.Name) and fn.id == "partial") or (
        isinstance(fn, ast.Attribute) and fn.attr == "partial")
    if is_partial and value.args and _is_jit_ref(value.args[0]):
        return True, _donate_from_call(value), None
    return False, (), None


def _decorated_jit(fn: ast.FunctionDef) -> tuple[bool, tuple[int, ...]]:
    for dec in fn.decorator_list:
        if _is_jit_ref(dec):
            return True, ()
        is_jit, donate, _w = _jit_call_info(dec)
        if is_jit:
            return True, donate
    return False, ()


class _JitInventory:
    """Every jitted callable in a module, resolvable at call sites:
    module-level names, ``self.attr`` assignments per class, and
    unit-local names (including nested decorated defs)."""

    def __init__(self, mod: _ModuleInfo):
        self.module: dict[str, JitDef] = {}
        self.by_class: dict[tuple[str, str], JitDef] = {}
        for name, fn in mod.functions.items():
            is_jit, donate = _decorated_jit(fn)
            if is_jit:
                self.module[name] = JitDef(name, mod.path, fn.lineno,
                                           donate)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                is_jit, donate, wrapped = _jit_call_info(node.value)
                if is_jit:
                    name = node.targets[0].id
                    self.module[name] = JitDef(name, mod.path,
                                               node.lineno, donate,
                                               wrapped)
        for cls in mod.classes.values():
            for m in cls.methods.values():
                for sub in _walk_unit(m):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and _self_attr(sub.targets[0]):
                        is_jit, donate, wrapped = _jit_call_info(sub.value)
                        if is_jit:
                            attr = _self_attr(sub.targets[0])
                            self.by_class[(cls.name, attr)] = JitDef(
                                attr, mod.path, sub.lineno, donate,
                                wrapped)

    def local_jits(self, fn: ast.AST) -> dict[str, JitDef]:
        out: dict[str, JitDef] = {}
        for node in _walk_unit(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                is_jit, donate, wrapped = _jit_call_info(node.value)
                if is_jit:
                    name = node.targets[0].id
                    out[name] = JitDef(name, "", node.lineno, donate,
                                       wrapped)
            elif isinstance(node, ast.FunctionDef) and node is not fn:
                is_jit, donate = _decorated_jit(node)
                if is_jit:
                    out[node.name] = JitDef(node.name, "", node.lineno,
                                            donate)
        return out

    def resolve_call(self, call: ast.Call, cls: _ClassInfo | None,
                     local: dict[str, JitDef]) -> JitDef | None:
        fn = call.func
        if isinstance(fn, ast.Name):
            return local.get(fn.id) or self.module.get(fn.id)
        if isinstance(fn, ast.Attribute) and cls is not None:
            attr = _self_attr(fn)
            if attr is not None:
                return self.by_class.get((cls.name, attr))
        return None

    def all_defs(self):
        yield from self.module.values()
        yield from self.by_class.values()


# -- hot-path reachability ---------------------------------------------------

def _self_calls(fn: ast.AST) -> set[str]:
    out = set()
    for node in _walk_unit(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and (a := _self_attr(node.func)) is not None:
            out.add(a)
    return out


def _name_calls(fn: ast.AST) -> set[str]:
    return {node.func.id for node in _walk_unit(fn)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)}


def hot_units(mod: _ModuleInfo) -> set[tuple[str | None, str]]:
    """``(class_name | None, fn_name)`` units on the per-batch path:
    hot-named seeds closed over same-class ``self`` calls and same-module
    function calls, minus cold-named units."""
    hot: set[tuple[str | None, str]] = set()
    for cls, fn in _units(mod):
        if _is_hot_name(fn.name):
            hot.add((cls.name if cls else None, fn.name))
    changed = True
    while changed:
        changed = False
        for cls, fn in _units(mod):
            key = (cls.name if cls else None, fn.name)
            if key not in hot:
                continue
            callees: set[tuple[str | None, str]] = set()
            if cls is not None:
                callees |= {(cls.name, m) for m in _self_calls(fn)
                            if m in cls.methods}
            callees |= {(None, m) for m in _name_calls(fn)
                        if m in mod.functions}
            for ck in callees:
                if ck not in hot and not _is_cold_name(ck[1]):
                    hot.add(ck)
                    changed = True
    return hot


# -- device / host residency -------------------------------------------------

def _device_attrs(cls: _ClassInfo, jits: _JitInventory) -> set[str]:
    """Attrs device-resident anywhere in the class: named like device
    state, or assigned from ``jnp.*`` / ``device_put`` / a jitted call."""
    out = {a for a in _class_attr_names(cls) if _DEVICE_ATTR_RE.search(a)}
    for m in cls.methods.values():
        local = jits.local_jits(m)
        for node in _walk_unit(m):
            if not isinstance(node, ast.Assign):
                continue
            if not _is_device_producer(node.value, cls, jits, local,
                                       set(), set()):
                continue
            for tgt in node.targets:
                targets = tgt.elts if isinstance(
                    tgt, (ast.Tuple, ast.List)) else [tgt]
                for t in targets:
                    if (a := _self_attr(t)) is not None:
                        out.add(a)
    return out


def _class_attr_names(cls: _ClassInfo) -> set[str]:
    out = set()
    for m in cls.methods.values():
        for node in _walk_unit(m):
            if (a := _self_attr(node)) is not None:
                out.add(a)
    return out


def _is_device_producer(value: ast.expr, cls, jits, local,
                        device_names: set[str],
                        device_attrs: set[str]) -> bool:
    """Does evaluating ``value`` yield a device-resident array?"""
    if isinstance(value, ast.Call):
        fn = value.func
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) \
                    and fn.value.id in _DEVICE_NS:
                return True
            if fn.attr in ("device_put", "device_put_sharded"):
                return True
            # method chains on device values (x.at[...].set(...), x.sum())
            if _mentions_device(fn.value, device_names, device_attrs):
                return True
        if jits.resolve_call(value, cls, local) is not None:
            return True
        return False
    if isinstance(value, (ast.Subscript, ast.Attribute, ast.BinOp,
                          ast.UnaryOp)):
        return _mentions_device(value, device_names, device_attrs)
    if isinstance(value, ast.Name):
        return value.id in device_names
    return False


def _mentions_device(expr: ast.expr, device_names: set[str],
                     device_attrs: set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in device_names:
            return True
        if (a := _self_attr(n)) is not None and (
                a in device_attrs or _DEVICE_ATTR_RE.search(a)):
            return True
    return False


def _unit_residency(fn: ast.AST, cls, jits, local, device_attrs
                    ) -> tuple[set[str], set[str]]:
    """(device-resident local names, host-resident local names) by a
    forward dataflow sweep over the unit's assignments."""
    device: set[str] = set()
    host: set[str] = set()
    nodes = sorted(
        (n for n in _walk_unit(fn) if isinstance(n, ast.Assign)),
        key=lambda n: n.lineno)
    for node in nodes:
        is_dev = _is_device_producer(node.value, cls, jits, local,
                                     device, device_attrs)
        is_host = _is_host_producer(node.value, host)
        for tgt in node.targets:
            targets = tgt.elts if isinstance(
                tgt, (ast.Tuple, ast.List)) else [tgt]
            for t in targets:
                if isinstance(t, ast.Name):
                    if is_dev:
                        device.add(t.id)
                        host.discard(t.id)
                    elif is_host:
                        host.add(t.id)
                        device.discard(t.id)
    return device, host


def _is_host_producer(value: ast.expr, host_names: set[str]) -> bool:
    if isinstance(value, ast.Call):
        fn = value.func
        if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name) and fn.value.id in ("np", "numpy"):
            return True
    if isinstance(value, ast.Name):
        return value.id in host_names
    if isinstance(value, (ast.Subscript, ast.BinOp)):
        return any(isinstance(n, ast.Name) and n.id in host_names
                   for n in ast.walk(value)) \
            or any((a := _self_attr(n)) is not None
                   and _HOST_ATTR_RE.search(a)
                   for n in ast.walk(value))
    return False


# -- warmup registry (PWT407) ------------------------------------------------

def load_warmup_registry(paths) -> set[str] | None:
    """The ``WARMED_ENTRY_POINTS`` name set parsed (never imported) from
    the package's warmup.py, located relative to the scanned trees; None
    when no warmup.py is reachable — PWT407 then stays silent."""
    import pathlib

    seen: set[pathlib.Path] = set()
    for p in paths:
        d = pathlib.Path(p).resolve()
        if d.is_file():
            d = d.parent
        for candidate in (d, *d.parents[:3]):
            if candidate in seen:
                continue
            seen.add(candidate)
            w = candidate / "warmup.py"
            if w.is_file():
                reg = _parse_registry(w)
                if reg is not None:
                    return reg
    return None


def _parse_registry(path) -> set[str] | None:
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not any(isinstance(t, ast.Name)
                   and t.id == "WARMED_ENTRY_POINTS" for t in targets):
            continue
        if isinstance(value, ast.Call) and value.args:
            value = value.args[0]  # frozenset({...})
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            return {e.value for e in value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return None


# ---------------------------------------------------------------------------
# checker
# ---------------------------------------------------------------------------

class PerfChecker:
    """Runs every PWT4xx check over a parsed corpus."""

    def __init__(self, corpus: _Corpus,
                 warmup_registry: set[str] | None = None):
        self.corpus = corpus
        self.registry = warmup_registry
        self.diagnostics: list[Diagnostic] = []
        self._sources = {m.path: m.source_lines for m in corpus.modules}

    def _report(self, code: str, message: str, file: str, line: int,
                function: str = "") -> None:
        lines = self._sources.get(file, [])
        if _waived(lines, line, code):
            return
        src = lines[line - 1].strip() if 0 < line <= len(lines) else ""
        self.diagnostics.append(Diagnostic(
            code=code, message=message,
            trace=Trace(file, line, function, src)))

    def run(self) -> list[Diagnostic]:
        for path, err in self.corpus.parse_failures:
            self.diagnostics.append(Diagnostic(
                code="PWT000", message=f"cannot analyze {path}: {err}"))
        for mod in self.corpus.modules:
            self._check_module(mod)
        return self.diagnostics

    # -- per-module driver ---------------------------------------------------
    def _check_module(self, mod: _ModuleInfo) -> None:
        jits = _JitInventory(mod)
        hot = hot_units(mod)
        module_has_batched = self._module_has_batched_kernel(mod)
        dev_attrs_by_class = {
            cls.name: _device_attrs(cls, jits)
            for cls in mod.classes.values()}
        self._check_entry_registry(mod, jits)              # PWT407
        for cls, fn in _units(mod):
            owner = f"{cls.name}.{fn.name}" if cls else fn.name
            local = jits.local_jits(fn)
            self._check_donation(mod, cls, fn, owner, jits, local)  # 406
            self._check_f64(mod, cls, fn, owner)                    # 405
            key = (cls.name if cls else None, fn.name)
            if key not in hot or _is_instrumentation(mod, fn.name):
                continue
            dev_attrs = dev_attrs_by_class.get(cls.name, set()) \
                if cls else set()
            device, host = _unit_residency(fn, cls, jits, local,
                                           dev_attrs)
            self._check_syncs(mod, cls, fn, owner, device, dev_attrs)  # 402
            flagged_401 = self._check_unbucketed(
                mod, cls, fn, owner, jits, local, host)                # 401
            self._check_loop_dispatch(mod, cls, fn, owner, jits,
                                      local, module_has_batched)       # 403
            self._check_host_operands(mod, cls, fn, owner, jits,
                                      local, host, flagged_401)        # 404
            self._check_blocking_io(mod, cls, fn, owner, jits, local)  # 408

    @staticmethod
    def _module_has_batched_kernel(mod: _ModuleInfo) -> bool:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "vmap":
                return True
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and "batch" in _name_tokens(node.name):
                return True
        return False

    # -- PWT402 --------------------------------------------------------------
    def _check_syncs(self, mod, cls, fn, owner, device: set[str],
                     dev_attrs: set[str]) -> None:
        def is_dev(expr: ast.expr) -> bool:
            return _mentions_device(expr, device, dev_attrs)

        for node in _walk_unit(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in _SYNC_METHOD_ATTRS and is_dev(f.value):
                    self._report(
                        "PWT402",
                        f"{owner} calls .{f.attr}() on device value "
                        f"{ast.unparse(f.value)} on a per-batch path: "
                        f"every batch stalls the dispatch queue for a "
                        f"device→host round-trip — keep the value on "
                        f"device (jnp ops) or move the read to an "
                        f"output boundary",
                        mod.path, node.lineno, owner)
                elif f.attr == "block_until_ready":
                    self._report(
                        "PWT402",
                        f"{owner} blocks on device completion "
                        f"(.block_until_ready()) on a per-batch path "
                        f"outside instrumentation code: the host idles "
                        f"for the full device leg instead of "
                        f"overlapping — let the consumer's read "
                        f"synchronize, or move the barrier to the "
                        f"output boundary",
                        mod.path, node.lineno, owner)
                elif isinstance(f.value, ast.Name) \
                        and f.value.id in ("np", "numpy") \
                        and f.attr in _SYNC_NP_FNS \
                        and node.args and is_dev(node.args[0]):
                    self._report(
                        "PWT402",
                        f"{owner} materializes device value "
                        f"{ast.unparse(node.args[0])} on the host "
                        f"(np.{f.attr}) on a per-batch path: a full "
                        f"device→host transfer every batch — keep the "
                        f"compute in jnp, or hoist the read out of the "
                        f"hot path",
                        mod.path, node.lineno, owner)
            elif isinstance(f, ast.Name):
                if f.id == "block_until_ready":
                    self._report(
                        "PWT402",
                        f"{owner} blocks on device completion "
                        f"(block_until_ready) on a per-batch path "
                        f"outside instrumentation code",
                        mod.path, node.lineno, owner)
                elif f.id in _CAST_BUILTINS and node.args \
                        and is_dev(node.args[0]):
                    self._report(
                        "PWT402",
                        f"{owner} casts device value "
                        f"{ast.unparse(node.args[0])} to a Python "
                        f"{f.id} on a per-batch path: the implicit "
                        f".item() blocks until the device flushes — "
                        f"PWT105's old list missed exactly this form; "
                        f"keep it a 0-d array or read at the output "
                        f"boundary",
                        mod.path, node.lineno, owner)

    # -- PWT401 --------------------------------------------------------------
    def _check_unbucketed(self, mod, cls, fn, owner, jits, local,
                          host: set[str]) -> set[int]:
        """Flag jit dispatches whose operand's leading dim is raw data
        length with no bucketing evidence in the unit. Returns flagged
        call linenos (PWT404 skips those sites)."""
        has_bucketing = any(
            isinstance(n, ast.Call) and _BUCKET_EVIDENCE_RE.search(
                n.func.attr if isinstance(n.func, ast.Attribute)
                else n.func.id if isinstance(n.func, ast.Name) else "")
            for n in _walk_unit(fn))
        flagged: set[int] = set()
        if has_bucketing:
            return flagged
        params = _param_names(fn)
        ragged = _data_dependent_names(fn, params)
        for node in _walk_unit(fn):
            if not isinstance(node, ast.Call) \
                    or jits.resolve_call(node, cls, local) is None:
                continue
            for arg in node.args:
                bad = None
                if isinstance(arg, ast.Name) and arg.id in ragged:
                    bad = arg.id
                elif _conversion_of_param(arg, params | ragged):
                    bad = ast.unparse(arg)
                if bad is None:
                    continue
                self._report(
                    "PWT401",
                    f"{owner} dispatches jitted callable "
                    f"{ast.unparse(node.func)} with data-dependent "
                    f"shape ({bad}): every distinct batch length "
                    f"compiles a fresh executable — bucket the leading "
                    f"dim (pad to a power-of-two width) before the "
                    f"dispatch site, as the encoder's bucket ladder "
                    f"does",
                    mod.path, node.lineno, owner)
                flagged.add(node.lineno)
                break
        return flagged

    # -- PWT403 --------------------------------------------------------------
    def _check_loop_dispatch(self, mod, cls, fn, owner, jits, local,
                             module_has_batched: bool) -> None:
        if not module_has_batched:
            return
        for node in _walk_unit(fn):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and jits.resolve_call(sub, cls, local) \
                        is not None:
                    self._report(
                        "PWT403",
                        f"{owner} dispatches jitted callable "
                        f"{ast.unparse(sub.func)} per row inside a "
                        f"Python loop while this module has a batched/"
                        f"vmapped kernel: ~100 µs dispatch overhead "
                        f"per row instead of one amortized launch — "
                        f"stack the rows and dispatch once",
                        mod.path, node.lineno, owner)
                    break

    # -- PWT404 --------------------------------------------------------------
    def _check_host_operands(self, mod, cls, fn, owner, jits, local,
                             host: set[str], flagged_401: set[int]
                             ) -> None:
        has_device_put = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "device_put"
            for n in _walk_unit(fn))
        if has_device_put:
            return
        for node in _walk_unit(fn):
            if not isinstance(node, ast.Call) \
                    or node.lineno in flagged_401 \
                    or jits.resolve_call(node, cls, local) is None:
                continue
            for arg in node.args:
                hosty = None
                if isinstance(arg, ast.Name) and arg.id in host:
                    hosty = arg.id
                elif (a := _self_attr(arg)) is not None \
                        and _HOST_ATTR_RE.search(a):
                    hosty = f"self.{a}"
                if hosty is None:
                    continue
                self._report(
                    "PWT404",
                    f"{owner} feeds numpy operand {hosty} to jitted "
                    f"callable {ast.unparse(node.func)}: an implicit "
                    f"host→device transfer every tick — device_put it "
                    f"once upstream (or keep it device-resident) so "
                    f"steady-state dispatches reuse the on-device "
                    f"buffer",
                    mod.path, node.lineno, owner)
                break

    # -- PWT405 --------------------------------------------------------------
    def _check_f64(self, mod, cls, fn, owner) -> None:
        for node in _walk_unit(fn):
            bad = None
            if isinstance(node, ast.Attribute) \
                    and node.attr == "float64" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in ("np", "numpy", "jnp"):
                bad = f"{node.value.id}.float64"
            elif isinstance(node, ast.Constant) \
                    and node.value == "float64":
                bad = "'float64'"
            elif isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "float":
                bad = "dtype=float (Python float is float64)"
            if bad is None:
                continue
            if not self._near_device_code(fn):
                continue
            self._report(
                "PWT405",
                f"{owner} lets {bad} reach kernel code: TPUs emulate "
                f"f64 at ~1/10 throughput and one stray dtype "
                f"contaminates every downstream op through promotion — "
                f"pin float32 (or the slab dtype) explicitly",
                mod.path, node.lineno, owner)

    @staticmethod
    def _near_device_code(fn: ast.AST) -> bool:
        """float64 only matters where arrays can reach a device op: the
        unit touches jnp/jax/lax or numpy array constructors."""
        for n in _walk_unit(fn):
            if isinstance(n, ast.Name) and n.id in ("jnp", "jax", "lax"):
                return True
            if isinstance(n, ast.Attribute) and isinstance(
                    n.value, ast.Name) and n.value.id in ("np", "numpy"):
                return True
        return False

    # -- PWT406 --------------------------------------------------------------
    def _check_donation(self, mod, cls, fn, owner, jits, local) -> None:
        stmts = [n for n in _walk_unit(fn)
                 if isinstance(n, (ast.Assign, ast.Expr, ast.AugAssign,
                                   ast.Return, ast.If, ast.For))]
        for node in _walk_unit(fn):
            if not isinstance(node, ast.Call):
                continue
            jd = jits.resolve_call(node, cls, local)
            if jd is None or not jd.donate:
                continue
            donated: set[str] = set()
            for idx in jd.donate:
                if idx < len(node.args):
                    arg = node.args[idx]
                    if isinstance(arg, ast.Name):
                        donated.add(arg.id)
                    elif (a := _self_attr(arg)) is not None:
                        donated.add(f"self.{a}")
            if not donated:
                continue
            rebound = self._assignment_targets_for_call(fn, node)
            live = donated - rebound
            if not live:
                continue
            for read_line, name in self._reads_after(
                    fn, node.lineno, live):
                self._report(
                    "PWT406",
                    f"{owner} reads {name} after donating it to "
                    f"{ast.unparse(node.func)} (donate_argnums) at "
                    f"line {node.lineno}: XLA may already have reused "
                    f"the buffer — rebind the result over the donated "
                    f"name, or drop the read",
                    mod.path, read_line, owner)
                break  # one report per donation site

    @staticmethod
    def _assignment_targets_for_call(fn, call: ast.Call) -> set[str]:
        """Names/attrs rebound from the call's result (``x, y = f(...)``)."""
        out: set[str] = set()
        for node in _walk_unit(fn):
            if isinstance(node, ast.Assign) and node.value is call:
                for tgt in node.targets:
                    targets = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
                        elif (a := _self_attr(t)) is not None:
                            out.add(f"self.{a}")
        return out

    @staticmethod
    def _reads_after(fn, call_line: int, names: set[str]):
        """(lineno, name) for reads of ``names`` after the call, skipping
        names rebound in between."""
        rebinds: dict[str, int] = {}
        for node in _walk_unit(fn):
            if isinstance(node, ast.Assign) and node.lineno > call_line:
                for tgt in node.targets:
                    targets = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for t in targets:
                        key = t.id if isinstance(t, ast.Name) else \
                            (f"self.{_self_attr(t)}"
                             if _self_attr(t) else None)
                        if key in names:
                            rebinds[key] = min(
                                rebinds.get(key, node.lineno),
                                node.lineno)
        reads = []
        for node in _walk_unit(fn):
            if node is None or getattr(node, "lineno", 0) <= call_line:
                continue
            key = None
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in names:
                key = node.id
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and (a := _self_attr(node)) is not None \
                    and f"self.{a}" in names:
                key = f"self.{a}"
            if key is None:
                continue
            if key in rebinds and node.lineno >= rebinds[key]:
                continue
            reads.append((node.lineno, key))
        return sorted(reads)

    # -- PWT407 --------------------------------------------------------------
    def _check_entry_registry(self, mod: _ModuleInfo,
                              jits: _JitInventory) -> None:
        if self.registry is None:
            return
        for jd in jits.all_defs():
            names = {jd.name}
            if jd.wrapped:
                names.add(jd.wrapped)
            if not any(_name_tokens(n) & _SERVING_ENTRY_TOKENS
                       for n in names):
                continue
            if names & self.registry:
                continue
            self._report(
                "PWT407",
                f"jitted serving entry point {jd.name!r} is absent "
                f"from pw.warmup's bucket registry "
                f"(warmup.WARMED_ENTRY_POINTS): its cold compile lands "
                f"inside the first real query instead of the warmup "
                f"window — walk it in warmup() and register the name",
                jd.file or mod.path, jd.line, jd.name)

    # -- PWT408 --------------------------------------------------------------
    def _check_blocking_io(self, mod, cls, fn, owner, jits, local
                           ) -> None:
        dispatches = any(
            isinstance(n, ast.Call) and (
                jits.resolve_call(n, cls, local) is not None
                or (isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in _DEVICE_NS))
            for n in _walk_unit(fn))
        if not dispatches:
            return
        for node in _walk_unit(fn):
            if not isinstance(node, ast.Call):
                continue
            what = None
            f = node.func
            if isinstance(f, ast.Name) and f.id in ("open", "print"):
                what = f"{f.id}()"
            elif isinstance(f, ast.Attribute):
                recv = ast.unparse(f.value).lower()
                if f.attr == "fsync" and recv == "os":
                    what = "os.fsync"
                elif f.attr in ("sendall", "send_bytes", "recv_bytes"):
                    what = f".{f.attr}()"
                elif f.attr == "flush" \
                        and _BLOCKING_IO_RECV_RE.search(recv):
                    what = f"{recv}.flush()"
                elif f.attr == "sleep" and recv == "time":
                    what = "time.sleep"
            if what is None:
                continue
            self._report(
                "PWT408",
                f"{owner} performs blocking host I/O ({what}) inside a "
                f"device-leg function: the dispatch pipeline stalls for "
                f"host I/O time every batch — queue the I/O to a "
                f"worker thread or move it off the device leg",
                mod.path, node.lineno, owner)


def _param_names(fn: ast.AST) -> set[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    names = {a.arg for a in (*args.posonlyargs, *args.args,
                             *args.kwonlyargs)}
    names.discard("self")
    return names


def _data_dependent_names(fn: ast.AST, params: set[str]) -> set[str]:
    """Locals whose leading dim is raw data length: array constructors
    shaped by ``len(<param>)``."""
    out: set[str] = set()
    for node in _walk_unit(fn):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        uses_len = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            and n.func.id == "len" and n.args
            and isinstance(n.args[0], ast.Name)
            and n.args[0].id in params
            for n in ast.walk(node.value))
        if not uses_len:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


def _conversion_of_param(arg: ast.expr, data_names: set[str]) -> bool:
    """``jnp.asarray(p)`` / ``jnp.stack(p)`` / ``np.asarray(p)`` where
    ``p`` carries raw data length."""
    if not isinstance(arg, ast.Call) \
            or not isinstance(arg.func, ast.Attribute):
        return False
    f = arg.func
    if not (isinstance(f.value, ast.Name)
            and f.value.id in ("jnp", "np", "numpy")
            and f.attr in ("asarray", "array", "stack")):
        return False
    return any(isinstance(n, ast.Name) and n.id in data_names
               for n in ast.walk(arg.args[0])) if arg.args else False


# ---------------------------------------------------------------------------
# front doors
# ---------------------------------------------------------------------------

def check_perf(paths, *, corpus: _Corpus | None = None,
               warmup_registry: set[str] | None = None
               ) -> list[Diagnostic]:
    """Run the PWT4xx family over ``paths`` (files or directories of
    Python source). Nothing is imported or executed. ``warmup_registry``
    overrides the ``WARMED_ENTRY_POINTS`` set normally parsed from the
    package's warmup.py (tests; None = autodiscover, and PWT407 stays
    silent when no registry is reachable)."""
    if warmup_registry is None:
        warmup_registry = load_warmup_registry(paths)
    return PerfChecker(corpus or build_corpus(paths),
                       warmup_registry=warmup_registry).run()


def perf_inventory(paths, *, corpus: _Corpus | None = None) -> dict:
    """The device-path inventory as plain data — every jitted callable
    (with its donation signature), the hot-unit reachability set per
    module, and the warmup registry — for ``check --perf --json``
    artifacts."""
    corpus = corpus or build_corpus(paths)
    jit_defs = []
    hot: list[str] = []
    for mod in corpus.modules:
        jits = _JitInventory(mod)
        for jd in jits.all_defs():
            jit_defs.append({
                "name": jd.name, "file": jd.file or mod.path,
                "line": jd.line, "donate_argnums": list(jd.donate),
            })
        for cls_name, fn_name in sorted(
                hot_units(mod), key=lambda k: (k[0] or "", k[1])):
            hot.append(f"{mod.stem}:{cls_name + '.' if cls_name else ''}"
                       f"{fn_name}")
    registry = load_warmup_registry(paths)
    return {
        "jit_entry_points": sorted(jit_defs, key=lambda d: (d["file"],
                                                            d["line"])),
        "hot_units": sorted(hot),
        "warmup_registry": sorted(registry) if registry else [],
    }
