"""Static sharding/placement analysis — the PWT1xx diagnostic family.

PR 1's analyzer validates the logical plan (dtypes, dead dataflow,
formats); this pass validates the layer where pod-scale outages actually
live: mesh/topology misconfiguration, slab shapes that silently replicate
or pad over the ``data`` axis, shard_map specs inconsistent with their
operands, index slabs placed on a different topology than the pipeline,
and Python UDFs that force host round-trips on per-batch paths.

Three check layers, mirroring the runtime stack:

1. **mesh/topology** — the analysis mesh (``--tpu-mesh data×model`` on the
   CLI, ``mesh=`` on :func:`pw.static_check`, ``PATHWAY_STATIC_CHECK_MESH``
   for ``pw.run``) is validated against env-var overrides (PWT101); slab
   reservations and kernel operand shapes are checked for data-axis
   divisibility (PWT102) using the SAME layout helpers the kernels size
   themselves with (parallel/sharded_knn.py ``slab_cap_per_shard`` /
   ``search_operand_layout``); shard_map in/out specs are checked against
   operand ranks and mesh axes (PWT103).
2. **placement/comms** — external-index slabs pinned to a mesh other than
   the analysis mesh flag the implicit per-batch cross-topology gather
   (PWT104); UDFs containing host-device sync points — ``.item()``,
   ``np.asarray`` on device values, Python-loop reductions — on per-batch
   paths flag PWT105.
3. **UDF traceability** — an AST (bytecode fallback) classifier tags every
   sync ``pw.udf`` as jit-traceable / vmappable / host-only. Host-only UDFs
   on a streaming hot path flag PWT109; traceable ones dispatched row-by-row
   flag PWT110. The classification is recorded on the expression
   (``expr._shard_class``) and in ``Analyzer.udf_classifications``; the
   auto-jit tier (internals/autojit.py) consumes it at compile time to fuse
   the traceable/vmappable classes into vectorized device dispatches, so
   with auto-jit enabled PWT110 is informational ("will be auto-jitted")
   rather than a manual-rewrite prompt.

Everything here is metadata-only: no device is touched, jax is never
imported — a hypothetical topology can be analyzed on a laptop that owns
no hardware.
"""

from __future__ import annotations

import ast
import inspect
import os
import textwrap
from dataclasses import dataclass

from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.static_check.diagnostics import Diagnostic
from pathway_tpu.internals.trace import Trace

# axis names mirror parallel/mesh.py (not imported: that module pulls jax
# at mesh-construction time; the checker must stay importable without it)
DATA_AXIS = "data"
MODEL_AXIS = "model"


# ---------------------------------------------------------------------------
# mesh topology
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MeshSpec:
    """A (data, model) topology to analyze against — real or hypothetical."""

    data: int
    model: int = 1

    @property
    def n_devices(self) -> int:
        return self.data * self.model

    def __str__(self) -> str:
        return f"{self.data}x{self.model}"


def parse_mesh_spec(value) -> MeshSpec | None:
    """Coerce any mesh-ish value to a :class:`MeshSpec` (or None).

    Accepts ``None``, a MeshSpec, a ``parallel.mesh.MeshConfig``, a
    ``jax.sharding.Mesh`` (its shape dict is read, jax is not imported),
    or a string ``"4x2"`` / ``"4×2"`` / ``"8"`` (model defaults to 1).
    """
    if value is None:
        return None
    if isinstance(value, MeshSpec):
        return value
    if isinstance(value, str):
        text = value.strip().lower().replace("×", "x").replace("*", "x")
        parts = [p for p in text.split("x") if p]
        try:
            dims = [int(p) for p in parts]
        except ValueError:
            dims = []
        if len(dims) == 1:
            dims.append(1)
        if len(dims) != 2 or any(d < 1 for d in dims):
            raise ValueError(
                f"cannot parse mesh spec {value!r}: expected 'DATAxMODEL' "
                "with positive integers, e.g. '4x2'")
        return MeshSpec(data=dims[0], model=dims[1])
    shape = getattr(value, "shape", None)
    if shape is not None and hasattr(shape, "get"):  # jax Mesh / Mapping
        return MeshSpec(data=int(shape.get(DATA_AXIS, 1)),
                        model=int(shape.get(MODEL_AXIS, 1)))
    data = getattr(value, "data", None)
    model = getattr(value, "model", None)
    if isinstance(data, int):  # parallel.mesh.MeshConfig (duck-typed)
        return MeshSpec(data=data, model=model if isinstance(model, int) else 1)
    raise ValueError(f"cannot interpret {value!r} as a mesh spec")


def check_mesh_fits(data: int, model: int, n_devices: int, *,
                    source: str = "mesh") -> list[Diagnostic]:
    """PWT101: axis sizes must fit — and tile — the device count.

    Delegates to ``MeshConfig.validate`` (parallel/mesh.py), the same rule
    ``MeshConfig.from_env`` enforces eagerly at runtime — a topology the
    checker flags is exactly one the runtime would refuse to build.
    """
    from pathway_tpu.parallel.mesh import MeshConfig

    return [
        Diagnostic(
            "PWT101",
            f"{source}: {problem} — fix: choose axis sizes whose product "
            f"divides {n_devices}")
        for problem in MeshConfig(data=data, model=model).validate(n_devices)
    ]


def check_sharded_dim(size: int | None, axis_size: int, *,
                      axis: str = DATA_AXIS,
                      what: str = "sharded operand") -> list[Diagnostic]:
    """PWT102: a dim sharded over ``axis`` must be divisible by its size."""
    if size is None or axis_size <= 1:
        return []
    if size % axis_size != 0:
        per = -(-size // axis_size)  # ceil
        pad = per * axis_size - size
        return [Diagnostic(
            "PWT102",
            f"{what}: leading dimension {size} is not divisible by the "
            f"{axis!r} axis size {axis_size} — each shard pads to {per} "
            f"rows ({pad} rows of silent replication/padding, skewed "
            f"shards) — fix: make it a multiple of {axis_size}")]
    return []


def check_shard_specs(mesh_axes: dict, in_specs, in_ranks,
                      out_specs=(), out_ranks=()) -> list[Diagnostic]:
    """PWT103: shard_map specs must match operand ranks and mesh axes.

    ``in_specs``/``out_specs`` are symbolic: each spec is a tuple with one
    entry per leading operand dim — ``None`` (replicated) or an axis name
    (see ``parallel.sharded_knn.search_operand_layout``). A real
    ``jax.sharding.PartitionSpec`` also works (it iterates the same way).
    """
    out: list[Diagnostic] = []

    def _check(kind, specs, ranks):
        for i, (spec, rank) in enumerate(zip(specs, ranks)):
            entries = tuple(spec)
            if len(entries) > rank:
                out.append(Diagnostic(
                    "PWT103",
                    f"{kind}[{i}]: spec {entries!r} names "
                    f"{len(entries)} dims but the operand has rank {rank} — "
                    f"fix: drop spec entries or pass a higher-rank operand"))
            for entry in entries:
                axes = entry if isinstance(entry, tuple) else (entry,)
                for a in axes:
                    if a is None:
                        continue
                    if a not in mesh_axes:
                        out.append(Diagnostic(
                            "PWT103",
                            f"{kind}[{i}]: spec names mesh axis {a!r} but "
                            f"the mesh only has axes "
                            f"{sorted(mesh_axes)} — fix: use one of those "
                            f"or add the axis to the mesh"))

    _check("in_specs", in_specs, in_ranks)
    _check("out_specs", out_specs, out_ranks)
    return out


def check_attention_sharding(shape, mesh: MeshSpec | str, *,
                             scheme: str = "ring",
                             axis: str = DATA_AXIS) -> list[Diagnostic]:
    """Shape pre-check for the sequence-parallel attention kernels.

    ``shape`` is the global (batch, seq, heads, head_dim). Ring attention
    shards ``seq`` over the axis (PWT102 on non-divisibility); Ulysses
    additionally re-shards to head-parallel and needs
    ``heads % axis_size == 0`` (PWT106).
    """
    spec = parse_mesh_spec(mesh)
    n = spec.data if axis == DATA_AXIS else spec.model
    _b, s, h, _d = shape
    out = check_sharded_dim(
        s, n, axis=axis,
        what=f"{scheme} attention sequence (shape {tuple(shape)})")
    if scheme == "ulysses" and n > 1 and h % n != 0:
        out.append(Diagnostic(
            "PWT106",
            f"ulysses attention: {h} heads not divisible by the {axis!r} "
            f"axis size {n} — the all_to_all re-shard to head-parallel "
            f"cannot split the head dim — fix: pad heads to a multiple of "
            f"{n} or use ring attention"))
    return out


def check_pipeline_layout(n_layers: int, n_stages: int) -> list[Diagnostic]:
    """PWT102 for the GPipe layer stack (parallel/pipeline.py): the stacked
    layer axis is sharded over the pipe axis."""
    return check_sharded_dim(
        n_layers, n_stages, axis="pipe",
        what=f"pipeline layer stack ({n_layers} layers over "
             f"{n_stages} stages)")


# ---------------------------------------------------------------------------
# UDF classifier: jit-traceable / vmappable / host-only
# ---------------------------------------------------------------------------

_KIND_ORDER = {"traceable": 0, "vmappable": 1, "host": 2}

# module aliases whose attribute calls trace into XLA
_NUMERIC_MODULES = {"np", "numpy", "jnp", "jax", "lax", "math"}
# math.* works per-scalar: vmap-able after a jnp rewrite, not jit-batchable
_SCALAR_MODULES = {"math"}
# attribute calls that force a device→host copy / synchronization
_SYNC_ATTRS = {"item", "tolist", "numpy", "block_until_ready",
               "copy_to_host_async"}
# numpy-namespace calls that materialize a host ndarray from their operand
_SYNC_NP_FNS = {"asarray", "array", "ascontiguousarray", "frombuffer"}
# per-scalar builtins a vmap rewrite can express
_VMAP_BUILTINS = {"abs", "min", "max", "round", "float", "int", "bool",
                  "divmod", "pow"}
# builtins that pin execution to the Python interpreter
_HOST_BUILTINS = {"open", "print", "input", "eval", "exec", "compile",
                  "len", "sum", "sorted", "list", "dict", "set", "tuple",
                  "str", "repr", "format", "zip", "enumerate", "map",
                  "filter", "iter", "next", "isinstance", "getattr",
                  "setattr", "hash", "id", "type", "vars", "globals"}


@dataclass(frozen=True)
class UdfClassification:
    """Outcome of :func:`classify_udf`.

    ``kind``: ``"traceable"`` (jit directly over batched columns),
    ``"vmappable"`` (per-row scalar code a vmap rewrite can batch) or
    ``"host"`` (must run on the Python interpreter). ``sync_points`` lists
    host-device synchronization constructs found regardless of kind.
    """

    kind: str
    reasons: tuple[str, ...] = ()
    sync_points: tuple[str, ...] = ()

    @property
    def jit_eligible(self) -> bool:
        return self.kind in ("traceable", "vmappable")


class _UdfVisitor(ast.NodeVisitor):
    def __init__(self):
        self.kind = "traceable"
        self.reasons: list[str] = []
        self.sync_points: list[str] = []

    def _bump(self, kind: str, reason: str) -> None:
        if _KIND_ORDER[kind] > _KIND_ORDER[self.kind]:
            self.kind = kind
        if reason not in self.reasons:
            self.reasons.append(reason)

    def _sync(self, what: str) -> None:
        if what not in self.sync_points:
            self.sync_points.append(what)

    # control flow ----------------------------------------------------------
    def visit_If(self, node):
        self._bump("host", "data-dependent `if` statement (jit cannot "
                           "trace Python branches)")
        self.generic_visit(node)

    def visit_While(self, node):
        self._bump("host", "data-dependent `while` loop")
        self.generic_visit(node)

    def visit_For(self, node):
        self._bump("host", "Python `for` loop over row values")
        if any(isinstance(n, ast.AugAssign) for n in ast.walk(node)):
            self._sync("Python-loop reduction (accumulates element by "
                       "element on the host)")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._bump("vmappable", "scalar conditional expression "
                                "(jnp.where under vmap)")
        self.generic_visit(node)

    # interpreter-only constructs -------------------------------------------
    def visit_Try(self, node):
        self._bump("host", "try/except block")
        self.generic_visit(node)

    def visit_With(self, node):
        self._bump("host", "context manager")
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Raise(self, node):
        self._bump("host", "raise statement")
        self.generic_visit(node)

    def visit_Await(self, node):
        self._bump("host", "await (event-loop bound)")
        self.generic_visit(node)

    def visit_Yield(self, node):
        self._bump("host", "generator")
        self.generic_visit(node)

    visit_YieldFrom = visit_Yield

    def visit_ListComp(self, node):
        self._bump("host", "Python comprehension")
        self.generic_visit(node)

    visit_SetComp = visit_ListComp
    visit_DictComp = visit_ListComp
    visit_GeneratorExp = visit_ListComp

    def visit_JoinedStr(self, node):
        self._bump("host", "string formatting")
        self.generic_visit(node)

    # calls -----------------------------------------------------------------
    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in _VMAP_BUILTINS:
                if name in ("int", "float", "bool") and node.args \
                        and not isinstance(node.args[0], ast.Constant):
                    # the cast's implicit .item() blocks until the device
                    # flushes — the sync form PWT105's original list
                    # missed (PWT402 widened the contract; this keeps
                    # classify_udf's view consistent with it)
                    self._sync(f"{name}() cast on a device value blocks "
                               "on an implicit .item()")
                self._bump("vmappable",
                           f"scalar builtin {name}() (vmap-able)")
            elif name in _HOST_BUILTINS:
                self._bump("host", f"host builtin {name}()")
            elif name not in ("jit", "vmap"):
                self._bump("host", f"call to {name}() (not a traceable "
                                   "numeric primitive)")
        elif isinstance(func, ast.Attribute):
            owner = func.value
            attr = func.attr
            if isinstance(owner, ast.Name) and owner.id in _NUMERIC_MODULES:
                if owner.id in ("np", "numpy") and attr in _SYNC_NP_FNS:
                    self._sync(f"{owner.id}.{attr}() on a device value "
                               "forces a device→host transfer")
                if owner.id in _SCALAR_MODULES:
                    self._bump("vmappable",
                               f"{owner.id}.{attr}() is per-scalar "
                               "(vmap-able after a jnp rewrite)")
                # numeric-namespace call: traceable, keep walking args
            elif attr in _SYNC_ATTRS:
                self._sync(f".{attr}() forces a device→host sync")
                self._bump("vmappable",
                           f".{attr}() yields a Python scalar")
            else:
                self._bump("host",
                           f"method call .{attr}() on a row value "
                           "(untraceable)")
        self.generic_visit(node)


def _function_node(fn):
    """The ast FunctionDef/Lambda for ``fn``, or None."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # a lambda sharing its line with surrounding code: retry just the
        # fragment from the first `lambda` keyword
        i = src.find("lambda")
        if i < 0:
            return None
        frag = src[i:].rstrip().rstrip("),]}")
        try:
            tree = ast.parse(frag, mode="eval")
        except SyntaxError:
            return None
    name = getattr(fn, "__name__", "<lambda>")
    candidates = [n for n in ast.walk(tree)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n.name == name]
    if candidates:
        return candidates[0]
    lambdas = [n for n in ast.walk(tree) if isinstance(n, ast.Lambda)]
    if lambdas:
        return lambdas[0]
    return None


def _classify_bytecode(fn) -> UdfClassification:
    """Source-less fallback: judge by the globals the code object touches
    and its control-flow opcodes (co_names alone misses pure-local
    loops/branches, which would mis-classify them traceable)."""
    import dis

    code = getattr(fn, "__code__", None)
    if code is None:
        return UdfClassification(
            "host", ("no Python source or bytecode available — "
                     "classified host-only",))
    names = set(code.co_names)
    host = sorted((names - _NUMERIC_MODULES) & (_HOST_BUILTINS | {
        "os", "sys", "time", "random", "requests", "socket", "subprocess",
        "pickle", "json", "re", "hashlib", "urllib", "logging"}))
    if host:
        return UdfClassification(
            "host", tuple(f"bytecode touches host global {n!r}"
                          for n in host))
    branchy = any(
        ins.opname == "FOR_ITER" or "JUMP" in ins.opname
        for ins in dis.get_instructions(code))
    if branchy:
        return UdfClassification(
            "host", ("source unavailable; bytecode contains data-dependent "
                     "control flow — classified host-only",))
    if names <= _NUMERIC_MODULES | {"jit", "vmap"}:
        return UdfClassification(
            "traceable", ("straight-line bytecode touching only numeric "
                          "modules",))
    return UdfClassification(
        "host", ("source unavailable; bytecode references "
                 f"{sorted(names)[:4]!r} — classified host-only",))


def classify_udf(fn) -> UdfClassification:
    """Tag a UDF as jit-traceable / vmappable / host-only.

    AST-based when the source is retrievable, bytecode heuristics
    otherwise. Conservative by design: anything not provably expressible
    as traced numeric code classifies ``host``.
    """
    fn = inspect.unwrap(fn)
    if inspect.iscoroutinefunction(fn):
        return UdfClassification("host", ("async (event-loop bound)",))
    node = _function_node(fn)
    if node is None:
        return _classify_bytecode(fn)
    visitor = _UdfVisitor()
    body = node.body if isinstance(node.body, list) else [node.body]
    for stmt in body:
        visitor.visit(stmt)
    return UdfClassification(visitor.kind, tuple(visitor.reasons),
                             tuple(visitor.sync_points))


_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _udf_def_trace(fn) -> Trace | None:
    """Where the UDF is *defined* (vs. where it is applied, which the
    diagnostic's main trace carries). PWT105 attaches this as a related
    trace so ``check --all`` can tell whether the definition lives in a
    tree the PWT4xx device-path lint already scanned — and defer to
    PWT402 there instead of double-reporting the same sync."""
    code = getattr(fn, "__code__", None)
    if code is None:
        code = getattr(getattr(fn, "func", None), "__code__", None)
    if code is None:
        return None
    return Trace(code.co_filename, code.co_firstlineno,
                 getattr(fn, "__name__", "<udf>"), "")


def _is_framework_fn(fn) -> bool:
    """True for callables defined inside pathway_tpu itself — their
    placement is the framework's concern, not a user diagnostic."""
    code = getattr(inspect.unwrap(fn), "__code__", None)
    if code is None:
        return False
    return os.path.abspath(code.co_filename).startswith(_PKG_ROOT + os.sep)


def _udf_key(fn) -> str:
    """Stable registry key for a UDF: qualname plus definition site, so two
    lambdas (or same-named functions in different modules) never collide."""
    base = getattr(fn, "__qualname__",
                   getattr(fn, "__name__", repr(fn)))
    code = getattr(inspect.unwrap(fn), "__code__", None)
    if code is None:
        return base
    return f"{base} [{code.co_filename}:{code.co_firstlineno}]"


# ---------------------------------------------------------------------------
# plan-level shard checker (driven by the Analyzer)
# ---------------------------------------------------------------------------

class ShardChecker:
    """Second-family pass over an already-walked plan DAG.

    Consumes the base :class:`Analyzer`'s node map and reporting helpers so
    PWT1xx diagnostics carry the same trace/dedup machinery as PWT0xx.
    ``analyzer.mesh`` (a :class:`MeshSpec` or None) is the topology under
    analysis; mesh-independent checks (UDF traceability, fused-slab
    hazards) run either way.
    """

    def __init__(self, analyzer):
        self.a = analyzer
        self.mesh: MeshSpec | None = analyzer.mesh

    # -- entry --------------------------------------------------------------
    def run(self, checked_ids: set[int] | None) -> None:
        """``checked_ids``: node ids to analyze (None = all nodes)."""
        nodes = [n for n in self.a._nodes.values()
                 if checked_ids is None or id(n.table) in checked_ids]
        if self.a.mesh_error is not None:
            self.a._report(
                "PWT101",
                f"analysis mesh is unusable: {self.a.mesh_error} — the "
                f"mesh-dependent checks were skipped")
        self._check_env_mesh()
        streaming = self._streaming_downstream()
        saw_model_parallel = False
        for node in nodes:
            plan = node.table._plan
            if plan.kind == "external_index":
                saw_model_parallel |= self._check_external_index(node)
            hot = id(node.table) in streaming
            for e in node.exprs:
                for sub in ex.walk(e):
                    if isinstance(sub, ex.ApplyExpression):
                        self._check_udf_placement(node, sub, hot=hot)
        if (self.mesh is not None and self.mesh.model > 1
                and not saw_model_parallel):
            self.a._report(
                "PWT107",
                f"analysis mesh {self.mesh} has model={self.mesh.model} but "
                f"nothing in the pipeline is model-parallel — model-axis "
                f"chips only replicate state ({self.mesh.model}x HBM for "
                f"zero speedup) — fix: run with model=1 (all "
                f"{self.mesh.n_devices} chips on the data axis) unless an "
                f"embedder forward uses tensor parallelism")

    # -- mesh/topology ------------------------------------------------------
    def _check_env_mesh(self) -> None:
        """PWT101: env-var topology overrides vs the analysis mesh."""
        if self.mesh is None:
            return
        data_env = os.environ.get("PATHWAY_DATA_PARALLEL")
        model_env = os.environ.get("PATHWAY_MODEL_PARALLEL")
        if data_env is None and model_env is None:
            return
        try:
            model = int(model_env) if model_env is not None else 1
            data = (int(data_env) if data_env is not None
                    else max(1, self.mesh.n_devices // model))
        except ValueError:
            self.a._report(
                "PWT101",
                f"PATHWAY_DATA_PARALLEL={data_env!r} / "
                f"PATHWAY_MODEL_PARALLEL={model_env!r} are not integers — "
                f"fix: set both to positive axis sizes")
            return
        for d in check_mesh_fits(
                data, model, self.mesh.n_devices,
                source=f"env topology (PATHWAY_DATA_PARALLEL={data_env}, "
                       f"PATHWAY_MODEL_PARALLEL={model_env}) vs analysis "
                       f"mesh {self.mesh}"):
            self.a._report(d.code, d.message, severity=d.severity)

    # -- external index: slab shape, specs, placement, growth ---------------
    def _check_external_index(self, node) -> bool:
        """All factory-derived checks. Returns True when the index is
        model-parallel-aware (an embedder forward can use the model axis)."""
        factory = node.table._plan.params.get("index_factory")
        if factory is None:
            return False
        slab_data = self._resolved_data_size(factory)
        embedder = getattr(factory, "embedder", None)
        device_embedder = hasattr(embedder, "encode_batch_device")

        # PWT104: slab pinned to a topology other than the analysis mesh
        explicit = self._explicit_mesh_spec(factory)
        if (explicit is not None and self.mesh is not None
                and explicit.data != self.mesh.data):
            self.a._report(
                "PWT104",
                f"index slab is pinned to a {explicit} mesh while the "
                f"pipeline is analyzed against {self.mesh} — every query "
                f"batch crosses topologies (implicit gather of "
                f"queries/results over DCN instead of ICI) — fix: build "
                f"the index with mesh='auto' or the pipeline's mesh",
                node)

        # PWT102: slab reservation must tile the data axis
        if slab_data is not None and slab_data > 1:
            from pathway_tpu.parallel.sharded_knn import (
                search_operand_layout, slab_cap_per_shard)

            reserved = getattr(factory, "reserved_space", None)
            if isinstance(reserved, int) and reserved > 0:
                # layout-accurate message: the paged store (default)
                # page-aligns each shard's slab, so the predicted cost
                # must use the same page_rows the runtime will
                pr = None
                from pathway_tpu.engine.paged_store import (
                    page_rows, paged_store_enabled)

                if paged_store_enabled():
                    try:
                        pr = page_rows()
                    except ValueError:
                        pr = None  # reported separately as PWT111
                for d in check_sharded_dim(
                        reserved, slab_data,
                        what=f"KNN slab reservation (reserved_space="
                             f"{reserved} over {slab_data} shards)"):
                    cap = slab_cap_per_shard(slab_data, reserved, pr)
                    self.a._report(
                        d.code,
                        d.message + f"; the slab allocates {cap} rows/shard "
                        f"({cap * slab_data} total)",
                        node, severity=d.severity)

            # PWT103: the search kernel's spec/rank contract on this mesh
            layout = search_operand_layout(getattr(factory, "dtype",
                                                   "float32"))
            axes = {DATA_AXIS: slab_data,
                    MODEL_AXIS: self.mesh.model if self.mesh else 1}
            for d in check_shard_specs(
                    axes, [spec for spec, _ in layout],
                    [rank for _, rank in layout]):
                self.a._report(d.code, d.message, node, severity=d.severity)

        # PWT108: fused donated ingest with no reserved capacity
        # (contiguous slab only — the paged store grows the fused path by
        # allocating pages, so no fallback cliff exists there)
        fused = (getattr(factory, "fuse", False) and device_embedder
                 and getattr(factory, "mesh", None) is None)
        reserved = getattr(factory, "reserved_space", None)
        from pathway_tpu.engine.paged_store import paged_store_enabled

        if fused and isinstance(reserved, int) and reserved <= 0 \
                and not paged_store_enabled():
            from pathway_tpu.ops.knn import planned_capacity

            cap = planned_capacity(reserved or 0)
            self.a._report(
                "PWT108",
                f"fused on-device ingest with reserved_space={reserved}: "
                f"the donated slab is pinned at the {cap}-row minimum and "
                f"cannot grow — past {cap} docs every batch silently falls "
                f"back to the slow two-dispatch path — fix: reserve the "
                f"expected corpus size up front",
                node)
        self._check_paged_layout(node, factory, reserved, slab_data)
        return device_embedder

    def _check_paged_layout(self, node, factory, reserved,
                            slab_data) -> None:
        """PWT111: paged-store reservations and tenant quotas. Alignment
        findings are warnings (the allocator rounds UP, silently
        over-reserving); quotas summing past device HBM are errors."""
        from pathway_tpu.engine.paged_store import (page_rows,
                                                    paged_store_enabled)
        from pathway_tpu.internals.static_check.diagnostics import Severity

        if not paged_store_enabled():
            return
        try:
            pr = page_rows()
        except ValueError as e:
            self.a._report("PWT111", f"invalid paged-store config: {e}",
                           node, severity=Severity.ERROR)
            return
        if isinstance(reserved, int) and reserved > 0 and reserved % pr:
            rounded = -(-reserved // pr) * pr
            self.a._report(
                "PWT111",
                f"reserved_space={reserved} is not page-aligned "
                f"(PATHWAY_PAGE_ROWS={pr}): the paged store rounds the "
                f"reservation up to {rounded} rows "
                f"({rounded // pr} pages), silently over-reserving "
                f"{rounded - reserved} rows of HBM — fix: reserve whole "
                f"pages",
                node)
        quotas = getattr(factory, "tenant_quotas", None)
        if not isinstance(quotas, dict) or not quotas:
            return
        total_pages = 0
        for tenant, rows in quotas.items():
            if not isinstance(rows, int) or rows <= 0:
                self.a._report(
                    "PWT111",
                    f"tenant {tenant!r} quota {rows!r} is not a positive "
                    f"row count",
                    node, severity=Severity.ERROR)
                continue
            pages = -(-rows // pr)
            total_pages += pages
            if rows % pr:
                self.a._report(
                    "PWT111",
                    f"tenant {tenant!r} quota of {rows} rows is not "
                    f"page-aligned (PATHWAY_PAGE_ROWS={pr}): the allocator "
                    f"grants whole pages, so the quota silently becomes "
                    f"{pages * pr} rows ({pages} pages) — fix: quota in "
                    f"multiples of {pr}",
                    node)
        dim = getattr(factory, "dimensions", None)
        if not isinstance(dim, int) or dim <= 0:
            return
        dtype = getattr(factory, "dtype", "float32")
        bytes_per_val = {"int8": 1, "bfloat16": 2}.get(dtype, 4)
        # int8 carries f32 scale+vsq side columns per row
        row_bytes = dim * bytes_per_val + (8 if dtype == "int8" else 0)
        hbm_bytes = int(float(os.environ.get(
            "PATHWAY_DEVICE_HBM_GB", "16")) * (1 << 30))
        n_dev = max(1, slab_data or 1)
        need = total_pages * pr * row_bytes
        if need > hbm_bytes * n_dev:
            self.a._report(
                "PWT111",
                f"tenant quotas sum to {total_pages} pages "
                f"({total_pages * pr} rows x {row_bytes} B/row = "
                f"{need / (1 << 30):.1f} GiB as {dtype}) but the device "
                f"has {hbm_bytes * n_dev / (1 << 30):.0f} GiB HBM "
                f"({n_dev} dev x PATHWAY_DEVICE_HBM_GB"
                f"={os.environ.get('PATHWAY_DEVICE_HBM_GB', '16')}) — "
                f"admitting every tenant at "
                f"quota OOMs the slab — fix: lower quotas or shard the "
                f"store over more chips",
                node, severity=Severity.ERROR)

    def _explicit_mesh_spec(self, factory) -> MeshSpec | None:
        """The factory's mesh when explicitly pinned (not None/'auto')."""
        mesh = getattr(factory, "mesh", None)
        if mesh is None or mesh == "auto":
            return None
        try:
            return parse_mesh_spec(mesh)
        except ValueError:
            return None

    def _resolved_data_size(self, factory) -> int | None:
        """Data-axis size the factory's slab will shard over (1 = single
        slab, None = unknown: mesh='auto' with no analysis mesh)."""
        mesh = getattr(factory, "mesh", None)
        if mesh is None:
            return 1
        if mesh == "auto":
            return self.mesh.data if self.mesh is not None else None
        spec = self._explicit_mesh_spec(factory)
        return spec.data if spec is not None else None

    # -- placement: streaming reachability ----------------------------------
    def _streaming_downstream(self) -> set[int]:
        """Ids of tables downstream of a streaming source — the per-batch
        hot path where host round-trips cost every tick."""
        out: set[int] = set()
        stack = []
        for node in self.a._nodes.values():
            plan = node.table._plan
            if plan.kind != "input":
                continue
            source = plan.params.get("datasource")
            if getattr(source, "mode", "streaming") != "static":
                stack.append(node.table)
        while stack:
            t = stack.pop()
            if id(t) in out:
                continue
            out.add(id(t))
            node = self.a._nodes.get(id(t))
            if node is not None:
                stack.extend(node.consumers)
            if t._plan.kind == "iterate_result":
                # the loop body re-executes every batch: a hot iterate
                # makes its body hot too (placeholders flow to the body
                # tables through the normal consumer edges)
                shared = t._plan.params.get("shared")
                if shared is not None:
                    stack.extend(shared.iterated_placeholders)
                    stack.extend(shared.extra_placeholders)
        return out

    # -- UDF traceability ----------------------------------------------------
    def _check_udf_placement(self, node, expr: ex.ApplyExpression, *,
                             hot: bool) -> None:
        if isinstance(expr, ex.AsyncApplyExpression):
            return  # async UDFs are concurrency tools, not compute kernels
        cls = getattr(expr, "_shard_class", None)
        if cls is None:
            cls = classify_udf(expr._fn)
            expr._shard_class = cls  # recorded for run.py's future auto-jit
        fn_name = getattr(expr._fn, "__name__", repr(expr._fn))
        self.a.udf_classifications[_udf_key(expr._fn)] = cls
        if not hot or _is_framework_fn(expr._fn):
            # framework-internal glue (index plumbing, rank projection) is
            # classified but never reported — the user cannot act on it
            return
        if getattr(expr, "_batch", False):
            # batch=True already amortizes dispatch to one call per engine
            # batch — exactly the fix PWT109/PWT110 would suggest
            return
        from pathway_tpu.internals.autojit import autojit_enabled
        from pathway_tpu.internals.autojit import \
            body_fusable as _autojit_body_fusable

        if cls.sync_points and cls.kind != "host":
            self.a._report(
                "PWT105",
                f"UDF {fn_name!r} contains a host-device sync point on a "
                f"per-batch streaming path: {'; '.join(cls.sync_points)} — "
                f"every engine batch stalls the dispatch queue — fix: keep "
                f"values on device (jnp ops) or move the conversion off "
                f"the hot path",
                node, expr=expr,
                related=(t,) if (t := _udf_def_trace(expr._fn)) else ())
        elif cls.kind == "host":
            detail = "; ".join(cls.reasons[:3]) or "unclassifiable"
            sync = (f" (also: {'; '.join(cls.sync_points)})"
                    if cls.sync_points else "")
            overlap = (
                " (with auto-jit on, host-only work in a select that also "
                "carries traceable UDFs is split out and overlapped with "
                "the device leg instead of serializing before it)"
                if autojit_enabled() else "")
            self.a._report(
                "PWT109",
                f"host-only UDF {fn_name!r} sits on a streaming hot path: "
                f"{detail}{sync} — each batch round-trips device→host→"
                f"device — fix: rewrite with jnp/np primitives, or batch "
                f"the work (pw.udf(batch=True)) to amortize the dispatch"
                f"{overlap}",
                node, expr=expr)
        elif autojit_enabled() and _autojit_body_fusable(expr._fn):
            # informational: the runtime is expected to fuse this UDF
            # automatically (internals/autojit.py) — suggesting a manual
            # batch=True rewrite would send the user to do the compiler's
            # job. The body passed the tier's static hazard screen; the
            # compiler still applies dtype/int-overflow gates, hence
            # "expected", never "guaranteed".
            self.a._report(
                "PWT110",
                f"UDF {fn_name!r} is {cls.kind} and is expected to be "
                f"auto-jitted into a fused vectorized dispatch at runtime "
                f"(PATHWAY_AUTO_JIT=1; byte-identical to the interpreted "
                f"path, demotes loudly if untraceable on real data) — no "
                f"change needed; pw.udf(batch=True) remains the manual "
                f"override, PATHWAY_AUTO_JIT=0 the escape hatch",
                node, expr=expr)
        else:
            # auto-jit off, or the body carries a hazard the fused tier
            # refuses (truthiness, inexact math.*, pow) — the manual
            # batch=True rewrite is the actionable advice
            self.a._report(
                "PWT110",
                f"UDF {fn_name!r} is {cls.kind} but dispatched row-by-row "
                f"on the host — eligible for vectorized TPU dispatch — "
                f"fix: pw.udf(batch=True) (columns in, column out)"
                + ("" if autojit_enabled() else
                   ", or re-enable auto-jit (PATHWAY_AUTO_JIT=1) to fuse "
                   "it automatically"),
                node, expr=expr)
