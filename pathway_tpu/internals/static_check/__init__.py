"""Pre-execution diagnostics over the Table plan DAG.

``pw.static_check(*tables)`` analyzes the lazily-built pipeline — plans,
expression trees, and the ParseGraph output registry — and returns a list
of :class:`Diagnostic` findings (codes ``PWT001``–``PWT011``, severities
error/warning/info) *before* the engine ever steps. The same analyzer backs
``pw.run(static_check="warn"|"error")`` and the
``python -m pathway_tpu check`` CLI.

>>> import pathway_tpu as pw
>>> t = pw.debug.table_from_markdown('''
... a | b
... 1 | x
... ''')
>>> diags = pw.static_check(t.select(bad=t.a + t.b))
>>> [d.code for d in diags]
['PWT001']
>>> print(str(diags[0]).splitlines()[0])  # doctest: +ELLIPSIS
PWT001 error ...: operator '+' is not defined between int and str
>>> pw.static_check(t.select(ok=t.a * 2))
[]
"""

from __future__ import annotations

from pathway_tpu.internals.static_check.analyzer import Analyzer, analyze
from pathway_tpu.internals.static_check.diagnostics import (
    CODES,
    Diagnostic,
    Severity,
    StaticCheckError,
    render,
)

__all__ = [
    "Analyzer", "CODES", "Diagnostic", "Severity", "StaticCheckError",
    "analyze", "render", "static_check",
]


def static_check(*tables, persistence: bool | None = None,
                 graph=None) -> list[Diagnostic]:
    """Statically validate the pipeline and return its diagnostics.

    With explicit ``tables``, those tables count as intended outputs (their
    whole upstream DAG is analyzed); with no arguments the globally
    registered sinks' upstream DAGs are analyzed — the same view
    ``pw.run(static_check=...)`` takes. Constructed tables outside every
    output's upstream closure never execute, so they are only flagged as
    dead dataflow (PWT004), not analyzed for errors. ``persistence`` arms the
    persisted-pipeline checks (PWT006); when ``None`` it is auto-detected
    from the persistence environment variables the CLI sets.
    """
    if persistence is None:
        from pathway_tpu.internals.run import _persistence_config_from_env

        persistence = _persistence_config_from_env() is not None
    return analyze(tables, graph=graph, persisted=bool(persistence))
