"""Pre-execution diagnostics over the Table plan DAG.

``pw.static_check(*tables)`` analyzes the lazily-built pipeline — plans,
expression trees, and the ParseGraph output registry — and returns a list
of :class:`Diagnostic` findings (codes ``PWT001``–``PWT011`` for the
logical plan, ``PWT101``–``PWT110`` for sharding/placement, severities
error/warning/info) *before* the engine ever steps. The same analyzer backs
``pw.run(static_check="warn"|"error")`` and the
``python -m pathway_tpu check`` CLI (``--tpu-mesh 4x2`` analyzes against a
hypothetical topology, ``--json`` emits machine-readable diagnostics).

The third family, ``PWT201``–``PWT208`` (concurrency_check.py), analyzes
*source files* rather than the plan DAG — the engine's own threads and
locks: :func:`check_concurrency` is the API door, ``check --concurrency``
the CLI door, and the runtime lock-order sanitizer
(``PATHWAY_LOCK_SANITIZER=1``, engine/locking.py) the execution door.

The fourth family, ``PWT301``–``PWT308`` (durability_check.py), walks the
same source-file road over the persistence plane: snapshot coverage,
capture/restore symmetry, atomic-write and fault-point discipline,
restore-path safety. :func:`check_durability` is the API door,
``check --durability`` the CLI door, and the snapshot-coverage sanitizer
(``PATHWAY_SNAPSHOT_SANITIZER=1``, engine/snapshot_sanitizer.py) the
execution door.

The fifth family, ``PWT401``–``PWT408`` (perf_check.py), guards the
serving hot path's device discipline: recompile zoos, hidden host-device
syncs (superseding PWT105's narrower list), per-row dispatch, residency,
donation and warmup-registry coverage. :func:`check_perf` is the API
door, ``check --perf`` the CLI door, and the steady-state device
sanitizer (``PATHWAY_DEVICE_SANITIZER=1``, engine/device_sanitizer.py)
the execution door. ``check --all`` runs all five families in one
invocation with a versioned JSON document and per-family exit bits, and
``check --list-waivers`` (:func:`scan_waivers`) audits every inline
``pwt-ok`` exemption.

>>> import pathway_tpu as pw
>>> t = pw.debug.table_from_markdown('''
... a | b
... 1 | x
... ''')
>>> diags = pw.static_check(t.select(bad=t.a + t.b))
>>> [d.code for d in diags]
['PWT001']
>>> print(str(diags[0]).splitlines()[0])  # doctest: +ELLIPSIS
PWT001 error ...: operator '+' is not defined between int and str
>>> pw.static_check(t.select(ok=t.a * 2))
[]
"""

from __future__ import annotations

from pathway_tpu.internals.static_check.analyzer import Analyzer, analyze
from pathway_tpu.internals.static_check.concurrency_check import (
    check_concurrency,
    concurrency_inventory,
)
from pathway_tpu.internals.static_check.diagnostics import (
    CODES,
    Diagnostic,
    Severity,
    StaticCheckError,
    render,
)
from pathway_tpu.internals.static_check.durability_check import (
    check_durability,
    durability_inventory,
)
from pathway_tpu.internals.static_check.perf_check import (
    check_perf,
    perf_inventory,
)
from pathway_tpu.internals.static_check.shard_check import (
    MeshSpec,
    UdfClassification,
    classify_udf,
    parse_mesh_spec,
)
from pathway_tpu.internals.static_check.waivers import (
    render_waivers,
    scan_waivers,
)

__all__ = [
    "Analyzer", "CODES", "Diagnostic", "MeshSpec", "Severity",
    "StaticCheckError", "UdfClassification", "analyze",
    "check_concurrency", "check_durability", "check_perf",
    "classify_udf", "concurrency_inventory", "durability_inventory",
    "parse_mesh_spec", "perf_inventory", "render", "render_waivers",
    "scan_waivers", "static_check",
]


def static_check(*tables, persistence: bool | None = None,
                 graph=None, mesh=None,
                 terminate_on_error: bool | None = None,
                 connector_policy=None,
                 qos: bool | None = None) -> list[Diagnostic]:
    """Statically validate the pipeline and return its diagnostics.

    With explicit ``tables``, those tables count as intended outputs (their
    whole upstream DAG is analyzed); with no arguments the globally
    registered sinks' upstream DAGs are analyzed — the same view
    ``pw.run(static_check=...)`` takes. Constructed tables outside every
    output's upstream closure never execute, so they are only flagged as
    dead dataflow (PWT004), not analyzed for errors. ``persistence`` arms the
    persisted-pipeline checks (PWT006); when ``None`` it is auto-detected
    from the persistence environment variables the CLI sets.

    ``mesh`` arms the mesh-dependent sharding/placement checks (PWT1xx,
    static_check/shard_check.py) against a real or hypothetical topology:
    a string ``"4x2"`` (data×model), a :class:`MeshSpec`, a
    ``parallel.mesh.MeshConfig`` or a ``jax.sharding.Mesh``. When ``None``
    the ``PATHWAY_STATIC_CHECK_MESH`` env var is consulted; without either,
    only the mesh-independent PWT1xx checks (UDF traceability, sync
    points, fused-slab hazards) run.
    """
    import os

    if persistence is None:
        from pathway_tpu.internals.run import _persistence_config_from_env

        persistence = _persistence_config_from_env() is not None
    if mesh is None:
        mesh = os.environ.get("PATHWAY_STATIC_CHECK_MESH") or None
    if qos is None:
        from pathway_tpu.engine.qos import qos_enabled_from_env

        qos = qos_enabled_from_env()
    return analyze(tables, graph=graph, persisted=bool(persistence),
                   mesh=mesh, terminate_on_error=terminate_on_error,
                   connector_policy=connector_policy, qos_enabled=qos)
