"""Inline ``pwt-ok`` waiver audit — the reviewable face of suppression.

Every static-check family (PWT0xx–PWT3xx) honors an inline waiver: a
``# pwt-ok: PWTnnn — justification`` comment on the flagged line or in
the contiguous comment block above it. Waivers are deliberate,
audit-trailed exemptions — which only works if someone can actually see
them. :func:`scan_waivers` enumerates every waiver in a source tree as
``(codes, file, line, justification)`` records; ``python -m pathway_tpu
check --list-waivers`` renders them in text or JSON, and CI uploads the
JSON as an artifact so exemptions stay reviewable instead of invisible.

A waiver with no code (bare ``pwt-ok``) suppresses every check on its
line; it is reported with ``codes == ["*"]`` so blanket waivers stand
out in review.
"""

from __future__ import annotations

import io
import re
import tokenize

from pathway_tpu.internals.static_check.concurrency_check import \
    _collect_files

_CODE_RE = re.compile(r"PWT\d{3}")


def _comment_lines(text: str) -> dict[int, str] | None:
    """lineno -> comment text for every real COMMENT token, or None when
    the file does not tokenize (such files never reach the checkers
    either). Tokenizing — rather than substring-scanning raw lines —
    keeps ``pwt-ok`` mentions inside docstrings and help strings (the
    CLI documents the waiver contract in its own ``--help`` text) out of
    the audit."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        return {t.start[0]: t.string
                for t in tokens if t.type == tokenize.COMMENT}
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return None


def scan_waivers(paths) -> list[dict]:
    """Every inline ``pwt-ok`` waiver under ``paths`` as a list of
    ``{"codes", "file", "line", "comment"}`` dicts, ordered by file and
    line. ``comment`` is the waiver's justification text (everything
    after ``pwt-ok`` on the line, codes stripped) — empty means an
    unjustified waiver, which review should treat as a smell. Only real
    ``#`` comments count: a ``pwt-ok`` mentioned in a docstring or
    string literal is documentation, not a waiver."""
    out: list[dict] = []
    for f in _collect_files(paths):
        try:
            text = f.read_text()
        except OSError:
            continue
        lines = text.splitlines()
        comments = _comment_lines(text)
        if comments is None:
            continue
        for lineno in sorted(comments):
            line = comments[lineno]
            idx = line.find("pwt-ok")
            if idx < 0:
                continue
            rest = line[idx + len("pwt-ok"):]
            codes = _CODE_RE.findall(rest) or ["*"]
            head = _CODE_RE.sub("", rest).strip()
            parts = [head.lstrip(":,—–- ").rstrip()]
            # multi-line justifications continue in the comment block
            # below the marker line (same contiguous block _waived scans)
            for n in range(lineno + 1, len(lines) + 1):
                cont = comments.get(n)
                if cont is None or lines[n - 1].strip() != cont.strip():
                    break  # code line, or a trailing comment on one
                parts.append(cont.lstrip("#").strip())
            comment = " ".join(p for p in parts if p)
            out.append({"codes": codes, "file": str(f), "line": lineno,
                        "comment": comment})
    return out


def render_waivers(waivers: list[dict]) -> str:
    """One line per waiver: ``CODE[,CODE] file:line — justification``."""
    rows = []
    for w in waivers:
        just = w["comment"] or "(no justification)"
        rows.append(f"{','.join(w['codes'])} {w['file']}:{w['line']} "
                    f"— {just}")
    return "\n".join(rows)
