"""Static durability analysis — the PWT3xx diagnostic family.

PWT2xx fenced the engine's concurrency contract; this pass fences its
*crash-recovery* contract — the persistence plane (``engine/``, ``io/``)
where a silent bug costs data instead of a deadlock. PR 10's review
passes hand-found exactly the patterns below (hash()-keyed snapshot
state, non-atomic checkpoint writes, seal/drain atomicity gaps); each is
mechanical enough for an AST pass to catch at authoring time. Like
PWT2xx it analyzes **source files**, never importing them, and builds a
small corpus: every class, its methods, its ``__init__``-assigned
mutable state attributes, and its capture/restore method pair
(``snapshot_state``/``restore_state`` for operators,
``state_dict``/``load_state`` for reducer states).

====== ======================================================== =========
code   finding                                                  severity
====== ======================================================== =========
PWT301 stateful operator with no snapshot/restore pair          warning
PWT302 capture/restore key asymmetry                            error
PWT303 hash()/id()-keyed snapshot state with no re-key          error
PWT304 persistence-path write outside tmp+fsync+rename          error
PWT305 blocking persistence I/O with no named fault point       warning
PWT306 unrestricted pickle.load/Unpickler on a restore path     error
PWT307 ``Session.drain`` outside the ``seal_drain`` helper      error
PWT308 nondeterminism source feeding snapshotted state          warning
====== ======================================================== =========

The runtime twin is the snapshot-coverage sanitizer
(engine/snapshot_sanitizer.py, ``PATHWAY_SNAPSHOT_SANITIZER=1``): what
this pass proves about the source — every mutated state attr is captured
— the sanitizer asserts about the execution, attr by attr, snapshot by
snapshot, with a shadow restore round-trip on top.

**Waivers.** Same contract as PWT2xx: a finding on a line whose source
(or the contiguous comment block above it) carries ``pwt-ok: PWT3xx``
is suppressed, and the comment doubles as the audit trail
(``check --list-waivers`` enumerates them). "Fixed, not suppressed" is
the norm; waivers are for the handful of deliberate exceptions (the
trusted intra-fleet wire protocol's pickle, the non-persisted session's
plain ``drain``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from pathway_tpu.internals.static_check.concurrency_check import (
    _collect_files, _waived)
from pathway_tpu.internals.static_check.diagnostics import Diagnostic
from pathway_tpu.internals.trace import Trace

# capture/restore method-name pairs the contract recognizes: operators
# use snapshot_state/restore_state, reducer states state_dict/load_state
_PAIRS = (("snapshot_state", "restore_state"), ("state_dict", "load_state"))
_CAPTURE_NAMES = {cap for cap, _ in _PAIRS}
_RESTORE_NAMES = {res for _, res in _PAIRS}

# key-producing calls whose values are process-local: Python hash() is
# salted per process, id() is an address, row_fingerprint is hash-based
# (engine/delta.py). _stable_row_fp (content digest) is deliberately NOT
# here — stable keys need no re-key.
_VOLATILE_KEY_FNS = {"hash", "id", "row_fingerprint"}

# a write-mode open() whose path expression mentions one of these is a
# persistence-plane write and must go through tmp+fsync+rename
_PERSIST_PATH_TOKENS = ("root", "snapshot", "wal", "checkpoint",
                        "generation", "persist", "manifest")

# in-place container mutators (PWT301's "mutated in step/drain paths")
_MUTATOR_ATTRS = {"append", "add", "pop", "update", "setdefault", "extend",
                  "discard", "clear", "popitem", "insert", "remove"}

# nondeterminism sources (PWT308): module-attribute call forms
_NONDET_CALLS = {("time", "time"), ("time", "time_ns"), ("os", "urandom"),
                 ("uuid", "uuid4"), ("uuid", "uuid1")}
_NONDET_MODULES = {"random"}  # any random.* call


def _self_attr(node: ast.expr) -> str | None:
    """``"X"`` for a ``self.X`` expression, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_volatile_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and (
        (isinstance(node.func, ast.Name)
         and node.func.id in _VOLATILE_KEY_FNS)
        or (isinstance(node.func, ast.Attribute)
            and node.func.attr in _VOLATILE_KEY_FNS))


def _contains_volatile_call(node: ast.AST) -> bool:
    return any(_is_volatile_call(n) for n in ast.walk(node))


def _is_nondet_call(node: ast.AST) -> bool:
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)):
        return False
    mod, attr = node.func.value.id, node.func.attr
    return (mod, attr) in _NONDET_CALLS or mod in _NONDET_MODULES


def _walk_unit(fn_node: ast.AST):
    """Walk a function subtree including nested functions but excluding
    nested class bodies (those are analysis units of their own)."""
    stack = [fn_node]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# corpus model
# ---------------------------------------------------------------------------

@dataclass
class _ClassInfo:
    name: str
    path: str
    lineno: int
    bases: list[str]
    node: ast.ClassDef
    #: direct method name -> def node
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: attr assigned a container literal/ctor in __init__ -> lineno
    mutable_attrs: dict[str, int] = field(default_factory=dict)


@dataclass
class _ModuleInfo:
    path: str
    stem: str
    source_lines: list[str]
    tree: ast.Module
    classes: dict[str, _ClassInfo] = field(default_factory=dict)
    functions: dict[str, ast.FunctionDef] = field(default_factory=dict)


class _Corpus:
    def __init__(self, modules: list[_ModuleInfo],
                 parse_failures: list[tuple[str, str]]):
        self.modules = modules
        self.parse_failures = parse_failures
        #: class name -> _ClassInfo (last definition wins; good enough
        #: for base-chain resolution inside one source tree)
        self.class_index: dict[str, _ClassInfo] = {}
        for mod in modules:
            for cls in mod.classes.values():
                self.class_index[cls.name] = cls


_CONTAINER_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                    "Counter", "deque"}


def _is_container_literal(value: ast.expr) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        return name in _CONTAINER_CTORS
    return False


def build_corpus(paths) -> _Corpus:
    modules: list[_ModuleInfo] = []
    parse_failures: list[tuple[str, str]] = []
    for f in _collect_files(paths):
        try:
            source = f.read_text()
            tree = ast.parse(source, filename=str(f))
        except (OSError, SyntaxError) as e:
            parse_failures.append((str(f), f"{type(e).__name__}: {e}"))
            continue
        stem = f.parent.name if f.stem == "__init__" else f.stem
        mod = _ModuleInfo(path=str(f), stem=stem,
                          source_lines=source.splitlines(), tree=tree)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                cls = _ClassInfo(
                    name=node.name, path=mod.path, lineno=node.lineno,
                    bases=[ast.unparse(b) for b in node.bases], node=node)
                for sub in node.body:
                    if isinstance(sub,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                        cls.methods[sub.name] = sub
                init = cls.methods.get("__init__")
                if init is not None:
                    for stmt in ast.walk(init):
                        if isinstance(stmt, ast.Assign) \
                                and len(stmt.targets) == 1 \
                                and _self_attr(stmt.targets[0]) \
                                and _is_container_literal(stmt.value):
                            cls.mutable_attrs.setdefault(
                                _self_attr(stmt.targets[0]), stmt.lineno)
                mod.classes[node.name] = cls
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
        modules.append(mod)
    return _Corpus(modules, parse_failures)


def _units(mod: _ModuleInfo):
    """Yield (class_info | None, function_node) analysis units."""
    for cls in mod.classes.values():
        for fn in cls.methods.values():
            yield cls, fn
    for fn in mod.functions.values():
        yield None, fn


# ---------------------------------------------------------------------------
# contract resolution helpers
# ---------------------------------------------------------------------------

def _defines_pair_locally(cls: _ClassInfo) -> bool:
    return any(cap in cls.methods and res in cls.methods
               for cap, res in _PAIRS)


def _inherits_real_pair(cls: _ClassInfo, corpus: _Corpus) -> bool:
    """True when a corpus ancestor other than the root ``Operator``
    (whose defaults are the trivial None/raise pair) defines the
    capture/restore pair — e.g. ColumnarGroupByOperator inheriting
    GroupByOperator's, or a reducer inheriting ReducerState's."""
    seen = set()
    queue = list(cls.bases)
    while queue:
        base = queue.pop()
        if base in seen:
            continue
        seen.add(base)
        anc = corpus.class_index.get(base)
        if anc is None or anc.name == "Operator":
            continue
        if _defines_pair_locally(anc):
            return True
        queue.extend(anc.bases)
    return False


def _is_operator_like(cls: _ClassInfo, corpus: _Corpus) -> bool:
    """The class participates in the operator snapshot protocol: its own
    name (or a transitively resolved base's) ends with "Operator"."""
    seen = set()
    queue = [cls.name, *cls.bases]
    while queue:
        name = queue.pop()
        if name in seen:
            continue
        seen.add(name)
        if name.endswith("Operator"):
            return True
        anc = corpus.class_index.get(name)
        if anc is not None:
            queue.extend(anc.bases)
    return False


def _local_capture(cls: _ClassInfo) -> ast.FunctionDef | None:
    for cap in _CAPTURE_NAMES:
        if cap in cls.methods:
            return cls.methods[cap]
    return None


def _local_restore(cls: _ClassInfo) -> ast.FunctionDef | None:
    for res in _RESTORE_NAMES:
        if res in cls.methods:
            return cls.methods[res]
    return None


def _mutations(cls: _ClassInfo, fn: ast.FunctionDef) -> dict[str, int]:
    """State-attr in-place mutations in ``fn``: attr -> first lineno.
    Counts subscript stores/deletes, augassigns and container-mutator
    method calls against attrs initialized as containers in __init__."""
    out: dict[str, int] = {}

    def _hit(attr: str | None, lineno: int) -> None:
        if attr in cls.mutable_attrs and attr not in out:
            out[attr] = lineno

    for node in _walk_unit(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    _hit(_self_attr(tgt.value), node.lineno)
        elif isinstance(node, ast.AugAssign):
            tgt = node.target
            if isinstance(tgt, ast.Subscript):
                _hit(_self_attr(tgt.value), node.lineno)
            else:
                _hit(_self_attr(tgt), node.lineno)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    _hit(_self_attr(tgt.value), node.lineno)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_ATTRS:
            _hit(_self_attr(node.func.value), node.lineno)
    return out


def _capture_reads(capture: ast.FunctionDef) -> set[str]:
    """Attrs ``self.X`` referenced anywhere in the capture method."""
    return {a for n in _walk_unit(capture)
            if (a := _self_attr(n)) is not None}


# ---------------------------------------------------------------------------
# capture/restore key extraction (PWT302)
# ---------------------------------------------------------------------------

def _capture_keys(capture: ast.FunctionDef) -> tuple[set[str], bool]:
    """(literal state keys the capture emits, capture_is_open).

    Keys come from dict literals in ``return`` statements plus
    ``local["k"] = ...`` stores into a returned local. Dynamic keys
    (non-constant subscripts, ``**`` unpacks, non-dict returns) mark the
    capture *open*: we cannot claim a restored key was never captured.
    """
    keys: set[str] = set()
    open_capture = False
    returned_names: set[str] = set()
    for node in _walk_unit(capture):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        keys.add(k.value)
                    else:  # **unpack (None) or computed key
                        open_capture = True
            elif isinstance(node.value, ast.Name):
                returned_names.add(node.value.id)
            elif isinstance(node.value, ast.Constant) \
                    and node.value.value is None:
                pass  # `return None` branch (stateless fast path)
            else:
                open_capture = True
    for node in _walk_unit(capture):
        # normalize `st: dict = {...}` to the plain-assign shape
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            node = ast.Assign(targets=[node.target], value=node.value)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in returned_names \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                else:
                    open_capture = True
        elif isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in returned_names
                        for t in node.targets):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in returned_names:
                    if isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str):
                        keys.add(t.slice.value)
                    else:
                        open_capture = True
    return keys, open_capture


def _restore_keys(restore: ast.FunctionDef) -> tuple[set[str], bool]:
    """(literal state keys the restore reads, restore_is_open)."""
    args = restore.args.args
    # first arg after self is the state parameter
    param = args[1].arg if len(args) > 1 else None
    if param is None:
        return set(), True
    keys: set[str] = set()
    open_restore = False
    for node in _walk_unit(restore):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == param:
            if isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                keys.add(node.slice.value)
            else:
                open_restore = True
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == param:
            if node.func.attr == "get" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                keys.add(node.args[0].value)
            elif node.func.attr in ("items", "keys", "values", "get",
                                    "pop"):
                open_restore = True
        elif isinstance(node, ast.Compare) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str) \
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in node.ops) \
                and any(isinstance(c, ast.Name) and c.id == param
                        for c in node.comparators):
            keys.add(node.left.value)  # `"k" in state` guard
        elif isinstance(node, ast.For) \
                and isinstance(node.iter, ast.Name) \
                and node.iter.id == param:
            open_restore = True
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "update" \
                and any(isinstance(a, ast.Name) and a.id == param
                        for a in node.args):
            open_restore = True
    return keys, open_restore


# ---------------------------------------------------------------------------
# diagnostics plumbing
# ---------------------------------------------------------------------------

def _diag(code: str, message: str, mod_path: str, line: int,
          function: str, source_lines: list[str]) -> Diagnostic:
    src = source_lines[line - 1].strip() if 0 < line <= len(source_lines) \
        else ""
    return Diagnostic(code=code, message=message,
                      trace=Trace(mod_path, line, function, src))


class DurabilityChecker:
    """Runs every PWT3xx check over a parsed corpus."""

    def __init__(self, corpus: _Corpus):
        self.corpus = corpus
        self.diagnostics: list[Diagnostic] = []
        self._sources = {m.path: m.source_lines for m in corpus.modules}

    def _report(self, code: str, message: str, file: str, line: int,
                function: str = "") -> None:
        lines = self._sources.get(file, [])
        if _waived(lines, line, code):
            return
        self.diagnostics.append(
            _diag(code, message, file, line, function, lines))

    def run(self) -> list[Diagnostic]:
        for path, err in self.corpus.parse_failures:
            self.diagnostics.append(Diagnostic(
                code="PWT000",
                message=f"cannot analyze {path}: {err}"))
        self.check_missing_pair()        # PWT301
        self.check_key_asymmetry()       # PWT302
        self.check_volatile_keys()       # PWT303
        self.check_non_atomic_writes()   # PWT304
        self.check_fault_point_coverage()  # PWT305
        self.check_unrestricted_pickle()   # PWT306
        self.check_unsealed_drain()        # PWT307
        self.check_nondeterminism()        # PWT308
        return self.diagnostics

    # -- PWT301 ------------------------------------------------------------
    def check_missing_pair(self) -> None:
        for mod in self.corpus.modules:
            for cls in mod.classes.values():
                if not _is_operator_like(cls, self.corpus):
                    continue
                if cls.name == "Operator":  # the protocol provider
                    continue
                if not cls.mutable_attrs:
                    continue
                if _defines_pair_locally(cls) \
                        or _inherits_real_pair(cls, self.corpus):
                    continue
                mutated: dict[str, int] = {}
                for name, fn in cls.methods.items():
                    if name == "__init__" or name in _CAPTURE_NAMES \
                            or name in _RESTORE_NAMES:
                        continue
                    for attr, line in _mutations(cls, fn).items():
                        mutated.setdefault(attr, line)
                if not mutated:
                    continue
                attrs = ", ".join(sorted(mutated))
                self._report(
                    "PWT301",
                    f"stateful operator {cls.name!r} mutates state "
                    f"attr(s) {attrs} on step/drain paths but defines no "
                    f"snapshot_state/restore_state pair: recovery "
                    f"silently degrades to full-WAL replay",
                    cls.path, cls.lineno, cls.name)

    # -- PWT302 ------------------------------------------------------------
    def check_key_asymmetry(self) -> None:
        for mod in self.corpus.modules:
            for cls in mod.classes.values():
                capture = _local_capture(cls)
                restore = _local_restore(cls)
                if capture is None or restore is None:
                    continue
                captured, cap_open = _capture_keys(capture)
                restored, res_open = _restore_keys(restore)
                if not res_open:
                    for key in sorted(captured - restored):
                        self._report(
                            "PWT302",
                            f"{cls.name}.{capture.name} captures state "
                            f"key {key!r} that {restore.name} never "
                            f"reads: the attr is lost on recovery",
                            cls.path, capture.lineno,
                            f"{cls.name}.{capture.name}")
                if not cap_open:
                    for key in sorted(restored - captured):
                        self._report(
                            "PWT302",
                            f"{cls.name}.{restore.name} reads state key "
                            f"{key!r} that {capture.name} never emits: "
                            f"restore raises KeyError (or installs a "
                            f"stale default) on every recovery",
                            cls.path, restore.lineno,
                            f"{cls.name}.{restore.name}")

    # -- PWT303 ------------------------------------------------------------
    def _volatile_keyed_attrs(self, cls: _ClassInfo) -> dict[str, int]:
        """Attrs stored into under a hash()/id()/row_fingerprint-derived
        key anywhere in the class: attr -> first store lineno."""
        out: dict[str, int] = {}
        for fn in cls.methods.values():
            local_volatile: set[str] = set()
            for node in _walk_unit(fn):
                if isinstance(node, ast.Assign) \
                        and _contains_volatile_call(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_volatile.add(tgt.id)

            def _key_is_volatile(key: ast.expr) -> bool:
                if _contains_volatile_call(key):
                    return True
                return any(isinstance(n, ast.Name)
                           and n.id in local_volatile
                           for n in ast.walk(key))

            for node in _walk_unit(fn):
                attr, key = None, None
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript):
                            attr = _self_attr(tgt.value)
                            key = tgt.slice
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("setdefault", "add") \
                        and node.args:
                    attr = _self_attr(node.func.value)
                    key = node.args[0]
                if attr is not None and key is not None \
                        and _key_is_volatile(key) and attr not in out:
                    out[attr] = node.lineno
        return out

    @staticmethod
    def _rekeyed_in_restore(restore: ast.FunctionDef, attr: str) -> bool:
        """True when the restore body rebuilds ``self.attr`` under fresh
        fingerprints: a comprehension assigned to it containing a
        volatile-key call, or a loop that both calls one and stores into
        the attr."""
        for node in _walk_unit(restore):
            if isinstance(node, ast.Assign) \
                    and any(_self_attr(t) == attr for t in node.targets) \
                    and isinstance(node.value,
                                   (ast.DictComp, ast.SetComp,
                                    ast.ListComp, ast.GeneratorExp)) \
                    and _contains_volatile_call(node.value):
                return True
            if isinstance(node, (ast.For, ast.While)) \
                    and _contains_volatile_call(node):
                for sub in ast.walk(node):
                    stored = None
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if isinstance(tgt, ast.Subscript):
                                stored = _self_attr(tgt.value)
                    elif isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in ("add", "setdefault"):
                        stored = _self_attr(sub.func.value)
                    if stored == attr:
                        return True
        return False

    def check_volatile_keys(self) -> None:
        for mod in self.corpus.modules:
            for cls in mod.classes.values():
                restore = _local_restore(cls)
                if restore is None:
                    continue
                volatile = self._volatile_keyed_attrs(cls)
                if not volatile:
                    continue
                capture = _local_capture(cls)
                if capture is not None:
                    captured_attrs = _capture_reads(capture)
                else:
                    # capture inherited (e.g. ReducerState.state_dict's
                    # generic __slots__ walk): every attr is captured
                    captured_attrs = set(volatile)
                restored_attrs = {a for n in _walk_unit(restore)
                                  if (a := _self_attr(n)) is not None}
                for attr, line in sorted(volatile.items()):
                    if attr not in captured_attrs \
                            or attr not in restored_attrs:
                        continue
                    if self._rekeyed_in_restore(restore, attr):
                        continue
                    self._report(
                        "PWT303",
                        f"{cls.name}.{attr} is keyed by hash()/id()/"
                        f"row_fingerprint values (process-local) and "
                        f"snapshotted, but {restore.name} reinstalls it "
                        f"without a stable re-key: every lookup misses "
                        f"after recovery",
                        cls.path, restore.lineno,
                        f"{cls.name}.{restore.name}")

    # -- PWT304 ------------------------------------------------------------
    def check_non_atomic_writes(self) -> None:
        blessed = {"atomic_write_json", "_atomic_write_bytes", "fsync_dir"}
        for mod in self.corpus.modules:
            for cls, fn in _units(mod):
                if fn.name in blessed:
                    continue
                has_replace = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("replace", "rename")
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "os"
                    for n in _walk_unit(fn))
                if has_replace:
                    continue  # the function implements the discipline
                owner = f"{cls.name}.{fn.name}" if cls else fn.name
                for node in _walk_unit(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    path_expr = None
                    if isinstance(node.func, ast.Name) \
                            and node.func.id == "open" and node.args:
                        mode = None
                        if len(node.args) > 1 and isinstance(
                                node.args[1], ast.Constant):
                            mode = node.args[1].value
                        for kw in node.keywords:
                            if kw.arg == "mode" and isinstance(
                                    kw.value, ast.Constant):
                                mode = kw.value.value
                        if not (isinstance(mode, str)
                                and mode.startswith("w")):
                            continue
                        path_expr = node.args[0]
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in ("write_text",
                                                   "write_bytes"):
                        path_expr = node.func.value
                    if path_expr is None:
                        continue
                    text = ast.unparse(path_expr).lower()
                    if not any(tok in text for tok in
                               _PERSIST_PATH_TOKENS):
                        continue
                    self._report(
                        "PWT304",
                        f"{owner} writes a persistence-root-derived "
                        f"path ({ast.unparse(path_expr)}) without the "
                        f"tmp+fsync+rename discipline: a crash mid-"
                        f"write leaves a torn file where a checkpoint "
                        f"should be (use _atomic_write_bytes / "
                        f"atomic_write_json)",
                        mod.path, node.lineno, owner)

    # -- PWT305 ------------------------------------------------------------
    def check_fault_point_coverage(self) -> None:
        for mod in self.corpus.modules:
            for cls, fn in _units(mod):
                has_fault_point = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("hit", "armed")
                    and "faults" in ast.unparse(n.func.value)
                    for n in _walk_unit(fn))
                if has_fault_point:
                    continue
                owner = f"{cls.name}.{fn.name}" if cls else fn.name
                for node in _walk_unit(fn):
                    if not isinstance(node, ast.Call) \
                            or not isinstance(node.func, ast.Attribute):
                        continue
                    what = None
                    recv = ast.unparse(node.func.value).lower()
                    if node.func.attr == "fsync" and recv == "os":
                        what = "os.fsync"
                    elif node.func.attr == "truncate":
                        what = f"{recv}.truncate"
                    elif node.func.attr in ("put", "put_object") \
                            and any(t in recv for t in
                                    ("s3", "client", "bucket")):
                        what = f"{recv}.{node.func.attr}"
                    if what is None:
                        continue
                    self._report(
                        "PWT305",
                        f"{owner} performs blocking persistence I/O "
                        f"({what}) with no named fault point in the "
                        f"enclosing function: this crash edge is not "
                        f"injectable by testing/faults.py (add "
                        f"faults.hit(\"...\") beside it)",
                        mod.path, node.lineno, owner)

    # -- PWT306 ------------------------------------------------------------
    def check_unrestricted_pickle(self) -> None:
        for mod in self.corpus.modules:
            for cls, fn in _units(mod):
                owner = f"{cls.name}.{fn.name}" if cls else fn.name
                for node in _walk_unit(fn):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id == "pickle" \
                            and node.func.attr in ("load", "loads",
                                                   "Unpickler"):
                        self._report(
                            "PWT306",
                            f"{owner} calls pickle.{node.func.attr} "
                            f"directly: a corrupt or hostile payload "
                            f"executes arbitrary code on restore (use "
                            f"persistence._safe_loads, which whitelists "
                            f"snapshot types by name)",
                            mod.path, node.lineno, owner)

    # -- PWT307 ------------------------------------------------------------
    def check_unsealed_drain(self) -> None:
        for mod in self.corpus.modules:
            for cls, fn in _units(mod):
                if fn.name == "seal_drain":
                    continue  # the atomic helper itself
                if cls is not None and "seal_drain" in cls.methods:
                    continue  # the provider class's internal delegation
                owner = f"{cls.name}.{fn.name}" if cls else fn.name
                for node in _walk_unit(fn):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "drain":
                        recv = ast.unparse(node.func.value).lower()
                        if "session" not in recv and recv != "sess":
                            continue
                        self._report(
                            "PWT307",
                            f"{owner} drains a session outside the "
                            f"atomic seal_drain helper: rows drained "
                            f"here are lost if the process dies before "
                            f"the seal (call rec.seal_drain(tick, "
                            f"limit) on persisted paths)",
                            mod.path, node.lineno, owner)

    # -- PWT308 ------------------------------------------------------------
    def check_nondeterminism(self) -> None:
        for mod in self.corpus.modules:
            for cls in mod.classes.values():
                capture = _local_capture(cls)
                if capture is None:
                    continue
                captured_attrs = _capture_reads(capture)
                for name, fn in cls.methods.items():
                    if name in _CAPTURE_NAMES:
                        continue
                    for node in _walk_unit(fn):
                        attr, value = None, None
                        if isinstance(node, ast.Assign) \
                                and len(node.targets) == 1:
                            tgt = node.targets[0]
                            if isinstance(tgt, ast.Subscript):
                                attr = _self_attr(tgt.value)
                            else:
                                attr = _self_attr(tgt)
                            value = node.value
                        elif isinstance(node, ast.AugAssign):
                            attr = _self_attr(node.target)
                            value = node.value
                        if attr is None or value is None \
                                or attr not in captured_attrs:
                            continue
                        if any(_is_nondet_call(n)
                               for n in ast.walk(value)):
                            self._report(
                                "PWT308",
                                f"{cls.name}.{attr} is snapshotted but "
                                f"fed from a nondeterminism source in "
                                f"{name} ({ast.unparse(value)}): "
                                f"restored replicas diverge from the "
                                f"writer",
                                mod.path, node.lineno,
                                f"{cls.name}.{name}")


# ---------------------------------------------------------------------------
# front doors
# ---------------------------------------------------------------------------

def check_durability(paths, *, corpus: _Corpus | None = None
                     ) -> list[Diagnostic]:
    """Run the PWT3xx family over ``paths`` (files or directories of
    Python source). Returns diagnostics; nothing is imported or
    executed. Pass a prebuilt ``corpus`` (from :func:`build_corpus`) to
    share the parse with :func:`durability_inventory`."""
    return DurabilityChecker(corpus or build_corpus(paths)).run()


def durability_inventory(paths, *, corpus: _Corpus | None = None) -> dict:
    """The snapshot-protocol and fault-point inventories as plain data —
    which classes participate in the operator snapshot protocol, with
    what state attrs, and which named crash edges testing/faults.py can
    inject."""
    corpus = corpus or build_corpus(paths)
    operators = []
    for mod in corpus.modules:
        for cls in mod.classes.values():
            if not _is_operator_like(cls, corpus) \
                    or cls.name == "Operator":
                continue
            operators.append({
                "class": cls.name,
                "file": cls.path,
                "state_attrs": sorted(cls.mutable_attrs),
                "has_snapshot_pair": _defines_pair_locally(cls)
                or _inherits_real_pair(cls, corpus),
            })
    fault_points: set[str] = set()
    for mod in corpus.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "hit" \
                    and "faults" in ast.unparse(node.func.value) \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                fault_points.add(node.args[0].value)
    return {
        "operators": sorted(operators, key=lambda o: o["class"]),
        "fault_points": sorted(fault_points),
    }
