"""Static analysis over the lazily-built Table plan DAG.

The analyzer walks plans / expression trees / the ParseGraph output registry
*before* the engine runs and reports :class:`Diagnostic` findings — dtype
mismatches that would fail (or silently mis-compute) at `pw.run` time, dead
subgraphs, streaming pipelines with no sink, sink formats that cannot carry
the bound table's schema, and universe relations the runtime solver would
reject. Everything here is metadata-only: no datasource is started and no
expression is evaluated.
"""

from __future__ import annotations

from typing import Any, Iterable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.static_check.diagnostics import Diagnostic, Severity
from pathway_tpu.internals.type_inference import infer_dtype

_ARITH = {"+", "-", "*", "/", "//", "%", "**"}
_ORDER_CMP = {"<", "<=", ">", ">="}
_EQ_CMP = {"==", "!="}
_BOOL_OPS = {"&", "|", "^"}
_NUMERIC = (dt.INT, dt.FLOAT)
_DATETIMES = (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC)
_SCALARS = (dt.INT, dt.FLOAT, dt.BOOL, dt.STR)

# plan params that hold bulk row data, not graph structure — skipped by the
# generic walker so analyzing a large static table stays O(plan), not O(rows)
_BULK_PARAMS = {"keys", "rows", "times", "diffs"}


def _is_unknown(d: dt.DType) -> bool:
    """Dtypes the analyzer never judges: inference gave up or the value is
    dynamically typed by design."""
    return d in (dt.ANY, dt.NONE, dt.ERROR, dt.JSON) or isinstance(
        d, (dt.Callable_, dt.Future))


class _Node:
    __slots__ = ("table", "parents", "consumers", "exprs")

    def __init__(self, table):
        self.table = table
        self.parents: list = []      # upstream Tables
        self.consumers: list = []    # downstream Tables
        self.exprs: list = []        # expressions carried by this plan


class Analyzer:
    def __init__(self, *, graph=None, persisted: bool = False, mesh=None,
                 terminate_on_error: bool | None = None,
                 connector_policy=None, qos_enabled: bool | None = None):
        if graph is None:
            from pathway_tpu.internals.parse_graph import G as graph
        from pathway_tpu.internals.static_check.shard_check import \
            parse_mesh_spec

        self.graph = graph
        self.persisted = persisted
        # the run's escalation mode, when known (pw.run passes its
        # terminate_on_error; the CLI does not know it → None disarms the
        # failure-policy check PWT012 rather than guessing), and the
        # run-wide default ConnectorPolicy applied to sources without one
        self.terminate_on_error = terminate_on_error
        self.connector_policy = connector_policy
        # the run's QoS decision for PWT013 (engine/qos.py), tri-state
        # like terminate_on_error: True/False are explicit decisions
        # (False is the documented waiver — a deliberate opt-out), None
        # means nobody decided (QoS defaults OFF → the "measuring
        # without acting" square when an SLO target is configured)
        self.qos_enabled = qos_enabled
        # topology under analysis for the PWT1xx sharding family; None
        # skips the mesh-dependent checks (UDF/placement checks still run).
        # A malformed spec (e.g. a typo'd PATHWAY_STATIC_CHECK_MESH) must
        # surface as a diagnostic, not crash a warn-mode run
        self.mesh_error: str | None = None
        try:
            self.mesh = parse_mesh_spec(mesh)
        except ValueError as e:
            self.mesh = None
            self.mesh_error = str(e)
        self.diagnostics: list[Diagnostic] = []
        # fn name -> UdfClassification, filled by the shard checker; the
        # same classification is stamped on each ApplyExpression
        # (expr._shard_class) so run.py can auto-jit the traceable class
        self.udf_classifications: dict = {}
        self._nodes: dict[int, _Node] = {}
        self._seen_exprs: set[tuple[str, int]] = set()

    # ------------------------------------------------------------------
    # graph collection
    # ------------------------------------------------------------------
    def _collect(self, value: Any, tables: list, exprs: list) -> None:
        from pathway_tpu.internals.table import Table

        if isinstance(value, Table):
            tables.append(value)
        elif isinstance(value, ex.ColumnExpression):
            exprs.append(value)
            for e in ex.walk(value):
                t = getattr(e, "_table", None) or getattr(e, "table", None)
                if isinstance(t, Table):
                    tables.append(t)
        elif isinstance(value, (list, tuple, set, frozenset)):
            for v in value:
                self._collect(v, tables, exprs)
        elif isinstance(value, dict):
            for v in value.values():
                self._collect(v, tables, exprs)
        else:
            from pathway_tpu.internals.iterate import IterateShared

            if isinstance(value, IterateShared):
                # walk into the iterate body exactly once: the body tables
                # are shared by every iterate_result plan, and node identity
                # (plus per-expression dedup) keeps diagnostics from
                # repeating across the loop's outputs
                self._collect(value.input_tables, tables, exprs)
                self._collect(value.result_tables, tables, exprs)

    def _node(self, table) -> _Node:
        node = self._nodes.get(id(table))
        if node is not None:
            return node
        # iterative walk — a deep linear pipeline (thousands of chained
        # selects) must not blow the interpreter recursion limit
        stack = [table]
        edges: list = []  # (parent, child) pairs discovered in this walk
        while stack:
            t = stack.pop()
            if id(t) in self._nodes:
                continue
            n = self._nodes[id(t)] = _Node(t)
            tables: list = []
            exprs: list = []
            for name, value in t._plan.params.items():
                if name in _BULK_PARAMS:
                    continue
                self._collect(value, tables, exprs)
            n.exprs = exprs
            seen_parent: set[int] = set()
            for parent in tables:
                if parent is t or id(parent) in seen_parent:
                    continue
                seen_parent.add(id(parent))
                n.parents.append(parent)
                edges.append((parent, t))
                stack.append(parent)
        for parent, child in edges:
            self._nodes[id(parent)].consumers.append(child)
        return self._nodes[id(table)]

    def _closure(self, roots: Iterable) -> set[int]:
        out: set[int] = set()
        stack = list(roots)
        while stack:
            t = stack.pop()
            if id(t) in out:
                continue
            out.add(id(t))
            stack.extend(self._node(t).parents)
        return out

    # ------------------------------------------------------------------
    # entry point
    # ------------------------------------------------------------------
    def run(self, tables: Iterable = ()) -> list[Diagnostic]:
        explicit = list(tables)
        bound = [o.table for o in self.graph.outputs if o.table is not None]
        roots = explicit + bound
        registered = self.graph.tables()

        reachable = self._closure(roots)
        # build nodes for everything we know about so consumer edges exist
        for t in registered:
            self._node(t)

        # expression/plan checks run on the code that would actually execute:
        # the roots' upstream closure. Tables outside it never run, so their
        # defects are not errors — they get the PWT004 dead-dataflow warning
        # instead. With no roots at all there is no reachability notion and
        # everything is checked.
        check_all = not roots
        for node in list(self._nodes.values()):
            if not check_all and id(node.table) not in reachable:
                continue
            self._check_plan(node)
            for e in node.exprs:
                self._check_expr_tree(node, e)

        self._check_dead_dataflow(roots, registered, reachable)
        self._check_streaming_sources(roots, reachable)
        self._check_sinks()

        # second diagnostic family: sharding/placement (PWT1xx) over the
        # same node map and reporting machinery
        from pathway_tpu.internals.static_check.shard_check import \
            ShardChecker

        ShardChecker(self).run(None if check_all else reachable)
        return self.diagnostics

    # ------------------------------------------------------------------
    # reporting helpers
    # ------------------------------------------------------------------
    def _report(self, code: str, message: str, node: _Node | None = None,
                severity: Severity | None = None, related=(),
                expr=None) -> None:
        if expr is not None:
            key = (code, id(expr))
            if key in self._seen_exprs:
                return
            self._seen_exprs.add(key)
        trace = None
        name = None
        if node is not None:
            trace = node.table._plan.trace
            name = node.table._name
        self.diagnostics.append(Diagnostic(
            code=code, message=message, severity=severity, trace=trace,
            table=name, related=tuple(related)))

    # ------------------------------------------------------------------
    # expression-level checks: PWT001 / PWT002 / PWT006 / PWT008 / PWT010
    # ------------------------------------------------------------------
    def _check_expr_tree(self, node: _Node, root: ex.ColumnExpression) -> None:
        for e in ex.walk(root):
            if isinstance(e, ex.BinaryExpression):
                self._check_binary(node, e)
            elif isinstance(e, (ex.CastExpression, ex.ConvertExpression)):
                self._check_cast(node, e)
            elif isinstance(e, ex.GetExpression) and e._check_if_exists:
                self._check_get_default(node, e)
            if isinstance(e, ex.ApplyExpression):
                self._check_udf(node, e)

    def _check_binary(self, node: _Node, e: ex.BinaryExpression) -> None:
        lt = dt.unoptionalize(infer_dtype(e._left))
        rt = dt.unoptionalize(infer_dtype(e._right))
        if _is_unknown(lt) or _is_unknown(rt):
            return
        op = e._op
        if op in _ARITH and not _arith_ok(op, lt, rt):
            self._report(
                "PWT001",
                f"operator {op!r} is not defined between {lt!r} and {rt!r}",
                node, expr=e)
        elif op in _ORDER_CMP and not _comparable(lt, rt):
            self._report(
                "PWT001",
                f"ordering comparison {op!r} between incomparable dtypes "
                f"{lt!r} and {rt!r}",
                node, expr=e)
        elif op in _EQ_CMP and not _comparable(lt, rt):
            self._report(
                "PWT001",
                f"{op!r} between unrelated dtypes {lt!r} and {rt!r} is "
                f"constant {op == '!='!r}",
                node, severity=Severity.WARNING, expr=e)
        elif op in _BOOL_OPS and not _boolean_ok(lt, rt):
            self._report(
                "PWT001",
                f"boolean operator {op!r} requires bool/int operands, got "
                f"{lt!r} and {rt!r}",
                node, expr=e)
        elif op == "@" and not (isinstance(lt, dt.Array)
                                and isinstance(rt, dt.Array)):
            self._report(
                "PWT001",
                f"matmul '@' requires array operands, got {lt!r} and {rt!r}",
                node, expr=e)

    def _check_cast(self, node: _Node, e) -> None:
        src_full = infer_dtype(e._expr)
        src = dt.unoptionalize(src_full)
        tgt = dt.unoptionalize(e._return_type)
        if src_full == e._return_type and not _is_unknown(src):
            self._report(
                "PWT010",
                f"cast to {e._return_type!r} is redundant: the expression "
                f"already has that dtype",
                node, expr=e)
            return
        if _is_unknown(src) or _is_unknown(tgt):
            return
        if isinstance(e, ex.ConvertExpression) and src is dt.JSON:
            return  # JSON unpacking is exactly what convert is for
        if not _castable(src, tgt):
            kind = ("convert" if isinstance(e, ex.ConvertExpression)
                    else "cast")
            self._report(
                "PWT002",
                f"cannot {kind} {src!r} to {tgt!r}: no runtime conversion "
                f"exists between these dtypes",
                node, expr=e)

    def _check_get_default(self, node: _Node, e: ex.GetExpression) -> None:
        obj_t = dt.unoptionalize(infer_dtype(e._obj))
        if isinstance(obj_t, dt.Tuple):
            elem = dt.types_lca_many(*obj_t.args) if obj_t.args else dt.ANY
            if isinstance(e._index, ex.ConstExpression):
                i = e._index._value
                if isinstance(i, int) and -len(obj_t.args) <= i < len(obj_t.args):
                    elem = obj_t.args[i]
        elif isinstance(obj_t, dt.List):
            elem = obj_t.wrapped
        else:
            return
        default_t = infer_dtype(e._default)
        if _is_unknown(elem) or _is_unknown(default_t):
            return
        if not dt.dtype_issubclass(default_t, elem):
            widened = dt.types_lca(elem, default_t)
            self._report(
                "PWT008",
                f"get() default of dtype {default_t!r} widens the element "
                f"dtype {elem!r} to {widened!r} silently",
                node, expr=e)

    def _check_udf(self, node: _Node, e: ex.ApplyExpression) -> None:
        if not self.persisted:
            return
        is_async = isinstance(e, ex.AsyncApplyExpression)
        if e._deterministic and not is_async:
            return
        fn_name = getattr(e._fn, "__name__", repr(e._fn))
        kind = "async" if is_async else "non-deterministic"
        self._report(
            "PWT006",
            f"{kind} UDF {fn_name!r} feeds a persisted pipeline: replayed "
            f"runs may diverge from the recorded snapshot (mark the UDF "
            f"deterministic=True if it is)",
            node, expr=e)

    # ------------------------------------------------------------------
    # plan-level checks: PWT003 / PWT007 / PWT011
    # ------------------------------------------------------------------
    def _check_plan(self, node: _Node) -> None:
        plan = node.table._plan
        if plan.kind == "join_select":
            for a, b in plan.params.get("on", ()):
                la = dt.unoptionalize(infer_dtype(a))
                rb = dt.unoptionalize(infer_dtype(b))
                if _is_unknown(la) or _is_unknown(rb):
                    continue
                if dt.types_lca(la, rb) is dt.ANY:
                    self._report(
                        "PWT003",
                        f"join keys have incompatible dtypes: left is "
                        f"{la!r}, right is {rb!r} — no value can match",
                        node)
        elif plan.kind == "groupby":
            keys = list(plan.params.get("by") or [])
            inst = plan.params.get("instance")
            if inst is not None:
                keys.append(inst)
            for k in keys:
                kt = dt.unoptionalize(infer_dtype(k))
                if isinstance(kt, dt.Array) or kt in (dt.ERROR,) or isinstance(
                        kt, dt.Callable_):
                    self._report(
                        "PWT003",
                        f"groupby key has dtype {kt!r}, which cannot be used "
                        f"as a grouping key",
                        node)
        elif plan.kind == "ix":
            key_t = infer_dtype(plan.params["key_expr"])
            base_t = dt.unoptionalize(key_t)
            if _is_unknown(base_t):
                return
            if not isinstance(base_t, dt.Pointer):
                self._report(
                    "PWT011",
                    f"ix key expression has dtype {key_t!r}; pointer lookup "
                    f"requires a Pointer (use pointer_from to derive one)",
                    node)
        elif plan.kind == "update_cells":
            self._check_universe_relation(
                node, plan.params["other"], node.table._plan.params["base"],
                op="update_cells", need="other ⊆ base")
        elif plan.kind == "key_filter" and plan.params.get("mode") == "restrict":
            self._check_universe_relation(
                node, plan.params["other"], plan.params["base"],
                op="restrict", need="other ⊆ base")
        elif plan.kind == "identity" and plan.params.get("universe_from") is not None:
            self._check_universe_relation(
                node, plan.params["base"], plan.params["universe_from"],
                op="with_universe_of", need="same key set", equal=True)

    def _check_universe_relation(self, node: _Node, sub, sup, *, op: str,
                                 need: str, equal: bool = False) -> None:
        u_sub, u_sup = sub._universe, sup._universe
        related = tuple(t for t in (sub._plan.trace, sup._plan.trace)
                        if t is not None)
        if u_sub.is_disjoint_from(u_sup):
            self._report(
                "PWT007",
                f"{op}: universes of {sub._name!r} and {sup._name!r} are "
                f"declared disjoint — the runtime solver rejects this "
                f"({need} required)",
                node, related=related)
            return
        proven = (u_sub.is_equal_to(u_sup) if equal
                  else u_sub.is_subset_of(u_sup))
        if not proven:
            self._report(
                "PWT007",
                f"{op}: cannot statically prove {need} for {sub._name!r} vs "
                f"{sup._name!r}; add promise_universe_is_subset_of / "
                f"promise_universes_are_equal if this holds by construction",
                node, severity=Severity.INFO, related=related)

    # ------------------------------------------------------------------
    # graph-level checks: PWT004 / PWT005 / PWT009
    # ------------------------------------------------------------------
    def _check_dead_dataflow(self, roots, registered, reachable) -> None:
        if not roots:
            return
        root_ids = {id(t) for t in roots}
        for t in registered:
            if id(t) in reachable or id(t) in root_ids:
                continue
            node = self._nodes[id(t)]
            if node.consumers:
                continue  # only report the tip of a dead chain
            self._report(
                "PWT004",
                f"table {t._name!r} (and its upstream-only subgraph) is "
                f"computed but never reaches a sink",
                node)

    def _check_streaming_sources(self, roots, reachable) -> None:
        qos_reported = False
        for node in list(self._nodes.values()):
            if node.table._plan.kind != "input":
                continue
            source = node.table._plan.params.get("datasource")
            if getattr(source, "mode", "streaming") == "static":
                # a static read terminates on its own; if it feeds nothing,
                # the dead-dataflow check (PWT004) already reports it
                continue
            self._check_failure_policy(node, source)
            if not qos_reported:
                qos_reported = self._check_qos_slo(node, source)
            if not roots:
                self._report(
                    "PWT005",
                    f"streaming source {node.table._name!r} has no output "
                    f"binder: pw.run would consume it forever while "
                    f"producing nothing",
                    node)
            elif id(node.table) not in reachable:
                self._report(
                    "PWT005",
                    f"streaming source {node.table._name!r} never reaches "
                    f"a sink",
                    node)

    def _check_failure_policy(self, node: _Node, source) -> None:
        """PWT012: no retries AND no escalation — the one policy square
        where a reader crash neither restarts nor stops the run, so the
        source silently drops out while the pipeline reports progress."""
        if self.terminate_on_error is not False:
            return  # escalation (or an unknown run mode) covers the crash
        # the effective policy mirrors the supervisor's resolution: the
        # source's own, else the run-wide default; the supervisor's
        # built-in default retries, so no-policy-anywhere is safe
        policy = getattr(source, "connector_policy", None) \
            or self.connector_policy
        if policy is None or getattr(policy, "max_retries", None) != 0:
            return
        self._report(
            "PWT012",
            f"streaming source {node.table._name!r} has max_retries=0 and "
            f"the run uses terminate_on_error=False: a reader crash would "
            f"neither restart nor stop the run — the source is silently "
            f"dropped (give it retries, or let the failure terminate)",
            node)

    def _check_qos_slo(self, node: _Node, source) -> bool:
        """PWT013: a serving-latency SLO target is configured but the
        pipeline would run with QoS disabled — the measurement plane
        (PR 6) is armed while nothing acts on it (engine/qos.py).
        Arming mirrors PWT012's rules: the check fires only on the one
        square where nobody decided — ``qos_enabled is None`` means QoS
        defaults OFF; an explicit False (``pw.run(qos=False)`` /
        ``PATHWAY_QOS=0``) is the documented waiver, True is the fix.
        Scoped to pipelines that actually serve (a source carrying a
        request-tracker slot, i.e. a rest route): a pure ETL graph
        measures nothing per-request, so there is no loop to close.
        Returns True when reported (one finding per pipeline)."""
        if self.qos_enabled is not None:
            return False
        if not hasattr(source, "request_tracker"):
            return False
        import os

        if not (os.environ.get("PATHWAY_SLO_E2E_MS") or "").strip():
            return False
        self._report(
            "PWT013",
            f"serving source {node.table._name!r} runs under a configured "
            f"SLO target (PATHWAY_SLO_E2E_MS) with QoS disabled: latency "
            f"is measured but nothing acts on it — enable the control "
            f"loop (pw.run(qos=True) / PATHWAY_QOS=1) or waive "
            f"explicitly (qos=False / PATHWAY_QOS=0)",
            node)
        return True

    def _check_sinks(self) -> None:
        for binding in self.graph.outputs:
            if binding.table is None or binding.format is None:
                continue
            table = binding.table
            node = self._nodes.get(id(table))
            for name in table.column_names():
                col_t = dt.unoptionalize(table._schema[name].dtype)
                bad = _format_incompatibility(binding.format, col_t)
                if bad:
                    self._report(
                        "PWT009",
                        f"sink {binding.sink!r} (format={binding.format!r}) "
                        f"cannot faithfully serialize column {name!r} of "
                        f"dtype {col_t!r}: {bad}",
                        node)


# ---------------------------------------------------------------------------
# dtype compatibility tables
# ---------------------------------------------------------------------------

def _arith_ok(op: str, l: dt.DType, r: dt.DType) -> bool:
    if l in _NUMERIC and r in _NUMERIC:
        return True
    if isinstance(l, dt.Array) or isinstance(r, dt.Array):
        return True  # broadcasting elementwise arithmetic
    if op == "+":
        if l is dt.STR and r is dt.STR:
            return True
        if isinstance(l, (dt.Tuple, dt.List)) and isinstance(
                r, (dt.Tuple, dt.List)):
            return True
    if op == "*" and {l, r} == {dt.STR, dt.INT}:
        return True
    # datetime algebra
    if op == "-" and l in _DATETIMES and r is l:
        return True
    if op in ("+", "-") and l in _DATETIMES and r is dt.DURATION:
        return True
    if op == "+" and l is dt.DURATION and r in _DATETIMES:
        return True
    if l is dt.DURATION and r is dt.DURATION and op in ("+", "-", "/", "%"):
        return True
    if l is dt.DURATION and r in _NUMERIC and op in ("*", "/", "//"):
        return True
    if l in _NUMERIC and r is dt.DURATION and op == "*":
        return True
    return False


def _comparable(l: dt.DType, r: dt.DType) -> bool:
    if l in _NUMERIC and r in _NUMERIC:
        return True
    return dt.types_lca(l, r) is not dt.ANY


def _boolean_ok(l: dt.DType, r: dt.DType) -> bool:
    return l in (dt.BOOL, dt.INT) and r in (dt.BOOL, dt.INT)


def _castable(src: dt.DType, tgt: dt.DType) -> bool:
    if dt.dtype_issubclass(src, tgt) or dt.dtype_issubclass(tgt, src):
        return True
    if src in _SCALARS and tgt in _SCALARS:
        return True
    if isinstance(src, dt.Array) and isinstance(tgt, dt.Array):
        return True
    if {src, tgt} == {dt.BYTES, dt.STR}:
        return True
    if src in (*_DATETIMES, dt.DURATION) and tgt in (dt.STR, dt.INT, dt.FLOAT):
        return True
    if tgt is dt.STR:
        return True  # everything renders to a string
    return False


def _format_incompatibility(format: str | None, col_t: dt.DType) -> str | None:
    """Reason a column dtype cannot ride the sink format, or None if fine."""
    if format in ("csv", "dsv", "sql"):
        if isinstance(col_t, (dt.Array, dt.Tuple, dt.List)) or col_t in (
                dt.ANY_ARRAY,):
            return "flat text formats have no array/tuple encoding"
        if col_t is dt.BYTES:
            return "raw bytes are not representable in a text row format"
        if isinstance(col_t, dt.Callable_):
            return "callables cannot be serialized"
    elif format == "json":
        if col_t is dt.BYTES:
            return "JSON has no bytes type (encode to str first)"
        if isinstance(col_t, dt.Callable_):
            return "callables cannot be serialized"
    return None


def analyze(tables: Iterable = (), *, graph=None, persisted: bool = False,
            mesh=None, terminate_on_error: bool | None = None,
            connector_policy=None,
            qos_enabled: bool | None = None) -> list[Diagnostic]:
    """Run every static check; see :class:`Analyzer`. ``mesh`` arms the
    mesh-dependent sharding checks against a real or hypothetical
    topology (``"4x2"``, a MeshSpec/MeshConfig, or a jax Mesh);
    ``terminate_on_error`` (the run's escalation mode, when known) arms
    the connector failure-policy check (PWT012), with
    ``connector_policy`` as the run-wide default for sources that set
    none of their own; ``qos_enabled`` (the run's QoS decision,
    tri-state) arms the measuring-without-acting check (PWT013)."""
    return Analyzer(graph=graph, persisted=persisted, mesh=mesh,
                    terminate_on_error=terminate_on_error,
                    connector_policy=connector_policy,
                    qos_enabled=qos_enabled).run(tables)
