"""Static concurrency analysis — the PWT2xx diagnostic family.

PWT0xx validates the logical plan and PWT1xx the sharding layer; this pass
turns the Analyzer machinery on the layer where the reference engine gets
safety for free from Rust ownership and this Python reproduction does not:
the ~10 long-lived threads (device-bridge worker, supervisor reader
threads, watchdog, HTTP monitoring server, multiproc acceptor/sender)
sharing engine state. Unlike its siblings it analyzes **source files**, not
the plan DAG — an AST pass over ``pathway_tpu/engine/`` (and ``io/``,
``parallel/``) that builds:

- a **thread inventory** — every ``threading.Thread`` / factory ``spawn``
  target and the methods it reaches through ``self`` calls;
- a **lock inventory** — every lock/rlock/condition/event attribute or
  module global, resolved to a stable identity (``Class.attr`` /
  ``module.NAME``);
- a **lock-order graph** — a directed edge A→B for every ``with B:``
  nested (lexically or one ``self``-call deep) inside ``with A:``.

and flags:

====== ======================================================== =========
code   finding                                                  severity
====== ======================================================== =========
PWT201 lock-order inversion (cycle in the order graph)          error
PWT202 attribute written from ≥2 thread roots, no common guard  error
PWT203 lock held across a known-blocking call                   warning
PWT204 daemon thread whose handle is dropped (no stop/join)     warning
PWT205 ``Condition.wait`` outside a predicate re-check loop     error
PWT206 sleep-polling loop where an Event exists                 warning
PWT207 bare ``threading.Thread`` instead of the engine factory  warning
PWT208 ``Condition.notify`` outside the condition's ``with``    error
====== ======================================================== =========

The runtime twin is the lock-order sanitizer (engine/locking.py,
``PATHWAY_LOCK_SANITIZER=1``): what this pass proves about the source, the
sanitizer asserts about the execution.

**Waivers.** A finding on a line whose source carries ``pwt-ok: PWTxxx``
(or a bare ``pwt-ok``) is suppressed — for the handful of deliberate
lock-free patterns (single GIL-atomic stores, the thread factory's own
``threading.Thread`` call). CI treats the waiver comment as the audit
trail; "fixed, not suppressed" is the norm for everything else.

Everything here is metadata-only: the analyzed modules are parsed, never
imported.
"""

from __future__ import annotations

import ast
import os
import pathlib
from dataclasses import dataclass, field

from pathway_tpu.internals.static_check.diagnostics import Diagnostic
from pathway_tpu.internals.trace import Trace

# attribute/global kinds the inventory tracks
_THREADING_KINDS = {"Lock": "lock", "RLock": "rlock",
                    "Condition": "condition", "Event": "event"}
_FACTORY_KINDS = {"create_lock": "lock", "create_rlock": "rlock",
                  "create_condition": "condition"}
_LOCKISH = ("lock", "rlock", "condition")

# method names that block the calling thread indefinitely (or for device
# time) — holding an engine lock across one stalls every contender.
# ``submit``/``barrier`` are bridge-shaped and only match receivers whose
# source text mentions "bridge" (ThreadPoolExecutor.submit is not
# blocking); bare names match any receiver.
_BLOCKING_ATTRS = {"fsync", "sendall", "send_bytes", "recv_bytes",
                   "exchange", "block_until_ready", "device_put"}
_BLOCKING_BRIDGE_ATTRS = {"submit", "barrier"}
_SLEEP_NAMES = {"sleep"}


def _waived(source_lines: list[str], lineno: int, code: str) -> bool:
    """True when the flagged line — or the contiguous comment block
    directly above it — carries a ``pwt-ok`` waiver for ``code`` (a bare
    ``pwt-ok`` with no code waives every check on the line)."""
    def _matches(text: str) -> bool:
        return "pwt-ok" in text and (
            code in text or "PWT" not in text)

    if 1 <= lineno <= len(source_lines) and _matches(
            source_lines[lineno - 1]):
        return True
    ln = lineno - 1
    while 1 <= ln <= len(source_lines) and \
            source_lines[ln - 1].lstrip().startswith("#"):
        if _matches(source_lines[ln - 1]):
            return True
        ln -= 1
    return False


# ---------------------------------------------------------------------------
# inventory model
# ---------------------------------------------------------------------------

@dataclass
class LockDef:
    """One lock/rlock/condition/event in the inventory."""

    lock_id: str      # "Class.attr" or "module.NAME"
    kind: str         # lock | rlock | condition | event
    file: str
    line: int


@dataclass
class ThreadDef:
    """One thread creation site."""

    target: str | None      # resolved "Class.method" / "module.func" / None
    file: str
    line: int
    via_factory: bool       # engine/threads.py spawn
    daemon: bool
    handle_kept: bool       # stored/returned/appended/joined


@dataclass
class _Write:
    attr: str
    line: int
    guards: frozenset
    method: str


@dataclass
class _FuncInfo:
    name: str
    qualname: str
    cls: str | None
    file: str
    # (held_lock_id, acquired_lock_id, line)
    order_edges: list = field(default_factory=list)
    # lock ids this function acquires directly (any nesting)
    acquires: set = field(default_factory=set)
    # (held_lock_id, call_description, line)
    blocking_under_lock: list = field(default_factory=list)
    # (cond_id, line, inside_while)
    cond_waits: list = field(default_factory=list)
    # (cond_id, line, inside_with_same_cond)
    notifies: list = field(default_factory=list)
    writes: list = field(default_factory=list)          # [_Write]
    # (callee_method_name, frozenset(held), line) — self.<m>() calls
    self_calls: list = field(default_factory=list)
    # (line, event_id_or_None) sleep calls inside polling while-loops
    poll_sleeps: list = field(default_factory=list)
    spawns: list = field(default_factory=list)          # [ThreadDef]
    raw_threads: list = field(default_factory=list)     # [line]


@dataclass
class _ClassInfo:
    name: str
    file: str
    attr_kinds: dict = field(default_factory=dict)   # attr -> kind
    methods: dict = field(default_factory=dict)      # name -> _FuncInfo
    # attr -> method names in which it is the spawn target
    thread_targets: set = field(default_factory=set)


@dataclass
class _ModuleInfo:
    path: str
    stem: str
    source_lines: list
    classes: dict = field(default_factory=dict)      # name -> _ClassInfo
    functions: dict = field(default_factory=dict)    # name -> _FuncInfo
    global_kinds: dict = field(default_factory=dict)  # NAME -> kind
    # (line, "threading.Lock") raw primitive constructions anywhere in the
    # module (module level included) — PWT207's lock-factory aspect
    raw_locks: list = field(default_factory=list)
    # a module that DEFINES the factories is the provider, not a consumer
    is_factory_provider: bool = False


# ---------------------------------------------------------------------------
# pass 1: attribute/global kind collection (whole corpus, so `other._mutex`
# can resolve by unique definer)
# ---------------------------------------------------------------------------

def _call_kind(call: ast.expr) -> str | None:
    """Kind of primitive a call expression constructs, if any:
    ``threading.Lock()``, ``Condition()``, ``create_lock("...")`` …"""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    else:
        return None
    return _THREADING_KINDS.get(name) or _FACTORY_KINDS.get(name)


class _KindCollector(ast.NodeVisitor):
    def __init__(self, mod: _ModuleInfo):
        self.mod = mod
        self._cls: _ClassInfo | None = None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self._cls
        self._cls = self.mod.classes.setdefault(
            node.name, _ClassInfo(node.name, self.mod.path))
        self.generic_visit(node)
        self._cls = prev

    def visit_Assign(self, node: ast.Assign) -> None:
        kind = _call_kind(node.value)
        if kind is not None:
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and self._cls is not None:
                    self._cls.attr_kinds[t.attr] = kind
                elif isinstance(t, ast.Name) and self._cls is None:
                    self.mod.global_kinds[t.id] = kind
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# pass 2: per-function analysis
# ---------------------------------------------------------------------------

class _Corpus:
    """All analyzed modules + the cross-module attr-kind index."""

    def __init__(self, modules: list[_ModuleInfo],
                 parse_failures: list[tuple[str, str]] | None = None):
        self.modules = modules
        # (path, error) for files that could not be read/parsed — the
        # checker reports these as PWT000: a silently-skipped file would
        # hollow out a "directory is clean" gate
        self.parse_failures = parse_failures or []
        # attr name -> [(class_name, kind)] across the whole corpus
        self.attr_index: dict[str, list[tuple[str, str]]] = {}
        for m in modules:
            for c in m.classes.values():
                for attr, kind in c.attr_kinds.items():
                    self.attr_index.setdefault(attr, []).append(
                        (c.name, kind))

    def resolve(self, expr: ast.expr, mod: _ModuleInfo,
                cls: _ClassInfo | None,
                kinds: tuple = _LOCKISH) -> tuple[str, str] | None:
        """Resolve an expression to (lock_id, kind) when it names an
        inventoried primitive of one of ``kinds``; None otherwise.
        ``self.x`` prefers the enclosing class; any other ``<obj>.x``
        resolves only when exactly one class in the corpus defines ``x``
        with a matching kind (ambiguity drops the fact rather than
        inventing one)."""
        if isinstance(expr, ast.Name):
            kind = mod.global_kinds.get(expr.id)
            if kind in kinds:
                return (f"{mod.stem}.{expr.id}", kind)
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and cls is not None:
            kind = cls.attr_kinds.get(attr)
            if kind in kinds:
                return (f"{cls.name}.{attr}", kind)
        candidates = [(c, k) for c, k in self.attr_index.get(attr, ())
                      if k in kinds]
        if len(candidates) == 1:
            c, k = candidates[0]
            return (f"{c}.{attr}", k)
        return None


def _is_spawn_call(call: ast.Call) -> tuple[bool, bool] | None:
    """(is_thread_creation, via_factory) or None."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return (True, False)
    if isinstance(fn, ast.Name) and fn.id == "Thread":
        return (True, False)
    if isinstance(fn, ast.Name) and fn.id == "spawn":
        return (True, True)
    if isinstance(fn, ast.Attribute) and fn.attr == "spawn":
        return (True, True)
    return None


def _target_name(call: ast.Call) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    if call.args:
        return call.args[0]
    return None


def _resolve_target(expr: ast.expr | None,
                    cls: _ClassInfo | None,
                    mod: _ModuleInfo) -> str | None:
    if expr is None:
        return None
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self" \
            and cls is not None:
        return f"{cls.name}.{expr.attr}"
    if isinstance(expr, ast.Name):
        return f"{mod.stem}.{expr.id}"
    return None


def _expr_text(expr: ast.expr) -> str:
    try:
        return ast.unparse(expr)
    except Exception:
        return "<expr>"


class _FuncAnalyzer(ast.NodeVisitor):
    """Walks ONE function body tracking the lexical with-lock stack and
    while-loop ancestry. Nested functions are analyzed as part of their
    enclosing function (a closure spawned as a thread target shares the
    method's guards)."""

    def __init__(self, corpus: _Corpus, mod: _ModuleInfo,
                 cls: _ClassInfo | None, info: _FuncInfo):
        self.corpus = corpus
        self.mod = mod
        self.cls = cls
        self.info = info
        self.with_stack: list[str] = []     # lock ids, outermost first
        self.while_depth = 0
        self.while_tests: list[ast.expr] = []

    # -- with / locks ------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        entered: list[str] = []
        for item in node.items:
            res = self.corpus.resolve(item.context_expr, self.mod, self.cls)
            if res is not None:
                lock_id, _kind = res
                self.info.acquires.add(lock_id)
                for held in self.with_stack:
                    if held != lock_id:
                        self.info.order_edges.append(
                            (held, lock_id, node.lineno))
                self.with_stack.append(lock_id)
                entered.append(lock_id)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self.with_stack.pop()

    visit_AsyncWith = visit_With

    # -- loops -------------------------------------------------------------
    def visit_While(self, node: ast.While) -> None:
        self.while_depth += 1
        self.while_tests.append(node.test)
        self.generic_visit(node)
        self.while_tests.pop()
        self.while_depth -= 1

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self._check_spawn(node)
        self._check_blocking(node)
        self._check_wait_notify(node)
        self._check_sleep(node)
        self._check_self_call(node)
        self.generic_visit(node)

    def _check_spawn(self, node: ast.Call) -> None:
        spawn = _is_spawn_call(node)
        if spawn is None:
            return
        _is_thread, via_factory = spawn
        if not via_factory:
            self.info.raw_threads.append(node.lineno)
        target = _resolve_target(_target_name(node), self.cls, self.mod)
        daemon = True
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        self.info.spawns.append(ThreadDef(
            target=target, file=self.mod.path, line=node.lineno,
            via_factory=via_factory, daemon=daemon, handle_kept=False))
        if target is not None and self.cls is not None and \
                target.startswith(self.cls.name + "."):
            self.cls.thread_targets.add(target.split(".", 1)[1])

    def _check_blocking(self, node: ast.Call) -> None:
        if not self.with_stack:
            return
        fn = node.func
        desc = None
        if isinstance(fn, ast.Attribute):
            if fn.attr in _BLOCKING_ATTRS:
                desc = _expr_text(fn)
            elif fn.attr in _BLOCKING_BRIDGE_ATTRS and \
                    "bridge" in _expr_text(fn.value).lower():
                desc = _expr_text(fn)
            elif fn.attr in _SLEEP_NAMES and not isinstance(
                    fn.value, ast.Constant):
                # time.sleep / _time.sleep / session.sleep — all block
                desc = _expr_text(fn)
        elif isinstance(fn, ast.Name) and fn.id in (
                _BLOCKING_ATTRS | _SLEEP_NAMES):
            desc = fn.id
        if desc is not None:
            self.info.blocking_under_lock.append(
                (self.with_stack[-1], desc, node.lineno))

    def _check_wait_notify(self, node: ast.Call) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        if fn.attr in ("wait", "wait_for"):
            res = self.corpus.resolve(fn.value, self.mod, self.cls,
                                      kinds=("condition",))
            if res is not None:
                cond_id, _ = res
                ok = fn.attr == "wait_for" or self.while_depth > 0
                self.info.cond_waits.append((cond_id, node.lineno, ok))
                # waiting on a condition while holding OTHER locks blocks
                # those locks too (the condition only releases its own)
                others = [h for h in self.with_stack if h != cond_id]
                if others:
                    self.info.blocking_under_lock.append(
                        (others[-1], f"{_expr_text(fn)} (wait releases "
                                     f"only its own lock)", node.lineno))
        elif fn.attr in ("notify", "notify_all"):
            res = self.corpus.resolve(fn.value, self.mod, self.cls,
                                      kinds=("condition",))
            if res is not None:
                cond_id, _ = res
                self.info.notifies.append(
                    (cond_id, node.lineno, cond_id in self.with_stack))

    def _check_sleep(self, node: ast.Call) -> None:
        fn = node.func
        is_sleep = (isinstance(fn, ast.Attribute)
                    and fn.attr == "sleep"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ("time", "_time")) or (
                        isinstance(fn, ast.Name) and fn.id == "sleep")
        if not is_sleep or self.while_depth == 0:
            return
        # an Event is "available" when the loop condition polls one
        # (`while not self._stop.is_set()`) or the enclosing class owns
        # one — either way Event.wait(timeout) replaces the sleep
        event_id = None
        for test in self.while_tests:
            for sub in ast.walk(test):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "is_set":
                    res = self.corpus.resolve(
                        sub.func.value, self.mod, self.cls,
                        kinds=("event",))
                    event_id = res[0] if res else _expr_text(sub.func.value)
        if event_id is None and self.cls is not None:
            for attr, kind in self.cls.attr_kinds.items():
                if kind == "event":
                    event_id = f"{self.cls.name}.{attr}"
                    break
        if event_id is not None:
            self.info.poll_sleeps.append((node.lineno, event_id))

    def _check_self_call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self":
            self.info.self_calls.append(
                (fn.attr, frozenset(self.with_stack), node.lineno))

    # -- writes ------------------------------------------------------------
    def _record_write(self, target: ast.expr, lineno: int) -> None:
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            self.info.writes.append(_Write(
                target.attr, lineno, frozenset(self.with_stack),
                self.info.name))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    # handle spawn results: `t = threading.Thread(...)` / `self._t = ...`
    # handled post-hoc in _mark_kept_handles (needs whole-function view)


def _callee_keeps_param(class_node: ast.ClassDef | None,
                        method_name: str, arg_index: int) -> bool:
    """One-level resolution for ``self.m(spawn(...))``: does method ``m``
    of the same class keep its ``arg_index``-th parameter (append/add
    into a container, join, store on self, or return it)? Mirrors the
    direct keep rules so a tracking helper counts as keeping."""
    if class_node is None:
        return False
    for sub in class_node.body:
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))                 or sub.name != method_name:
            continue
        params = [a.arg for a in sub.args.args]
        pidx = arg_index + 1  # skip self
        if pidx >= len(params):
            return False
        pname = params[pidx]
        for node in ast.walk(sub):
            if isinstance(node, ast.Call)                     and isinstance(node.func, ast.Attribute)                     and node.func.attr in ("join", "append", "add")                     and any(isinstance(a, ast.Name) and a.id == pname
                            for a in node.args):
                return True
            if isinstance(node, ast.Assign)                     and isinstance(node.value, ast.Name)                     and node.value.id == pname                     and any(isinstance(t, ast.Attribute)
                            for t in node.targets):
                return True
            if isinstance(node, ast.Return)                     and isinstance(node.value, ast.Name)                     and node.value.id == pname:
                return True
        return False
    return False


def _mark_kept_handles(fn_node: ast.AST, info: _FuncInfo,
                       class_node: ast.ClassDef | None = None) -> None:
    """Decide handle_kept for each spawn in this function: kept when the
    thread object is stored on self, returned, appended into a container,
    joined by a local name, or handed to a same-class method that
    verifiably keeps it. Anything else is a dropped daemon handle
    (PWT204)."""
    # local name -> spawn indices (matched by the spawn call's line)
    local_spawns: dict[str, list[int]] = {}
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_spawn_call(node.value) is None:
                continue
            idx = next((i for i, sp in enumerate(info.spawns)
                        if sp.line == node.value.lineno), None)
            if idx is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    info.spawns[idx].handle_kept = True
                elif isinstance(t, ast.Name):
                    local_spawns.setdefault(t.id, []).append(idx)
        elif isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Call) and \
                    _is_spawn_call(node.value) is not None:
                idx = next((i for i, sp in enumerate(info.spawns)
                            if sp.line == node.value.lineno), None)
                if idx is not None:
                    info.spawns[idx].handle_kept = True
            elif isinstance(node.value, ast.Name):
                for idx in local_spawns.get(node.value.id, ()):
                    info.spawns[idx].handle_kept = True
    # second sweep: joins / appends of local names
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            fn = node.func
            if fn.attr in ("join", "append", "add"):
                names = [a.id for a in node.args
                         if isinstance(a, ast.Name)]
                if isinstance(fn.value, ast.Name):
                    names.append(fn.value.id)
                for name in names:
                    for idx in local_spawns.get(name, ()):
                        info.spawns[idx].handle_kept = True
                # direct form: container.append(spawn(...)) — the handle
                # lands in the container without ever touching a name
                for a in node.args:
                    if isinstance(a, ast.Call) \
                            and _is_spawn_call(a) is not None:
                        idx = next(
                            (i for i, sp in enumerate(info.spawns)
                             if sp.line == a.lineno), None)
                        if idx is not None:
                            info.spawns[idx].handle_kept = True
            elif isinstance(fn.value, ast.Name) and fn.value.id == "self":
                # tracking-helper form: self.m(spawn(...)) keeps the
                # handle IFF m of this class verifiably keeps its
                # parameter (one-level resolution, same keep rules)
                for ai, a in enumerate(node.args):
                    if isinstance(a, ast.Call) \
                            and _is_spawn_call(a) is not None \
                            and _callee_keeps_param(class_node, fn.attr,
                                                    ai):
                        idx = next(
                            (i for i, sp in enumerate(info.spawns)
                             if sp.line == a.lineno), None)
                        if idx is not None:
                            info.spawns[idx].handle_kept = True


# ---------------------------------------------------------------------------
# corpus construction
# ---------------------------------------------------------------------------

def _collect_files(paths) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            files.extend(
                f for f in sorted(path.rglob("*.py"))
                if "__pycache__" not in f.parts
                and not any(part.startswith(".") for part in f.parts))
        elif path.suffix == ".py":
            files.append(path)
        else:
            raise ValueError(f"not a python file or directory: {p}")
    return files


def build_corpus(paths) -> _Corpus:
    modules: list[_ModuleInfo] = []
    parse_failures: list[tuple[str, str]] = []
    for f in _collect_files(paths):
        try:
            source = f.read_text()
            tree = ast.parse(source, filename=str(f))
        except (OSError, SyntaxError) as e:
            parse_failures.append((str(f), f"{type(e).__name__}: {e}"))
            continue
        # __init__.py modules take their package's name, so two
        # connectors' module-global locks cannot collide on the id
        # prefix "__init__" (a collision would invent cross-package
        # order edges — and spurious PWT201 inversions)
        stem = f.parent.name if f.stem == "__init__" else f.stem
        mod = _ModuleInfo(path=str(f), stem=stem,
                          source_lines=source.splitlines())
        _KindCollector(mod).visit(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in ("create_lock", "create_rlock",
                                      "create_condition", "spawn"):
                mod.is_factory_provider = True
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("Lock", "RLock", "Condition") \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "threading":
                mod.raw_locks.append(
                    (node.lineno, f"threading.{node.func.attr}"))
        mod._tree = tree  # type: ignore[attr-defined]
        modules.append(mod)
    corpus = _Corpus(modules, parse_failures)
    # pass 2 needs the cross-module attr-kind index, so it runs after
    # every module's pass 1 completed
    for mod in corpus.modules:
        _analyze_module(corpus, mod)
    return corpus


def _analyze_module(corpus: _Corpus, mod: _ModuleInfo) -> None:
    tree = mod._tree  # type: ignore[attr-defined]
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cls = mod.classes.setdefault(
                node.name, _ClassInfo(node.name, mod.path))
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = _FuncInfo(sub.name,
                                     f"{cls.name}.{sub.name}",
                                     cls.name, mod.path)
                    _FuncAnalyzer(corpus, mod, cls, info).visit(sub)
                    _mark_kept_handles(sub, info, class_node=node)
                    cls.methods[sub.name] = info
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _FuncInfo(node.name, f"{mod.stem}.{node.name}", None,
                             mod.path)
            _FuncAnalyzer(corpus, mod, None, info).visit(node)
            _mark_kept_handles(node, info)
            mod.functions[node.name] = info


# ---------------------------------------------------------------------------
# inventories (consumed by tests, README generation and --json consumers)
# ---------------------------------------------------------------------------

def lock_inventory(corpus: _Corpus) -> list[LockDef]:
    out: list[LockDef] = []
    for mod in corpus.modules:
        for name, kind in mod.global_kinds.items():
            out.append(LockDef(f"{mod.stem}.{name}", kind, mod.path, 0))
        for cls in mod.classes.values():
            for attr, kind in cls.attr_kinds.items():
                out.append(LockDef(f"{cls.name}.{attr}", kind, mod.path, 0))
    return out


def thread_inventory(corpus: _Corpus) -> list[ThreadDef]:
    out: list[ThreadDef] = []
    for mod in corpus.modules:
        funcs = list(mod.functions.values()) + [
            m for c in mod.classes.values() for m in c.methods.values()]
        for fn in funcs:
            out.extend(fn.spawns)
    return out


def lock_order_edges(corpus: _Corpus) -> list[tuple[str, str, str, int]]:
    """(held, acquired, file, line) for every order edge: lexical nesting
    plus one level of ``self``-method call propagation (``with a:
    self.m()`` where ``m`` acquires ``b`` yields a→b)."""
    edges: list[tuple[str, str, str, int]] = []
    for mod in corpus.modules:
        all_funcs = list(mod.functions.values()) + [
            m for c in mod.classes.values() for m in c.methods.values()]
        for fn in all_funcs:
            for held, acq, line in fn.order_edges:
                edges.append((held, acq, fn.file, line))
        for cls in mod.classes.values():
            closure = _class_acquire_closure(cls)
            for fn in cls.methods.values():
                for callee, held, line in fn.self_calls:
                    if not held:
                        continue
                    for acq in closure.get(callee, ()):
                        for h in held:
                            if h != acq:
                                edges.append((h, acq, fn.file, line))
    return edges


def _class_acquire_closure(cls: _ClassInfo) -> dict[str, set]:
    """method -> lock ids it may acquire, transitively through self
    calls (fixpoint over the class's own call graph)."""
    acq = {name: set(fn.acquires) for name, fn in cls.methods.items()}
    changed = True
    while changed:
        changed = False
        for name, fn in cls.methods.items():
            for callee, _held, _line in fn.self_calls:
                extra = acq.get(callee, set()) - acq[name]
                if extra:
                    acq[name] |= extra
                    changed = True
    return acq


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def _diag(code: str, message: str, mod_path: str, line: int,
          function: str, source_lines: list[str]) -> Diagnostic:
    src = source_lines[line - 1].strip() if 0 < line <= len(source_lines) \
        else ""
    return Diagnostic(code=code, message=message,
                      trace=Trace(mod_path, line, function, src))


class ConcurrencyChecker:
    """Runs every PWT2xx check over a parsed corpus."""

    def __init__(self, corpus: _Corpus):
        self.corpus = corpus
        self.diagnostics: list[Diagnostic] = []
        self._sources = {m.path: m.source_lines for m in corpus.modules}

    def _report(self, code: str, message: str, file: str, line: int,
                function: str = "") -> None:
        lines = self._sources.get(file, [])
        if _waived(lines, line, code):
            return
        self.diagnostics.append(
            _diag(code, message, file, line, function, lines))

    def run(self) -> list[Diagnostic]:
        for path, err in self.corpus.parse_failures:
            # unparseable source cannot be certified clean — an error,
            # so the directory gate fails instead of quietly shrinking
            self.diagnostics.append(Diagnostic(
                code="PWT000",
                message=f"cannot analyze {path}: {err}"))
        self.check_lock_order()       # PWT201
        self.check_unguarded_writes()  # PWT202
        self.check_held_across_blocking()  # PWT203
        self.check_dropped_daemons()  # PWT204
        self.check_cond_waits()       # PWT205
        self.check_sleep_polling()    # PWT206
        self.check_raw_threads()      # PWT207
        self.check_notify_outside()   # PWT208
        return self.diagnostics

    # -- PWT201 ------------------------------------------------------------
    def check_lock_order(self) -> None:
        edges = lock_order_edges(self.corpus)
        adj: dict[str, set] = {}
        where: dict[tuple[str, str], tuple[str, int]] = {}
        for held, acq, file, line in edges:
            adj.setdefault(held, set()).add(acq)
            where.setdefault((held, acq), (file, line))
        reported: set[frozenset] = set()
        for (a, b), (file, line) in sorted(where.items(),
                                           key=lambda kv: kv[1]):
            if frozenset((a, b)) in reported:
                continue
            if self._reaches(adj, b, a):
                reported.add(frozenset((a, b)))
                rev = where.get((b, a))
                rev_s = f" (reverse order at {rev[0]}:{rev[1]})" \
                    if rev else ""
                self._report(
                    "PWT201",
                    f"lock-order inversion: {a!r} is acquired before "
                    f"{b!r} here, but the graph also orders {b!r} before "
                    f"{a!r}{rev_s} — two threads taking the two paths "
                    f"concurrently deadlock",
                    file, line)

    @staticmethod
    def _reaches(adj: dict, src: str, dst: str) -> bool:
        """Reachability src → dst in the order graph. The length-2 case
        (a direct reverse edge) is a cycle like any other."""
        stack = [src]
        seen = set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False

    # -- PWT202 ------------------------------------------------------------
    def check_unguarded_writes(self) -> None:
        for mod in self.corpus.modules:
            for cls in mod.classes.values():
                if not cls.thread_targets:
                    continue
                self._check_class_writes(mod, cls)

    def _check_class_writes(self, mod: _ModuleInfo,
                            cls: _ClassInfo) -> None:
        reach = _reachable_methods(cls)
        roots: dict[str, set] = {}
        for target in cls.thread_targets:
            roots[f"thread:{target}"] = reach.get(target, {target})
        thread_methods = set().union(*roots.values()) if roots else set()
        # the implicit main root: every method not reachable from a
        # thread target (constructor excluded: it runs before threads)
        main_methods = {name for name in cls.methods
                        if name not in thread_methods
                        and name != "__init__"}
        roots["main"] = main_methods
        guaranteed = _guaranteed_held(cls, roots)
        # attr -> root -> list[(write, guards)]
        per_attr: dict[str, dict[str, list]] = {}
        for root, methods in roots.items():
            for m in methods:
                fn = cls.methods.get(m)
                if fn is None:
                    continue
                for w in fn.writes:
                    if cls.attr_kinds.get(w.attr) in (
                            "lock", "rlock", "condition", "event"):
                        continue
                    guards = w.guards | guaranteed.get((root, m),
                                                      frozenset())
                    per_attr.setdefault(w.attr, {}).setdefault(
                        root, []).append((w, guards))
        for attr, by_root in per_attr.items():
            if len(by_root) < 2:
                continue
            # at least one genuine thread root must write it
            if not any(r.startswith("thread:") for r in by_root):
                continue
            common = None
            for _root, writes in by_root.items():
                for _w, guards in writes:
                    common = guards if common is None else common & guards
            if common:
                continue
            w = next(iter(by_root.values()))[0][0]
            rootnames = sorted(by_root)
            self._report(
                "PWT202",
                f"attribute {cls.name}.{attr} is written from "
                f"{len(by_root)} thread roots ({', '.join(rootnames)}) "
                f"with no common lock guard — interleaved writes race "
                f"(guard them with one lock, or make the hand-off an "
                f"Event)",
                cls.file, w.line, w.method)

    # -- PWT203 ------------------------------------------------------------
    def check_held_across_blocking(self) -> None:
        for mod in self.corpus.modules:
            for fn in _all_funcs(mod):
                for lock_id, desc, line in fn.blocking_under_lock:
                    self._report(
                        "PWT203",
                        f"{fn.qualname} holds {lock_id!r} across blocking "
                        f"call {desc}() — every thread contending on the "
                        f"lock waits out the call (move it outside the "
                        f"critical section)",
                        fn.file, line, fn.name)

    # -- PWT204 ------------------------------------------------------------
    def check_dropped_daemons(self) -> None:
        for mod in self.corpus.modules:
            for fn in _all_funcs(mod):
                for sp in fn.spawns:
                    if sp.daemon and not sp.handle_kept:
                        tgt = sp.target or "<unresolved target>"
                        self._report(
                            "PWT204",
                            f"daemon thread (target {tgt}) spawned in "
                            f"{fn.qualname} with its handle dropped: no "
                            f"stop/join path exists, so shutdown cannot "
                            f"wait it out and it dies mid-work at "
                            f"interpreter exit",
                            fn.file, sp.line, fn.name)

    # -- PWT205 ------------------------------------------------------------
    def check_cond_waits(self) -> None:
        for mod in self.corpus.modules:
            for fn in _all_funcs(mod):
                for cond_id, line, ok in fn.cond_waits:
                    if ok:
                        continue
                    self._report(
                        "PWT205",
                        f"{fn.qualname} calls {cond_id}.wait() outside a "
                        f"predicate re-check loop: spurious wake-ups and "
                        f"missed notifies break the invariant (use "
                        f"`while not pred: cv.wait()` or cv.wait_for)",
                        fn.file, line, fn.name)

    # -- PWT206 ------------------------------------------------------------
    def check_sleep_polling(self) -> None:
        for mod in self.corpus.modules:
            for fn in _all_funcs(mod):
                for line, event_id in fn.poll_sleeps:
                    self._report(
                        "PWT206",
                        f"{fn.qualname} sleep-polls in a loop while an "
                        f"Event ({event_id}) exists: "
                        f"`{event_id.split('.')[-1]}.wait(timeout)` wakes "
                        f"immediately on the state change instead of up "
                        f"to one poll interval late",
                        fn.file, line, fn.name)

    # -- PWT207 ------------------------------------------------------------
    def check_raw_threads(self) -> None:
        for mod in self.corpus.modules:
            for fn in _all_funcs(mod):
                for line in fn.raw_threads:
                    self._report(
                        "PWT207",
                        f"{fn.qualname} constructs threading.Thread "
                        f"directly: use the engine thread factory "
                        f"(pathway_tpu.engine.threads.spawn) so the "
                        f"thread gets excepthook coverage, inventory "
                        f"registration and uniform naming",
                        fn.file, line, fn.name)
            if mod.is_factory_provider:
                continue  # the factory module constructs the primitives
            for line, what in mod.raw_locks:
                self._report(
                    "PWT207",
                    f"{mod.stem} constructs {what} directly: use the "
                    f"engine lock factory (pathway_tpu.engine.locking "
                    f"create_lock/create_rlock/create_condition) so the "
                    f"lock is named, inventoried, and sanitizable under "
                    f"PATHWAY_LOCK_SANITIZER",
                    mod.path, line)

    # -- PWT208 ------------------------------------------------------------
    def check_notify_outside(self) -> None:
        for mod in self.corpus.modules:
            for fn in _all_funcs(mod):
                for cond_id, line, inside in fn.notifies:
                    if inside:
                        continue
                    self._report(
                        "PWT208",
                        f"{fn.qualname} notifies {cond_id} without "
                        f"holding it: threading.Condition.notify raises "
                        f"RuntimeError('cannot notify on un-acquired "
                        f"lock') at runtime — wrap it in `with "
                        f"{cond_id.split('.')[-1]}:`",
                        fn.file, line, fn.name)


def _all_funcs(mod: _ModuleInfo):
    yield from mod.functions.values()
    for cls in mod.classes.values():
        yield from cls.methods.values()


def _reachable_methods(cls: _ClassInfo) -> dict[str, set]:
    """method -> set of class methods reachable from it via self calls
    (inclusive)."""
    out: dict[str, set] = {}
    for start in cls.methods:
        seen = {start}
        stack = [start]
        while stack:
            m = stack.pop()
            fn = cls.methods.get(m)
            if fn is None:
                continue
            for callee, _h, _l in fn.self_calls:
                if callee in cls.methods and callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
        out[start] = seen
    return out


def _guaranteed_held(cls: _ClassInfo,
                     roots: dict[str, set]) -> dict[tuple, frozenset]:
    """(root, method) -> lock ids guaranteed held whenever ``method`` runs
    under ``root``: the intersection over all call paths from the root of
    the locks held at each call site. Root entry points hold nothing."""
    out: dict[tuple, frozenset] = {}
    for root, methods in roots.items():
        if root.startswith("thread:"):
            entries = {root.split(":", 1)[1]}
        else:
            entries = set(methods)
        held: dict[str, frozenset | None] = {m: None for m in methods}
        for e in entries:
            held[e] = frozenset()
        changed = True
        while changed:
            changed = False
            for m in methods:
                fn = cls.methods.get(m)
                if fn is None or held.get(m) is None:
                    continue
                base = held[m]
                for callee, at_call, _line in fn.self_calls:
                    if callee not in held:
                        continue
                    eff = frozenset(at_call) | base
                    cur = held[callee]
                    new = eff if cur is None else cur & eff
                    if new != cur:
                        held[callee] = new
                        changed = True
        for m in methods:
            out[(root, m)] = held.get(m) or frozenset()
    return out


# ---------------------------------------------------------------------------
# front door
# ---------------------------------------------------------------------------

def check_concurrency(paths, *, corpus: _Corpus | None = None
                      ) -> list[Diagnostic]:
    """Run the PWT2xx family over ``paths`` (files or directories of
    Python source). Returns diagnostics; nothing is imported or
    executed. Pass a prebuilt ``corpus`` (from :func:`build_corpus`) to
    share the parse with :func:`concurrency_inventory`."""
    return ConcurrencyChecker(corpus or build_corpus(paths)).run()


def concurrency_inventory(paths, *, corpus: _Corpus | None = None) -> dict:
    """The thread/lock inventories and lock-order graph as plain data —
    the machine-readable twin of README's "Concurrency model" tables."""
    corpus = corpus or build_corpus(paths)
    return {
        "threads": [vars(t).copy() for t in thread_inventory(corpus)],
        "locks": [vars(lk) for lk in lock_inventory(corpus)],
        "order_edges": sorted({(a, b) for a, b, _f, _l
                               in lock_order_edges(corpus)}),
    }
