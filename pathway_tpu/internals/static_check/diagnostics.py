"""Diagnostic objects for the static pipeline analyzer.

Each finding is a :class:`Diagnostic` with a stable code (``PWT001``…),
a severity, a human message, and — whenever the offending operator captured
one — the user stack frame from the plan's build-time trace
(internals/trace.py), so a diagnostic points at the user's line, exactly
like runtime operator errors do.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from pathway_tpu.internals.trace import Trace


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    def __str__(self) -> str:  # "error" in rendered diagnostics
        return self.value


#: code -> (default severity, one-line summary). The single source of truth
#: for what the analyzer can emit; README's "Static checks" section mirrors it.
CODES: dict[str, tuple[Severity, str]] = {
    "PWT000": (Severity.ERROR,
               "pipeline script failed to import / collect"),
    "PWT001": (Severity.ERROR,
               "binary operation on incompatible column dtypes"),
    "PWT002": (Severity.ERROR,
               "cast/convert between incompatible dtypes"),
    "PWT003": (Severity.ERROR,
               "join/groupby key columns have incompatible dtypes"),
    "PWT004": (Severity.WARNING,
               "dead dataflow: table computed but never reaches a sink"),
    "PWT005": (Severity.WARNING,
               "streaming source never reaches an output binder"),
    "PWT006": (Severity.WARNING,
               "non-deterministic or async UDF feeds a persisted pipeline"),
    "PWT007": (Severity.ERROR,
               "universe mismatch the solver would reject at runtime"),
    "PWT008": (Severity.WARNING,
               "get()/ix default dtype silently widens the column"),
    "PWT009": (Severity.WARNING,
               "sink schema incompatible with the connector's format"),
    "PWT010": (Severity.INFO,
               "redundant cast: expression already has the target dtype"),
    "PWT011": (Severity.ERROR,
               "ix key expression is not a pointer type"),
    "PWT012": (Severity.WARNING,
               "streaming source with max_retries=0 under "
               "terminate_on_error=False: a crash silently drops the "
               "source"),
    "PWT013": (Severity.WARNING,
               "SLO target configured (PATHWAY_SLO_E2E_MS) but the "
               "pipeline serves with QoS disabled: latency is measured "
               "but nothing acts on it"),
    # -- PWT1xx: sharding / placement (static_check/shard_check.py) --------
    "PWT101": (Severity.ERROR,
               "mesh axis sizes do not fit the device count"),
    "PWT102": (Severity.ERROR,
               "sharded leading dimension not divisible by the mesh axis "
               "(silent replication/padding)"),
    "PWT103": (Severity.ERROR,
               "shard_map in/out specs inconsistent with operand rank or "
               "mesh axes"),
    "PWT104": (Severity.WARNING,
               "operands placed on different meshes: every batch pays an "
               "implicit cross-topology gather"),
    "PWT105": (Severity.WARNING,
               "host-device sync point inside a per-batch path"),
    "PWT106": (Severity.ERROR,
               "head-parallel attention: heads not divisible by the axis "
               "size"),
    "PWT107": (Severity.INFO,
               "model axis configured but nothing in the pipeline is "
               "model-parallel (silent weight replication)"),
    "PWT108": (Severity.WARNING,
               "fused donated ingest slab has no reserved capacity: first "
               "growth silently drops the fused path"),
    "PWT109": (Severity.WARNING,
               "host-only UDF on a streaming hot path"),
    "PWT110": (Severity.INFO,
               "jit-traceable UDF dispatched row-by-row: auto-jitted at "
               "runtime when PATHWAY_AUTO_JIT=1, else a batch=True "
               "candidate"),
    "PWT111": (Severity.WARNING,
               "paged store reservation/tenant quota not page-aligned, or "
               "tenant quotas sum past device HBM"),
    # -- PWT2xx: concurrency (static_check/concurrency_check.py) -----------
    # Source-level AST analysis over the multi-threaded engine itself
    # (engine/, io/, parallel/), not the plan DAG: thread inventory, lock
    # inventory, lock-order graph. Runtime twin: PATHWAY_LOCK_SANITIZER
    # (engine/locking.py).
    "PWT201": (Severity.ERROR,
               "lock-order inversion: a cycle in the global lock "
               "acquisition-order graph (some interleaving deadlocks)"),
    "PWT202": (Severity.ERROR,
               "attribute written from two or more thread roots with no "
               "common lock guard"),
    "PWT203": (Severity.WARNING,
               "lock held across a known-blocking call (fsync, socket "
               "send/recv, bridge submit, device dispatch)"),
    "PWT204": (Severity.WARNING,
               "daemon thread spawned with no stop/join path (its handle "
               "is dropped; nothing can ever wait it out)"),
    "PWT205": (Severity.ERROR,
               "Condition.wait outside a predicate re-check loop (misses "
               "spurious wake-ups and missed-notify races)"),
    "PWT206": (Severity.WARNING,
               "sleep-polling loop where an Event exists (use Event.wait: "
               "immediate wake-up, no poll latency)"),
    "PWT207": (Severity.WARNING,
               "thread or lock primitive constructed bare instead of "
               "through the engine factories (threads.py spawn / "
               "locking.py create_*: excepthook, inventory and sanitizer "
               "coverage)"),
    "PWT208": (Severity.ERROR,
               "Condition.notify/notify_all outside the condition's "
               "`with` block (raises RuntimeError at runtime)"),
    # -- PWT3xx: durability / crash-recovery (static_check/
    # durability_check.py). Source-level AST analysis over the
    # persistence plane (engine/, io/): snapshot coverage, atomic-write
    # discipline, fault-point coverage, restore-path safety. Runtime
    # twin: PATHWAY_SNAPSHOT_SANITIZER (engine/snapshot_sanitizer.py).
    "PWT301": (Severity.WARNING,
               "stateful operator mutates state on step/drain paths but "
               "defines no snapshot_state/restore_state pair (silent "
               "degradation to full-WAL replay on recovery)"),
    "PWT302": (Severity.ERROR,
               "capture/restore asymmetry: a snapshot state key captured "
               "but never restored, or restored but never captured"),
    "PWT303": (Severity.ERROR,
               "hash()/id()/fingerprint-keyed container in snapshotted "
               "state restored without a stable re-key (keys from the "
               "writer process are meaningless in the restorer)"),
    "PWT304": (Severity.ERROR,
               "write to a persistence-root-derived path bypassing the "
               "atomic tmp+fsync+rename discipline (a crash mid-write "
               "leaves a torn file where a checkpoint should be)"),
    "PWT305": (Severity.WARNING,
               "blocking persistence I/O (fsync/truncate/put) with no "
               "named fault point in the enclosing function — the crash "
               "edge is not injectable by testing/faults.py"),
    "PWT306": (Severity.ERROR,
               "unrestricted pickle.load/loads/Unpickler on a restore "
               "path (use persistence._safe_loads: arbitrary-code "
               "execution from a corrupt or hostile snapshot)"),
    "PWT307": (Severity.ERROR,
               "Session.drain outside the atomic seal_drain helper on a "
               "persisted streaming path (drained rows can be lost "
               "between drain and seal on crash)"),
    "PWT308": (Severity.WARNING,
               "nondeterminism source (time.time, random, os.urandom, "
               "uuid4) feeds snapshotted state — restored replicas "
               "diverge from the writer"),
    # -- PWT4xx: device-path perf discipline (static_check/
    # perf_check.py). Source-level AST analysis over the serving hot
    # path (engine/, ops/, models/, parallel/): recompile zoos, hidden
    # host-device syncs, per-row dispatch, residency and donation
    # discipline. Runtime twin: PATHWAY_DEVICE_SANITIZER
    # (engine/device_sanitizer.py).
    "PWT401": (Severity.ERROR,
               "jitted callable dispatched with an unbucketed data-"
               "dependent shape (every distinct length compiles a fresh "
               "executable — a recompile zoo on the serving path)"),
    "PWT402": (Severity.ERROR,
               "host-device sync point (.item()/.tolist()/int()/float()/"
               "np.asarray/bare block_until_ready) on a per-batch path "
               "outside instrumentation code"),
    "PWT403": (Severity.WARNING,
               "per-row device dispatch inside a Python loop where a "
               "batched/vmapped kernel exists in the same module"),
    "PWT404": (Severity.WARNING,
               "implicit host→device transfer per tick: numpy operand "
               "fed to a jitted callable with no device residency or "
               "device_put upstream"),
    "PWT405": (Severity.ERROR,
               "float64/weak-type promotion reaching kernel code (TPUs "
               "emulate f64 at ~1/10 throughput; one stray dtype "
               "contaminates every downstream op)"),
    "PWT406": (Severity.ERROR,
               "donated buffer read after donation (XLA may have reused "
               "the memory: garbage values or a crash, backend-"
               "dependent)"),
    "PWT407": (Severity.WARNING,
               "jitted serving entry point absent from pw.warmup's "
               "bucket registry (the cold compile lands on the first "
               "real query instead of warmup)"),
    "PWT408": (Severity.WARNING,
               "blocking host I/O (file/socket/log flush) inside a "
               "device-leg function (stalls the dispatch pipeline for "
               "host I/O time)"),
}


@dataclass(frozen=True)
class Diagnostic:
    """One static-analysis finding."""

    code: str
    message: str
    severity: Severity | None = None
    trace: Trace | None = None
    table: str | None = None
    # secondary provenance (e.g. the other table of a universe mismatch)
    related: tuple[Trace, ...] = field(default=())

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code!r}")
        if self.severity is None:
            object.__setattr__(self, "severity", CODES[self.code][0])

    @property
    def is_error(self) -> bool:
        return self.severity is Severity.ERROR

    def to_dict(self) -> dict:
        """Flat machine-readable form (CLI ``--json`` / CI annotations)."""
        return {
            "code": self.code,
            "severity": str(self.severity),
            "message": self.message,
            "table": self.table,
            "file": self.trace.file_name if self.trace else None,
            "line": self.trace.line_number if self.trace else None,
        }

    def __str__(self) -> str:
        where = f" [{self.table}]" if self.table else ""
        out = f"{self.code} {self.severity}{where}: {self.message}"
        if self.trace is not None:
            out += f"\n{self.trace}"
        for t in self.related:
            out += f"\n  related:\n{t}"
        return out


class StaticCheckError(RuntimeError):
    """Raised by ``pw.run(static_check='error')`` when the analyzer finds
    error-severity diagnostics. Carries the full diagnostic list."""

    def __init__(self, diagnostics: list[Diagnostic]):
        self.diagnostics = diagnostics
        errors = [d for d in diagnostics if d.is_error]
        lines = "\n\n".join(str(d) for d in errors)
        super().__init__(
            f"static check failed with {len(errors)} error(s):\n{lines}")


def render(diagnostics: list[Diagnostic]) -> str:
    """Multi-line human rendering, errors first."""
    order = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}
    ranked = sorted(diagnostics, key=lambda d: order[d.severity])
    return "\n\n".join(str(d) for d in ranked)
