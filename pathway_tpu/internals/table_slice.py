"""TableSlice (reference: python/pathway/internals/table_slice.py)."""

from __future__ import annotations

from pathway_tpu.internals import expression as ex


class TableSlice:
    def __init__(self, table, mapping: dict):
        self._table = table
        self._mapping = dict(mapping)

    def __iter__(self):
        return iter(self._mapping.values())

    def keys(self):
        return list(self._mapping.keys())

    def __getitem__(self, name):
        if isinstance(name, ex.ColumnReference):
            name = name.name
        return self._mapping[name]

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._mapping:
            return self._mapping[name]
        raise AttributeError(name)

    def without(self, *cols):
        names = {c.name if isinstance(c, ex.ColumnReference) else c for c in cols}
        return TableSlice(
            self._table,
            {k: v for k, v in self._mapping.items() if k not in names},
        )

    def rename(self, mapping: dict):
        mapping = {
            (k.name if isinstance(k, ex.ColumnReference) else k):
            (v.name if isinstance(v, ex.ColumnReference) else v)
            for k, v in mapping.items()
        }
        return TableSlice(
            self._table,
            {mapping.get(k, k): v for k, v in self._mapping.items()},
        )

    def with_prefix(self, prefix: str):
        return self.rename({k: prefix + k for k in self._mapping})

    def with_suffix(self, suffix: str):
        return self.rename({k: k + suffix for k in self._mapping})

    @property
    def slice(self):
        return self

    def _to_column_mapping(self):
        return dict(self._mapping)

    def __repr__(self):
        return f"<TableSlice {list(self._mapping.keys())}>"
