"""OpenTelemetry traces + metrics for pipeline runs.

Reference: src/engine/telemetry.rs:196-366 (OTLP tracer/meter providers,
periodic process metrics — memory, CPU — and operator latency gauges) plus
the Python-side graph-build spans (internals/graph_runner/telemetry.py).

This build instruments through the **OTel API** (in-image): spans and
gauges are real instrumentation objects that become live the moment an
OTel SDK is configured in the process (the standard API/SDK split). When
``endpoint`` is passed and the SDK + OTLP exporter packages are
importable, ``Config.create`` wires a full pipeline provider itself;
otherwise instrumentation degrades to the API's no-op implementations —
never an import error (the reference gates the same way on its
license/monitoring-server config, telemetry.rs:196-264).
"""

from __future__ import annotations

import contextlib
import os
import time
from dataclasses import dataclass
from typing import Any


@dataclass
class Config:
    """Telemetry configuration (reference: telemetry::Config::create)."""

    telemetry_enabled: bool = False
    endpoint: str | None = None
    service_name: str = "pathway-tpu"
    run_id: str | None = None

    @classmethod
    def create(cls, *, telemetry_enabled: bool = False,
               endpoint: str | None = None,
               service_name: str = "pathway-tpu",
               run_id: str | None = None) -> "Config":
        if endpoint is None:
            from pathway_tpu.internals.compat import (
                get_monitoring_endpoint,
            )

            endpoint = get_monitoring_endpoint()
        endpoint = endpoint or os.environ.get(
            "PATHWAY_TELEMETRY_ENDPOINT") or None
        if endpoint:
            telemetry_enabled = True
        return cls(telemetry_enabled=telemetry_enabled, endpoint=endpoint,
                   service_name=service_name,
                   run_id=run_id or os.environ.get("PATHWAY_RUN_ID"))


class Telemetry:
    """Tracer + meter bundle bound to one pipeline run."""

    def __init__(self, config: Config):
        self.config = config
        self._provider = None
        self._meter_provider = None
        try:
            from opentelemetry import metrics, trace
        except ImportError:  # pragma: no cover - otel api is in-image
            self.tracer = None
            self.meter = None
            return
        if config.endpoint and self._try_setup_sdk(config):
            # providers stay LOCAL to this run (never set as the process
            # globals): a second pw.run() builds fresh ones, so per-run
            # shutdown cannot orphan later runs on a dead global provider
            self.tracer = self._provider.get_tracer(config.service_name)
            self.meter = self._meter_provider.get_meter(config.service_name)
        else:
            self.tracer = trace.get_tracer(config.service_name)
            self.meter = metrics.get_meter(config.service_name)
        self._instruments: dict[str, Any] = {}

    def _try_setup_sdk(self, config: Config) -> bool:
        """Build OTLP providers when the SDK is importable (reference:
        tracer/meter provider construction, telemetry.rs:85-130)."""
        try:
            from opentelemetry.exporter.otlp.proto.grpc.metric_exporter import (  # noqa: E501
                OTLPMetricExporter)
            from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (  # noqa: E501
                OTLPSpanExporter)
            from opentelemetry.sdk.metrics import MeterProvider
            from opentelemetry.sdk.metrics.export import (
                PeriodicExportingMetricReader)
            from opentelemetry.sdk.resources import Resource
            from opentelemetry.sdk.trace import TracerProvider
            from opentelemetry.sdk.trace.export import BatchSpanProcessor

            resource = Resource.create({
                "service.name": self.config.service_name,
                "pathway.run_id": self.config.run_id or "",
            })
            provider = TracerProvider(resource=resource)
            provider.add_span_processor(BatchSpanProcessor(
                OTLPSpanExporter(endpoint=config.endpoint)))
            self._provider = provider
            reader = PeriodicExportingMetricReader(
                OTLPMetricExporter(endpoint=config.endpoint))
            self._meter_provider = MeterProvider(resource=resource,
                                                 metric_readers=[reader])
            return True
        except ImportError:
            import logging

            logging.getLogger(__name__).warning(
                "telemetry endpoint %s configured but the OTel SDK/OTLP "
                "exporter packages are not installed — instrumentation "
                "stays no-op", config.endpoint)
            return False

    # -- spans -----------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        if self.tracer is None:
            yield None
            return
        with self.tracer.start_as_current_span(name) as sp:
            for k, v in attrs.items():
                try:
                    sp.set_attribute(k, v)
                except Exception:
                    pass
            yield sp

    # -- metrics ---------------------------------------------------------
    def register_scheduler_gauges(self, scheduler, graph) -> None:
        """Observable gauges over the scheduler's per-operator stats —
        the analogue of the reference's input/output latency gauges
        (telemetry.rs:312-366) plus process memory/CPU.

        The OTel API has no instrument unregistration, so callbacks read
        through ``self._gauge_state``, which ``shutdown()`` clears — after
        the run they return nothing and hold no reference to the dead
        scheduler/graph (relevant in global-SDK mode, where the meter
        outlives the run)."""
        if self.meter is None:
            return
        self._gauge_state = {"scheduler": scheduler, "graph": graph}
        state = self._gauge_state

        def observe_latency(options):
            from opentelemetry.metrics import Observation

            sched, g = state.get("scheduler"), state.get("graph")
            if sched is None:
                return []
            out = []
            for node in g.nodes:
                st = sched.stats.get(node.id)
                if st:
                    out.append(Observation(
                        st.get("latency_ms", 0.0),
                        {"operator": node.name or str(node.id)}))
            return out

        def observe_counts(kind):
            def observe(options):
                from opentelemetry.metrics import Observation

                sched, g = state.get("scheduler"), state.get("graph")
                if sched is None:
                    return []
                return [
                    Observation(sched.stats[n.id][kind],
                                {"operator": n.name or str(n.id)})
                    for n in g.nodes if n.id in sched.stats
                ]

            return observe

        def observe_memory(options):
            from opentelemetry.metrics import Observation

            import resource as _res

            rss_kb = _res.getrusage(_res.RUSAGE_SELF).ru_maxrss
            return [Observation(rss_kb * 1024)]

        def observe_cpu(options):
            from opentelemetry.metrics import Observation

            return [Observation(time.process_time())]

        m = self.meter
        self._instruments["latency"] = m.create_observable_gauge(
            "pathway.operator.latency_ms", callbacks=[observe_latency])
        self._instruments["insertions"] = m.create_observable_counter(
            "pathway.operator.insertions",
            callbacks=[observe_counts("insertions")])
        self._instruments["retractions"] = m.create_observable_counter(
            "pathway.operator.retractions",
            callbacks=[observe_counts("retractions")])
        self._instruments["memory"] = m.create_observable_gauge(
            "pathway.process.memory_bytes", callbacks=[observe_memory])
        self._instruments["cpu"] = m.create_observable_gauge(
            "pathway.process.cpu_seconds", callbacks=[observe_cpu])

    def shutdown(self) -> None:
        if getattr(self, "_gauge_state", None):
            self._gauge_state.clear()  # disarm global-meter callbacks
        for p in (self._provider, self._meter_provider):
            if p is not None:
                try:
                    p.shutdown()
                except Exception:
                    pass
