"""Static type lattice for schemas and expressions.

TPU-native rebuild of the reference's dtype system
(reference: python/pathway/internals/dtype.py, 919 LoC). We keep the same
user-visible concepts — a lattice of column dtypes with Optional/Tuple/Array
parametric types, wrapping of Python annotations, and least-common-ancestor
computation used by `if_else`, `concat` and friends — but the representation
is geared towards columnar/XLA lowering: every dtype knows its numpy storage
dtype so the engine can keep numeric columns as dense arrays (MXU/VPU
friendly) and only falls back to object columns for variant data.
"""

from __future__ import annotations

import datetime
import typing
from abc import ABC
from typing import Any, Callable, Mapping, Optional as TOptional

import numpy as np


class DType(ABC):
    """Base of all column dtypes."""

    _name: str = "DType"

    @property
    def typehint(self) -> Any:
        return Any

    # numpy storage dtype for engine columns ("object" = host boxed values)
    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(object)

    @property
    def is_numeric(self) -> bool:
        return self.np_dtype != np.dtype(object) or self is BOOL

    def is_value_compatible(self, value: Any) -> bool:
        return True

    def __repr__(self) -> str:
        return self._name

    def __hash__(self):
        return hash(repr(self))

    def __eq__(self, other):
        return isinstance(other, DType) and repr(self) == repr(other)

    def equivalent_to(self, other: "DType") -> bool:
        return dtype_issubclass(self, other) and dtype_issubclass(other, self)


class _SimpleDType(DType):
    def __init__(self, name: str, typehint: Any, np_dtype: Any,
                 check: TOptional[Callable[[Any], bool]] = None):
        self._name = name
        self._typehint = typehint
        self._np = np.dtype(np_dtype)
        self._check = check

    @property
    def typehint(self) -> Any:
        return self._typehint

    @property
    def np_dtype(self) -> np.dtype:
        return self._np

    def is_value_compatible(self, value: Any) -> bool:
        if self._check is not None:
            return self._check(value)
        return True


class Pointer(DType):
    """128-bit row id. Parametrized variant ``Pointer[S]`` not tracked yet."""

    _name = "Pointer"

    def __init__(self, *args):
        self._args = args
        if args:
            self._name = f"Pointer[{', '.join(repr(a) for a in args)}]"

    @property
    def typehint(self):
        from pathway_tpu.internals.keys import Pointer as PointerValue

        return PointerValue

    def is_value_compatible(self, value):
        from pathway_tpu.internals.keys import Pointer as PointerValue

        return isinstance(value, PointerValue)


ANY = _SimpleDType("ANY", Any, object)
NONE = _SimpleDType("NONE", type(None), object, lambda v: v is None)
BOOL = _SimpleDType("bool", bool, np.bool_, lambda v: isinstance(v, (bool, np.bool_)))
INT = _SimpleDType(
    "int", int, np.int64,
    lambda v: isinstance(v, (int, np.integer)) and not isinstance(v, bool),
)
FLOAT = _SimpleDType(
    "float", float, np.float64,
    lambda v: isinstance(v, (int, float, np.integer, np.floating))
    and not isinstance(v, bool),
)
STR = _SimpleDType("str", str, object, lambda v: isinstance(v, str))
BYTES = _SimpleDType("bytes", bytes, object, lambda v: isinstance(v, bytes))
POINTER = Pointer()
DATE_TIME_NAIVE = _SimpleDType(
    "DateTimeNaive", "DateTimeNaive", "datetime64[ns]",
    lambda v: isinstance(v, datetime.datetime) or isinstance(v, np.datetime64),
)
DATE_TIME_UTC = _SimpleDType(
    "DateTimeUtc", "DateTimeUtc", object,
    lambda v: isinstance(v, datetime.datetime) or isinstance(v, np.datetime64),
)
DURATION = _SimpleDType(
    "Duration", "Duration", "timedelta64[ns]",
    lambda v: isinstance(v, datetime.timedelta) or isinstance(v, np.timedelta64),
)
ERROR = _SimpleDType("ERROR", "Error", object)


class _Json(DType):
    _name = "Json"

    @property
    def typehint(self):
        from pathway_tpu.internals.json import Json as JsonValue

        return JsonValue


JSON = _Json()


class Optional(DType):
    """``Optional(T)`` — T or None.  Flattens nested optionals."""

    def __new__(cls, wrapped: DType):
        if isinstance(wrapped, Optional) or wrapped in (NONE, ANY):
            return wrapped
        self = super().__new__(cls)
        self.wrapped = wrapped
        self._name = f"Optional({wrapped!r})"
        return self

    @property
    def typehint(self):
        return typing.Optional[self.wrapped.typehint]

    @property
    def np_dtype(self) -> np.dtype:
        # floats can hold NaN; everything else degrades to object when nullable
        if self.wrapped is FLOAT:
            return np.dtype(np.float64)
        return np.dtype(object)

    def is_value_compatible(self, value):
        return value is None or self.wrapped.is_value_compatible(value)


class Tuple(DType):
    """Heterogeneous fixed-arity tuple ``Tuple(T1, T2, …)``."""

    def __init__(self, *args: DType):
        self.args = tuple(args)
        self._name = f"Tuple({', '.join(repr(a) for a in args)})"

    @property
    def typehint(self):
        return typing.Tuple[tuple(a.typehint for a in self.args)]

    def is_value_compatible(self, value):
        return isinstance(value, tuple) and len(value) == len(self.args) and all(
            a.is_value_compatible(v) for a, v in zip(self.args, value)
        )


class List(DType):
    """Homogeneous variable-length tuple ``List(T)``."""

    def __init__(self, arg: DType):
        self.wrapped = arg
        self._name = f"List({arg!r})"

    @property
    def typehint(self):
        return typing.Tuple[self.wrapped.typehint, ...]

    def is_value_compatible(self, value):
        return isinstance(value, (tuple, list))


ANY_TUPLE = List(ANY)


class Array(DType):
    """N-dim numeric array ``Array(n_dim, wrapped)`` (ndarray-valued cells).

    These are the cells the engine promotes to stacked device tensors
    (e.g. embedding columns feeding the Pallas KNN kernel).
    """

    def __init__(self, n_dim: TOptional[int] = None, wrapped: DType = ANY):
        self.n_dim = n_dim
        self.wrapped = wrapped
        self._name = f"Array({n_dim}, {wrapped!r})"

    @property
    def typehint(self):
        return np.ndarray

    def is_value_compatible(self, value):
        return isinstance(value, np.ndarray) or _np_like(value)


ANY_ARRAY = Array(None, ANY)
INT_ARRAY = Array(None, INT)
FLOAT_ARRAY = Array(None, FLOAT)


def _np_like(value):
    try:
        import jax

        return isinstance(value, jax.Array)
    except Exception:  # pragma: no cover
        return False


class Callable_(DType):
    def __init__(self, arg_types=..., return_type: DType = ANY):
        self.arg_types = arg_types
        self.return_type = return_type
        self._name = f"Callable({arg_types}, {return_type!r})"

    @property
    def typehint(self):
        return typing.Callable


class Future(DType):
    """Result of a fully-async UDF not yet awaited (reference: dtype.Future)."""

    def __new__(cls, wrapped: DType):
        if isinstance(wrapped, Future):
            return wrapped
        self = super().__new__(cls)
        self.wrapped = wrapped
        self._name = f"Future({wrapped!r})"
        return self

    @property
    def typehint(self):
        return typing.Awaitable[self.wrapped.typehint]


_SIMPLE_WRAPS: Mapping[Any, DType] = {}


def _build_wrap_table():
    global _SIMPLE_WRAPS
    from pathway_tpu.internals.keys import Pointer as PointerValue
    from pathway_tpu.internals.json import Json as JsonValue

    _SIMPLE_WRAPS = {
        Any: ANY,
        ...: ANY,
        type(None): NONE,
        None: NONE,
        bool: BOOL,
        int: INT,
        np.int64: INT,
        np.int32: INT,
        float: FLOAT,
        np.float64: FLOAT,
        np.float32: FLOAT,
        str: STR,
        bytes: BYTES,
        PointerValue: POINTER,
        JsonValue: JSON,
        dict: JSON,
        datetime.datetime: DATE_TIME_NAIVE,
        datetime.timedelta: DURATION,
        np.ndarray: ANY_ARRAY,
    }


def wrap(input_type: Any) -> DType:
    """Convert a Python annotation / dtype literal into a DType."""
    if isinstance(input_type, DType):
        return input_type
    if not _SIMPLE_WRAPS:
        _build_wrap_table()
    if input_type in _SIMPLE_WRAPS:
        return _SIMPLE_WRAPS[input_type]
    origin = typing.get_origin(input_type)
    args = typing.get_args(input_type)
    if origin is typing.Union:
        non_none = [a for a in args if a is not type(None)]
        if len(non_none) == len(args):
            return ANY
        if len(non_none) == 1:
            return Optional(wrap(non_none[0]))
        return ANY
    if origin in (tuple, typing.Tuple):
        if len(args) == 2 and args[1] is Ellipsis:
            return List(wrap(args[0]))
        return Tuple(*[wrap(a) for a in args])
    if origin in (list, typing.List):
        return List(wrap(args[0]) if args else ANY)
    if origin is typing.Callable or input_type is typing.Callable:
        return Callable_()
    if isinstance(input_type, str):
        named = {
            "DateTimeNaive": DATE_TIME_NAIVE,
            "DateTimeUtc": DATE_TIME_UTC,
            "Duration": DURATION,
        }
        if input_type in named:
            return named[input_type]
    if input_type is datetime.datetime:
        return DATE_TIME_NAIVE
    try:
        npdt = np.dtype(input_type)
    except Exception:
        return ANY
    if np.issubdtype(npdt, np.bool_):
        return BOOL
    if np.issubdtype(npdt, np.integer):
        return INT
    if np.issubdtype(npdt, np.floating):
        return FLOAT
    if np.issubdtype(npdt, np.str_):
        return STR
    return ANY


def unoptionalize(dtype: DType) -> DType:
    return dtype.wrapped if isinstance(dtype, Optional) else dtype


def is_optional(dtype: DType) -> bool:
    return isinstance(dtype, Optional) or dtype in (NONE, ANY)


def dtype_issubclass(left: DType, right: DType) -> bool:
    """Is every `left` value a valid `right` value (lattice ≤)?"""
    if right is ANY or left is right or left == right:
        return True
    if left is NONE:
        return isinstance(right, Optional) or right is NONE
    if isinstance(left, Optional):
        return isinstance(right, Optional) and dtype_issubclass(
            left.wrapped, right.wrapped
        )
    if isinstance(right, Optional):
        return dtype_issubclass(left, right.wrapped)
    if left is INT and right is FLOAT:
        return True
    if left is BOOL and right is INT:
        return False
    if isinstance(left, (Tuple, List)) and right == ANY_TUPLE:
        return True
    if isinstance(left, Tuple) and isinstance(right, Tuple):
        return len(left.args) == len(right.args) and all(
            dtype_issubclass(l, r) for l, r in zip(left.args, right.args)
        )
    if isinstance(left, List) and isinstance(right, List):
        return dtype_issubclass(left.wrapped, right.wrapped)
    if isinstance(left, Array) and isinstance(right, Array):
        return True
    if isinstance(left, Pointer) and isinstance(right, Pointer):
        return True
    return False


def types_lca(left: DType, right: DType, raising: bool = False) -> DType:
    """Least common ancestor of two dtypes (used by if_else / concat / coalesce)."""
    if dtype_issubclass(left, right):
        return right
    if dtype_issubclass(right, left):
        return left
    if left is NONE:
        return Optional(right)
    if right is NONE:
        return Optional(left)
    if isinstance(left, Optional) or isinstance(right, Optional):
        inner = types_lca(unoptionalize(left), unoptionalize(right), raising=raising)
        return Optional(inner)
    if {left, right} == {INT, FLOAT}:
        return FLOAT
    if isinstance(left, Tuple) and isinstance(right, Tuple):
        if len(left.args) == len(right.args):
            return Tuple(*[types_lca(l, r) for l, r in zip(left.args, right.args)])
        return ANY_TUPLE
    if isinstance(left, (Tuple, List)) and isinstance(right, (Tuple, List)):
        return ANY_TUPLE
    if isinstance(left, Array) and isinstance(right, Array):
        return ANY_ARRAY
    if raising:
        raise TypeError(f"no common supertype of {left!r} and {right!r}")
    return ANY


def types_lca_many(*dtypes: DType, raising: bool = False) -> DType:
    out = NONE
    for dt in dtypes:
        out = types_lca(out, dt, raising=raising)
    return out


def coerce_value(value: Any, dtype: DType) -> Any:
    """Best-effort cast of a scalar to `dtype` (used by connectors/markdown parsing)."""
    if value is None:
        return None
    target = unoptionalize(dtype)
    if target is FLOAT and isinstance(value, (int, np.integer)):
        return float(value)
    if target is INT and isinstance(value, (float, np.floating)) and float(value).is_integer():
        return int(value)
    if isinstance(value, str) and target in (INT, FLOAT, BOOL):
        # textual connectors (csv/dsv) deliver strings; parse per schema
        # (best-effort: unparseable text passes through unchanged). The
        # bool vocabulary matches the DSV parser's (io/formats.py
        # _parse_typed, data_format.rs:403) so csv and dsv agree.
        try:
            if target is INT:
                return int(value)
            if target is FLOAT:
                return float(value)
            low = value.strip().lower()
            if low in ("true", "t", "yes", "y", "on", "1"):
                return True
            if low in ("false", "f", "no", "n", "off", "0"):
                return False
            return value
        except ValueError:
            return value
    if target is STR and not isinstance(value, str):
        return str(value)
    if target is BOOL and not isinstance(value, bool):
        return bool(value)
    return value


def normalize_scalar(value: Any) -> Any:
    """Normalize numpy scalars coming out of columnar storage to Python values."""
    if isinstance(value, np.bool_):
        return bool(value)
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.str_):
        return str(value)
    return value
