"""The Table DSL — the user-facing core of the framework.

Rebuild of the reference's Table (python/pathway/internals/table.py:52,
2,636 LoC) with the same public methods, but lowering to plan nodes consumed
by the TPU-native engine runner (internals/runner.py) instead of a PyO3
Scope. A Table is pure metadata: a plan node + schema + universe; nothing
computes until pw.run / pw.debug.compute_and_print.
"""

from __future__ import annotations

import itertools
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.type_inference import infer_dtype
from pathway_tpu.internals.universe import Universe

_table_ids = itertools.count()


class Plan:
    """One logical operator producing a keyed table."""

    __slots__ = ("kind", "params", "trace", "error_log")

    def __init__(self, kind: str, **params):
        self.kind = kind
        self.params = params
        from pathway_tpu.internals.error import current_construction_log
        from pathway_tpu.internals.trace import trace_user_frame

        self.trace = trace_user_frame()
        # operators built inside `with pw.local_error_log()` report there
        self.error_log = current_construction_log()

    def __repr__(self):
        return f"<Plan {self.kind}>"


class Table:
    def __init__(self, plan: Plan, schema: type[sch.Schema],
                 universe: Universe | None = None, name: str = ""):
        self._plan = plan
        self._schema = schema
        self._universe = universe or Universe()
        self._name = name or f"table_{next(_table_ids)}"
        self._id_dtype = dt.POINTER
        from pathway_tpu.internals.parse_graph import G

        G.register_table(self)

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------
    @property
    def schema(self) -> type[sch.Schema]:
        return self._schema

    @property
    def id(self) -> ex.ColumnExpression:
        return ex.IdExpression(self)

    def column_names(self) -> list[str]:
        return self._schema.column_names()

    _column_names = column_names

    def typehints(self):
        return self._schema.typehints()

    def keys(self):
        return self.column_names()

    @property
    def C(self) -> "_ColumnNamespaceProxy":
        return _ColumnNamespaceProxy(self)

    @property
    def slice(self) -> "TableSlice":
        from pathway_tpu.internals.table_slice import TableSlice

        return TableSlice(self, {n: self[n] for n in self.column_names()})

    def __getattr__(self, name: str) -> ex.ColumnReference:
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            schema = object.__getattribute__(self, "_schema")
        except AttributeError:
            raise AttributeError(name) from None
        if name in schema.column_names():
            return ex.ColumnReference(self, name)
        raise AttributeError(
            f"table has no column {name!r}; columns: {schema.column_names()}"
        )

    def __getitem__(self, name) -> Any:
        if isinstance(name, (list, tuple)):
            return [self[n] for n in name]
        if isinstance(name, ex.ColumnReference):
            name = name.name
        if name == "id":
            return self.id
        if name not in self._schema.column_names():
            raise KeyError(name)
        return ex.ColumnReference(self, name)

    def __iter__(self):
        raise TypeError("Table is not iterable; use pw.debug helpers")

    def __repr__(self):
        return f"<pw.Table {self._name} {self._schema.column_names()}>"

    # ------------------------------------------------------------------
    # expression plumbing
    # ------------------------------------------------------------------
    def _resolve(self, expr):
        return thisclass.resolve_this({"this": self}, expr)

    def _select_args_to_exprs(self, args, kwargs) -> dict[str, ex.ColumnExpression]:
        out: dict[str, ex.ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, thisclass.ThisWithout):
                excluded = set(arg._cols)
                for n in self.column_names():
                    if n not in excluded:
                        out[n] = self[n]
            elif isinstance(arg, thisclass.ThisRef):
                for n in self.column_names():
                    out[n] = self[n]
            elif isinstance(arg, ex.ColumnReference):
                resolved = self._resolve(arg)
                out[arg.name] = resolved
            elif isinstance(arg, Table):
                for n in arg.column_names():
                    out[n] = arg[n]
            elif hasattr(arg, "_to_column_mapping"):  # TableSlice
                out.update(arg._to_column_mapping())
            else:
                raise TypeError(f"positional select arg must be a column: {arg!r}")
        for name, e in kwargs.items():
            out[name] = self._resolve(ex.wrap_arg(e))
        return out

    def _result_schema(self, exprs: dict[str, ex.ColumnExpression]):
        cols = {
            name: sch.ColumnSchema(name=name, dtype=infer_dtype(e))
            for name, e in exprs.items()
        }
        return sch.schema_from_columns(cols)

    # ------------------------------------------------------------------
    # projection & mutation
    # ------------------------------------------------------------------
    def select(self, *args, **kwargs) -> "Table":
        """Project/compute columns (reference: Table.select, table.py).

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown(\'\'\'
        ... name  | qty
        ... bolt  | 3
        ... screw | 5
        ... \'\'\')
        >>> pw.debug.compute_and_print(
        ...     t.select(t.name, double=t.qty * 2), include_id=False)
        name | double
        bolt | 6
        screw | 10
        """
        exprs = self._select_args_to_exprs(args, kwargs)
        schema = self._result_schema(exprs)
        plan = Plan("map", base=self, exprs=list(exprs.values()),
                    names=list(exprs.keys()))
        return Table(plan, schema, self._universe)

    def debug(self, name: str) -> "Table":
        """Print this table's final state during pw.run (reference:
        table.py Table.debug → DebugOperator)."""
        from pathway_tpu.internals.parse_graph import G

        def binder(runner):
            def callback(time, delta):
                for key, row, diff in delta.entries:
                    print(f"[debug {name}] t={time} diff={diff} "
                          f"{dict(zip(self.column_names(), row))}")

            runner.subscribe(self, callback)

        G.add_output(binder, table=self, sink="debug")
        return self

    def eval_type(self, expression):
        """dtype of an expression evaluated in this table's row context
        (reference: table.py:2510)."""
        return infer_dtype(self._resolve(ex.wrap_arg(expression)))

    def remove_errors(self) -> "Table":
        """Filter out rows containing ERROR values (reference:
        table.py:2452)."""
        from pathway_tpu.internals.error import is_error

        def no_errors(keys, rows):
            return [not any(is_error(v) for v in r) for r in rows]

        plan = Plan("filter_raw", base=self, pred_fn=no_errors)
        return Table(plan, self.schema, self._universe.subuniverse())

    def update_id_type(self, id_type) -> "Table":
        """Re-declare the id column's pointer type (metadata only here:
        ids are untyped 128-bit pointers engine-side — reference
        table.py:1993 narrows the schema's id type)."""
        return self

    def live(self):
        """Interactive-mode live view (reference: table.py Table.live +
        internals/interactive.py LiveTable)."""
        from pathway_tpu.internals.interactive import LiveTable

        return LiveTable.create(self)

    def with_columns(self, *args, **kwargs) -> "Table":
        new = self._select_args_to_exprs(args, kwargs)
        exprs = {n: self[n] for n in self.column_names()}
        exprs.update(new)
        schema = self._result_schema(exprs)
        plan = Plan("map", base=self, exprs=list(exprs.values()),
                    names=list(exprs.keys()))
        return Table(plan, schema, self._universe)

    def without(self, *columns) -> "Table":
        names = {c.name if isinstance(c, ex.ColumnReference) else c for c in columns}
        keep = [n for n in self.column_names() if n not in names]
        return self.select(*[self[n] for n in keep])

    def rename(self, names_mapping: dict | None = None, **kwargs) -> "Table":
        if names_mapping:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def rename_columns(self, **kwargs) -> "Table":
        # kwargs: new_name=old_column
        mapping = {}
        for new_name, old in kwargs.items():
            old_name = old.name if isinstance(old, ex.ColumnReference) else old
            mapping[old_name] = new_name
        return self.rename_by_dict(mapping)

    def rename_by_dict(self, names_mapping: dict) -> "Table":
        mapping = {
            (k.name if isinstance(k, ex.ColumnReference) else k):
            (v.name if isinstance(v, ex.ColumnReference) else v)
            for k, v in names_mapping.items()
        }
        exprs = {}
        for n in self.column_names():
            exprs[mapping.get(n, n)] = self[n]
        return self.select(**exprs)

    def with_prefix(self, prefix: str) -> "Table":
        return self.rename_by_dict({n: prefix + n for n in self.column_names()})

    def with_suffix(self, suffix: str) -> "Table":
        return self.rename_by_dict({n: n + suffix for n in self.column_names()})

    def cast_to_types(self, **kwargs) -> "Table":
        exprs = {n: self[n] for n in self.column_names()}
        for name, target in kwargs.items():
            exprs[name] = ex.CastExpression(target, self[name])
        return self.select(**exprs)

    def update_types(self, **kwargs) -> "Table":
        schema = self._schema.with_types(**kwargs)
        t = Table(Plan("identity", base=self), schema, self._universe)
        return t

    # ------------------------------------------------------------------
    # filtering / universe ops
    # ------------------------------------------------------------------
    def filter(self, filter_expression) -> "Table":
        """Keep rows where the predicate holds.

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown(\'\'\'
        ... name  | qty
        ... bolt  | 3
        ... screw | 5
        ... nut   | 9
        ... \'\'\')
        >>> pw.debug.compute_and_print(t.filter(t.qty > 4), include_id=False)
        name | qty
        nut | 9
        screw | 5
        """
        pred = self._resolve(ex.wrap_arg(filter_expression))
        plan = Plan("filter", base=self, pred=pred)
        return Table(plan, self._schema, self._universe.subuniverse())

    def split(self, split_expression) -> tuple["Table", "Table"]:
        pred = self._resolve(ex.wrap_arg(split_expression))
        return self.filter(pred), self.filter(~ex.wrap_arg(pred))

    def restrict(self, other: "Table") -> "Table":
        plan = Plan("key_filter", base=self, other=other, mode="restrict")
        return Table(plan, self._schema, other._universe)

    def intersect(self, *tables: "Table") -> "Table":
        out = self
        for t in tables:
            plan = Plan("key_filter", base=out, other=t, mode="intersect")
            out = Table(plan, self._schema, self._universe.subuniverse())
        return out

    def difference(self, other: "Table") -> "Table":
        plan = Plan("key_filter", base=self, other=other, mode="difference")
        return Table(plan, self._schema, self._universe.subuniverse())

    def having(self, *indexers) -> "Table":
        out = self
        for indexer in indexers:
            # keep rows whose id appears as value of `indexer` expression rows
            plan = Plan("having", base=out, indexer=indexer,
                        indexer_table=indexer.table)
            out = Table(plan, self._schema, self._universe.subuniverse())
        return out

    def copy(self) -> "Table":
        return Table(Plan("identity", base=self), self._schema, self._universe)

    def with_universe_of(self, other: "Table") -> "Table":
        # universe_from lets the static analyzer (PWT007) tell this apart
        # from copy()/update_types() identity plans
        t = Table(Plan("identity", base=self, universe_from=other),
                  self._schema, other._universe)
        return t

    def promise_universes_are_disjoint(self, other: "Table") -> "Table":
        self._universe.promise_is_disjoint_from(other._universe)
        return self

    def promise_universes_are_equal(self, other: "Table") -> "Table":
        self._universe.promise_is_subset_of(other._universe)
        other._universe.promise_is_subset_of(self._universe)
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        self._universe.promise_is_subset_of(other._universe)
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        return self.promise_universes_are_equal(other)

    def is_subset_of(self, other: "Table") -> bool:
        return self._universe.is_subset_of(other._universe)

    # ------------------------------------------------------------------
    # keys / reindex
    # ------------------------------------------------------------------
    def with_id(self, new_index: ex.ColumnExpression) -> "Table":
        expr = self._resolve(ex.wrap_arg(new_index))
        plan = Plan("reindex", base=self, key_exprs=[expr], raw=True)
        return Table(plan, self._schema, Universe())

    def with_id_from(self, *args, instance=None) -> "Table":
        exprs = [self._resolve(ex.wrap_arg(a)) for a in args]
        if instance is not None:
            exprs.append(self._resolve(ex.wrap_arg(instance)))
        plan = Plan("reindex", base=self, key_exprs=exprs, raw=False)
        return Table(plan, self._schema, Universe())

    def pointer_from(self, *args, optional=False, instance=None):
        return ex.PointerExpression(self, *args, optional=optional, instance=instance)

    # ------------------------------------------------------------------
    # groupby / reduce / dedup
    # ------------------------------------------------------------------
    def groupby(self, *args, id=None, sort_by=None, _filter_out_results_of_forgetting=False,
                instance=None, _is_window: bool = False, **kwargs):
        from pathway_tpu.internals.groupbys import GroupedTable

        by = [self._resolve(ex.wrap_arg(a)) for a in args]
        if id is not None:
            by = [self._resolve(ex.wrap_arg(id))]
        inst = self._resolve(ex.wrap_arg(instance)) if instance is not None else None
        return GroupedTable(self, by, instance=inst, sort_by=sort_by, by_id=id is not None)

    def reduce(self, *args, **kwargs) -> "Table":
        return self.groupby().reduce(*args, **kwargs)

    def deduplicate(self, *, value=None, instance=None, acceptor=None, name=None,
                    persistent_id=None) -> "Table":
        """Keep one accepted value per instance; by default a new distinct
        value replaces the old one (reference: pw.Table.deduplicate).

        >>> import pathway_tpu as pw
        >>> s = pw.debug.table_from_markdown('''
        ... sensor | reading | _time
        ... a      | 5       | 2
        ... a      | 5       | 4
        ... a      | 8       | 6
        ... ''')
        >>> pw.debug.compute_and_print(s.deduplicate(value=s.reading),
        ...                            include_id=False)
        sensor | reading
        a | 8
        """
        value_e = self._resolve(ex.wrap_arg(value)) if value is not None else None
        inst_e = self._resolve(ex.wrap_arg(instance)) if instance is not None else None
        if acceptor is None:
            acceptor = lambda new, old: new != old
        plan = Plan("dedupe", base=self, value=value_e, instance=inst_e,
                    acceptor=acceptor)
        return Table(plan, self._schema, Universe())

    # ------------------------------------------------------------------
    # joins (delegates to joins.py)
    # ------------------------------------------------------------------
    def join(self, other: "Table", *on, id=None, how="inner", left_instance=None,
             right_instance=None):
        from pathway_tpu.internals.joins import JoinResult

        mode = how if isinstance(how, str) else how.value
        return JoinResult.create(self, other, on, mode, id,
                                 left_instance, right_instance)

    def join_inner(self, other, *on, **kw):
        return self.join(other, *on, how="inner", **kw)

    def join_left(self, other, *on, **kw):
        return self.join(other, *on, how="left", **kw)

    def join_right(self, other, *on, **kw):
        return self.join(other, *on, how="right", **kw)

    def join_outer(self, other, *on, **kw):
        return self.join(other, *on, how="outer", **kw)

    # asof/interval/window joins provided via stdlib.temporal monkey-level API
    def asof_join(self, other, t_left, t_right, *on, how="inner", defaults={},
                  direction=None):
        from pathway_tpu.stdlib.temporal import asof_join as _asof

        return _asof(self, other, t_left, t_right, *on, how=how,
                     defaults=defaults, direction=direction)

    def asof_now_join(self, other, *on, how="inner", id=None, **kw):
        from pathway_tpu.stdlib.temporal import asof_now_join as _anj

        return _anj(self, other, *on, how=how, id=id, **kw)

    def interval_join(self, other, self_time, other_time, interval, *on, how="inner"):
        from pathway_tpu.stdlib.temporal import interval_join as _ij

        return _ij(self, other, self_time, other_time, interval, *on, how=how)

    def window_join(self, other, self_time, other_time, window, *on, how="inner"):
        from pathway_tpu.stdlib.temporal import window_join as _wj

        return _wj(self, other, self_time, other_time, window, *on, how=how)

    def windowby(self, time_expr, *, window, behavior=None, instance=None, **kwargs):
        from pathway_tpu.stdlib.temporal import windowby as _windowby

        return _windowby(self, time_expr, window=window, behavior=behavior,
                         instance=instance, **kwargs)

    # ------------------------------------------------------------------
    # set ops / combination
    # ------------------------------------------------------------------
    def _gradual_broadcast(self, threshold_table: "Table", lower_column,
                           value_column, upper_column) -> "Table":
        """Add an ``apx_value`` column approximating a changing broadcast
        scalar: keys below (value-lower)/(upper-lower) of the key space
        read ``upper``, the rest ``lower`` — a moving value retracts only
        the key range it crossed (reference: Table._gradual_broadcast,
        internals/table.py:627 + operators/gradual_broadcast.rs)."""
        from pathway_tpu.internals import dtype as dt

        thr = threshold_table.select(_pw_l=lower_column, _pw_v=value_column,
                                     _pw_u=upper_column)
        plan = Plan("gradual_broadcast", base=self, thr=thr)
        schema = self.schema | sch.schema_from_types(apx_value=dt.ANY)
        return Table(plan, schema, self._universe)

    def concat(self, *others: "Table") -> "Table":
        tables = [self, *others]
        schema = _common_schema(tables)
        plan = Plan("concat", tables=tables, update=False)
        out = Table(plan, schema, Universe())
        for t in tables:  # union: every input is a subset of the result
            t._universe.promise_is_subset_of(out._universe)
        return out

    def concat_reindex(self, *others: "Table") -> "Table":
        tables = [self, *others]
        schema = _common_schema(tables)
        plan = Plan("concat_reindex", tables=tables)
        return Table(plan, schema, Universe())

    def update_rows(self, other: "Table") -> "Table":
        schema = _common_schema([self, other], update=True)
        plan = Plan("concat", tables=[self, other], update=True)
        out = Table(plan, schema, Universe())
        self._universe.promise_is_subset_of(out._universe)
        other._universe.promise_is_subset_of(out._universe)
        return out

    def update_cells(self, other: "Table") -> "Table":
        names = other.column_names()
        for n in names:
            if n not in self.column_names():
                raise ValueError(f"update_cells: unknown column {n!r}")
        plan = Plan("update_cells", base=self, other=other, columns=names)
        return Table(plan, self._schema, self._universe)

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    # ------------------------------------------------------------------
    # reshaping
    # ------------------------------------------------------------------
    def flatten(self, to_flatten: ex.ColumnReference, *, origin_id: str | None = None) -> "Table":
        resolved = self._resolve(to_flatten)
        name = to_flatten.name if isinstance(to_flatten, ex.ColumnReference) else "flat"
        inner = infer_dtype(resolved)
        inner_dt = dt.ANY
        if isinstance(inner, dt.List):
            inner_dt = inner.wrapped
        elif isinstance(inner, dt.Tuple):
            inner_dt = dt.types_lca_many(*inner.args)
        elif inner is dt.STR:
            inner_dt = dt.STR
        cols = {}
        for n in self.column_names():
            if n == name:
                cols[n] = sch.ColumnSchema(name=n, dtype=inner_dt)
            else:
                cols[n] = sch.ColumnSchema(name=n, dtype=self._schema[n].dtype)
        if origin_id is not None:
            cols[origin_id] = sch.ColumnSchema(name=origin_id, dtype=dt.POINTER)
        schema = sch.schema_from_columns(cols)
        plan = Plan("flatten", base=self, expr=resolved, col_name=name,
                    origin_id=origin_id)
        return Table(plan, schema, Universe())

    def sort(self, key: ex.ColumnExpression, instance=None) -> "Table":
        key_e = self._resolve(ex.wrap_arg(key))
        inst_e = self._resolve(ex.wrap_arg(instance)) if instance is not None else None
        cols = {
            "prev": sch.ColumnSchema(name="prev", dtype=dt.Optional(dt.POINTER)),
            "next": sch.ColumnSchema(name="next", dtype=dt.Optional(dt.POINTER)),
        }
        schema = sch.schema_from_columns(cols)
        plan = Plan("sort", base=self, key=key_e, instance=inst_e)
        return Table(plan, schema, self._universe)

    def diff(self, timestamp: ex.ColumnExpression, *values,
             instance=None) -> "Table":
        from pathway_tpu.stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    def interpolate(self, timestamp, *values, mode=None):
        from pathway_tpu.stdlib.statistical import interpolate as _interp

        return _interp(self, timestamp, *values, mode=mode)

    # ------------------------------------------------------------------
    # pointer lookup
    # ------------------------------------------------------------------
    def ix(self, expression, *, optional: bool = False, context=None) -> "Table":
        ctx_table = context
        if ctx_table is None:
            ctx_table = _expr_base_table(expression)
        if ctx_table is None:
            raise ValueError("ix needs a context table (pass context=...)")
        schema = self._schema
        if optional:
            schema = sch.schema_from_columns({
                n: sch.ColumnSchema(name=n, dtype=dt.Optional(self._schema[n].dtype))
                for n in self.column_names()
            })
        plan = Plan("ix", target=self, key_expr=expression, ctx=ctx_table,
                    optional=optional)
        return Table(plan, schema, ctx_table._universe)

    def ix_ref(self, *args, optional: bool = False, context=None, instance=None):
        if context is None:
            raise ValueError("ix_ref requires context= (the table to index from)")
        expr = context.pointer_from(*args, instance=instance)
        return self.ix(expr, optional=optional, context=context)

    # ------------------------------------------------------------------
    # visualization (reference: stdlib/viz/table_viz.py show:26, Table.plot)
    # ------------------------------------------------------------------
    def show(self, *, snapshot: bool = True, include_id: bool = True,
             short_pointers: bool = True, sorters=None):
        # short_pointers: our Pointer str() is already the short digest form
        if sorters is not None:
            raise NotImplementedError(
                "show(sorters=...) needs the panel widget backend "
                "(not in this build)")
        from pathway_tpu.stdlib.viz import show as _show

        return _show(self, snapshot=snapshot, include_id=include_id)

    def plot(self, plotting_function=None, sorting_col=None):
        from pathway_tpu.stdlib.viz import plot as _plot

        return _plot(self, plotting_function, sorting_col)

    def _repr_html_(self) -> str:
        import html as _html

        from pathway_tpu.internals.runner import run_tables

        [cap] = run_tables(self)
        names = self._column_names()
        out = ["<table>", "<tr>"]
        out.extend(f"<th>{_html.escape(str(c))}</th>"
                   for c in ["id"] + names)
        out.append("</tr>")
        for key, row in sorted(cap.snapshot().items(),
                               key=lambda kv: int(kv[0]))[:50]:
            out.append("<tr>")
            out.append(f"<td>{_html.escape(str(key))}</td>")
            out.extend(
                f"<td>{_html.escape('' if v is None else str(v))}</td>"
                for v in row)
            out.append("</tr>")
        out.append("</table>")
        return "".join(out)

    # ------------------------------------------------------------------
    # iteration / indexes / io hooks (wired by other modules)
    # ------------------------------------------------------------------
    def _external_index_as_of_now(self, query_table, *, index_factory,
                                  query_responses_limit_column=None,
                                  query_filter_column=None,
                                  index_filter_data_column=None,
                                  res_type=dt.ANY_TUPLE,
                                  revise: bool = False):
        cols = {"_pw_index_reply": sch.ColumnSchema(name="_pw_index_reply",
                                                    dtype=res_type)}
        schema = sch.schema_from_columns(cols)
        plan = Plan(
            "external_index", data=self, queries=query_table,
            index_factory=index_factory,
            limit_col=query_responses_limit_column,
            query_filter_col=query_filter_column,
            data_filter_col=index_filter_data_column,
            revise=revise,
        )
        return Table(plan, schema, query_table._universe.subuniverse())

    def _forget_immediately(self) -> "Table":
        plan = Plan("forget_immediately", base=self)
        return Table(plan, self._schema, self._universe.subuniverse())

    def _buffer(self, threshold_column, time_column) -> "Table":
        plan = Plan("buffer", base=self,
                    threshold=self._resolve(ex.wrap_arg(threshold_column)),
                    time=self._resolve(ex.wrap_arg(time_column)))
        return Table(plan, self._schema, self._universe.subuniverse())

    def _forget(self, threshold_column, time_column,
                mark_forgetting_records: bool = False) -> "Table":
        plan = Plan("forget", base=self,
                    threshold=self._resolve(ex.wrap_arg(threshold_column)),
                    time=self._resolve(ex.wrap_arg(time_column)),
                    mark=mark_forgetting_records)
        return Table(plan, self._schema, self._universe.subuniverse())

    def _freeze(self, threshold_column, time_column) -> "Table":
        plan = Plan("freeze", base=self,
                    threshold=self._resolve(ex.wrap_arg(threshold_column)),
                    time=self._resolve(ex.wrap_arg(time_column)))
        return Table(plan, self._schema, self._universe.subuniverse())

    def _filter_out_results_of_forgetting(self) -> "Table":
        plan = Plan("filter_out_forgetting", base=self)
        return Table(plan, self._schema, self._universe.subuniverse())

    # ------------------------------------------------------------------
    # constructors
    # ------------------------------------------------------------------
    @staticmethod
    def empty(**kwargs) -> "Table":
        schema = sch.schema_from_types(**kwargs)
        return Table(Plan("static", keys=[], rows=[], times=None, diffs=None),
                     schema)

    @staticmethod
    def from_columns(*args, **kwargs) -> "Table":
        raise NotImplementedError("use pw.debug.table_from_pandas")

    def to(self, sink) -> None:
        """t.to(sink) — route this table to an output connector."""
        sink.write(self)


class _ColumnNamespaceProxy:
    def __init__(self, table: Table):
        self._table = table

    def __getattr__(self, name):
        return self._table[name]

    def __getitem__(self, name):
        return self._table[name]


def _common_schema(tables: list[Table], update: bool = False):
    names = tables[0].column_names()
    for t in tables[1:]:
        if set(t.column_names()) != set(names):
            raise ValueError(
                f"concat/update requires same columns; got {names} vs "
                f"{t.column_names()}"
            )
    cols = {}
    for n in names:
        dtypes = [t._schema[n].dtype for t in tables]
        cols[n] = sch.ColumnSchema(name=n, dtype=dt.types_lca_many(*dtypes))
    return sch.schema_from_columns(cols)


def _expr_base_table(expr) -> Table | None:
    if isinstance(expr, ex.ColumnReference) and isinstance(expr.table, Table):
        return expr.table
    for d in getattr(expr, "_deps", ()):
        t = _expr_base_table(d)
        if t is not None:
            return t
    return None
