"""pw.sql — SQL subset compiled to Table ops
(reference: python/pathway/internals/sql.py, 726 LoC, sqlglot-based).

sqlglot is not available in this environment; a hand-rolled parser for the
same subset (SELECT/WHERE/GROUP BY/HAVING/JOIN/UNION/INTERSECT/WITH) lives
in internals/sql_parser.py.
"""

from __future__ import annotations

from pathway_tpu.internals.table import Table


def sql(query: str, **tables: Table) -> Table:
    from pathway_tpu.internals.sql_parser import compile_sql

    return compile_sql(query, tables)
