"""pw.sql — SQL subset compiled to Table ops
(reference: python/pathway/internals/sql.py, 726 LoC, sqlglot-based).

sqlglot is not available in this environment; a hand-rolled parser for the
same subset (SELECT/WHERE/GROUP BY/HAVING/JOIN/UNION/INTERSECT/WITH) lives
in internals/sql_parser.py.
"""

from __future__ import annotations

from pathway_tpu.internals.table import Table


def sql(query: str, **tables: Table) -> Table:
    """Compile a SQL query over named tables.

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown(\'\'\'
    ... item | price
    ... pen  | 4
    ... ink  | 9
    ... pad  | 2
    ... \'\'\')
    >>> r = pw.sql("SELECT item, price * 2 AS double FROM t WHERE price > 3",
    ...            t=t)
    >>> pw.debug.compute_and_print(r, include_id=False)
    item | double
    ink | 18
    pen | 8
    """
    from pathway_tpu.internals.sql_parser import compile_sql

    return compile_sql(query, tables)
