"""GroupedTable.reduce (reference: python/pathway/internals/groupbys.py).

Reducer expressions inside ``reduce(...)`` are split out; the engine
GroupByOperator maintains incremental per-group reducer state; compound
expressions around reducers become a post-map over (group values, reduced
values) rows.
"""

from __future__ import annotations

from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.expression_utils import map_expression
from pathway_tpu.internals.table import Plan, Table
from pathway_tpu.internals.type_inference import infer_dtype
from pathway_tpu.internals.universe import Universe


class GroupedTable:
    def __init__(self, table: Table, by: list[ex.ColumnExpression], *,
                 instance=None, sort_by=None, by_id: bool = False):
        self._table = table
        self._by = by
        self._instance = instance
        self._sort_by = sort_by
        self._by_id = by_id

    def reduce(self, *args, **kwargs) -> Table:
        table = self._table
        out: dict[str, ex.ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ex.ColumnReference):
                out[arg.name] = thisclass.resolve_this({"this": table}, arg)
            elif isinstance(arg, thisclass.ThisRef):
                for b in self._by:
                    if isinstance(b, ex.ColumnReference):
                        out[b.name] = b
            else:
                raise TypeError(f"positional reduce arg must be a column: {arg!r}")
        for name, e in kwargs.items():
            out[name] = thisclass.resolve_this({"this": table}, ex.wrap_arg(e))

        schema = sch.schema_from_columns({
            name: sch.ColumnSchema(name=name, dtype=infer_dtype(e))
            for name, e in out.items()
        })
        plan = Plan(
            "groupby",
            base=table,
            by=self._by,
            instance=self._instance,
            out_names=list(out.keys()),
            out_exprs=list(out.values()),
            sort_by=self._sort_by,
            by_id=self._by_id,
        )
        return Table(plan, schema, Universe())


def split_reducers(out_exprs: list[ex.ColumnExpression], by_exprs, instance,
                   proxy: object):
    """Rewrite output expressions over the grouped row space.

    Returns (rewritten_exprs, reducer_nodes) where the rewritten expressions
    reference the synthetic `proxy` table with columns
    ``__g{i}`` (grouping values) then ``__r{j}`` (reducer results).
    """
    by_keys = {}
    for i, b in enumerate(by_exprs):
        if isinstance(b, ex.ColumnReference):
            by_keys[(id(b.table), b.name)] = i
    if instance is not None and isinstance(instance, ex.ColumnReference):
        by_keys.setdefault((id(instance.table), instance.name), len(by_exprs))

    reducers: list[ex.ReducerExpression] = []

    def mapper(e):
        if isinstance(e, ex.ReducerExpression):
            for j, r in enumerate(reducers):
                if r is e:
                    return ex.ColumnReference(proxy, f"__r{j}")
            reducers.append(e)
            return ex.ColumnReference(proxy, f"__r{len(reducers) - 1}")
        if isinstance(e, ex.IdExpression):
            # id of the grouped row
            return ex.IdExpression(proxy)
        if isinstance(e, ex.ColumnReference):
            key = (id(e.table), e.name)
            if key in by_keys:
                return ex.ColumnReference(proxy, f"__g{by_keys[key]}")
            if e.table is proxy:
                return e
            raise KeyError(
                f"column {e.name!r} is neither a groupby key nor inside a reducer"
            )
        return None

    rewritten = [map_expression(e, mapper) for e in out_exprs]
    return rewritten, reducers
