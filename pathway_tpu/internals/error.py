"""Error value propagation (reference: Value::Error poisoned cells,
src/engine/error.rs + graph.rs error_log APIs).

A cell whose computation failed becomes the ``ERROR`` sentinel; downstream
expressions propagate it; ``fill_error`` replaces it; with
``terminate_on_error=False`` runs keep going and errors stream into a global
error-log table instead of aborting.
"""

from __future__ import annotations

import threading


class _ErrorValue:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "Error"

    def __bool__(self):
        raise ValueError("cannot use pw Error value in a boolean context")

    def __eq__(self, other):
        return self is other

    def __hash__(self):
        return hash("pathway-tpu::Error")


ERROR = _ErrorValue()


def is_error(value) -> bool:
    return value is ERROR


class ErrorLog:
    """Collects (message, operator_name) error rows for the run.

    ``kind`` partitions the log: ``"runtime"`` for poisoned-cell operator
    errors, ``"connector"`` for supervised-source failures escalated by the
    streaming runtime with ``terminate_on_error=False`` — the channel that
    keeps a dropped source visible after the run reports completion."""

    def __init__(self):
        self._lock = threading.Lock()
        self.entries: list[dict] = []

    def log(self, message: str, operator: str = "", trace=None,
            kind: str = "runtime") -> None:
        with self._lock:
            self.entries.append(
                {"message": message, "operator": operator, "trace": trace,
                 "kind": kind}
            )

    def connector_failures(self) -> list[dict]:
        """Entries logged by the connector supervisor (failed sources)."""
        with self._lock:
            return [e for e in self.entries if e["kind"] == "connector"]


_global_log = ErrorLog()

# scoped logs: Plans built inside a `with local_error_log()` block carry
# the scope's log; the scheduler activates it around each node's step so
# RUNTIME errors from those operators land in the scoped log too
# (reference: per-scope error-log tables, graph.rs error_log APIs)
_construction_scope = threading.local()
_active_step = threading.local()


def current_construction_log():
    stack = getattr(_construction_scope, "stack", None)
    return stack[-1] if stack else None


def push_construction_log(log) -> None:
    if not hasattr(_construction_scope, "stack"):
        _construction_scope.stack = []
    _construction_scope.stack.append(log)


def pop_construction_log() -> None:
    stack = getattr(_construction_scope, "stack", None)
    if stack:
        stack.pop()


def set_active_step_log(log) -> None:
    _active_step.log = log


def global_error_log() -> ErrorLog:
    """The log errors go to RIGHT NOW: the stepping node's scoped log when
    one is active, else the run-global log (the reference's
    global_error_log vs local error-log tables)."""
    active = getattr(_active_step, "log", None)
    return active if active is not None else _global_log
