"""Error value propagation (reference: Value::Error poisoned cells,
src/engine/error.rs + graph.rs error_log APIs).

A cell whose computation failed becomes the ``ERROR`` sentinel; downstream
expressions propagate it; ``fill_error`` replaces it; with
``terminate_on_error=False`` runs keep going and errors stream into a global
error-log table instead of aborting.
"""

from __future__ import annotations

import threading


class _ErrorValue:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "Error"

    def __bool__(self):
        raise ValueError("cannot use pw Error value in a boolean context")

    def __eq__(self, other):
        return self is other

    def __hash__(self):
        return hash("pathway-tpu::Error")


ERROR = _ErrorValue()


def is_error(value) -> bool:
    return value is ERROR


class ErrorLog:
    """Collects (message, operator_name) error rows for the run."""

    def __init__(self):
        self._lock = threading.Lock()
        self.entries: list[dict] = []

    def log(self, message: str, operator: str = "", trace=None) -> None:
        with self._lock:
            self.entries.append(
                {"message": message, "operator": operator, "trace": trace}
            )


_global_log = ErrorLog()


def global_error_log() -> ErrorLog:
    return _global_log
