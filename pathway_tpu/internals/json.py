"""Json value wrapper (reference: python/pathway/internals/json.py — pw.Json).

A thin immutable wrapper over parsed JSON with ``.as_int()``-style accessors
and ``[]`` item access, so JSON-typed cells round-trip through the engine as
one opaque value (stored in object columns host-side; never shipped to TPU).
"""

from __future__ import annotations

import json as _json
from typing import Any, Iterator


class Json:
    __slots__ = ("_value", "_dumps_cache")

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value._value
        self._value = value
        self._dumps_cache: str | None = None

    # -- construction ------------------------------------------------------
    @classmethod
    def parse(cls, s: str | bytes) -> "Json":
        return cls(_json.loads(s))

    @property
    def value(self) -> Any:
        return self._value

    def dumps(self) -> str:
        # cached: key derivation / fingerprinting serializes the same Json
        # cell at every exchange and groupby it flows through (the wrapper
        # is immutable by contract)
        if self._dumps_cache is None:
            self._dumps_cache = _json.dumps(
                self._value, sort_keys=True, default=_default)
        return self._dumps_cache

    # -- access ------------------------------------------------------------
    def __getitem__(self, item) -> "Json":
        v = self._value
        if isinstance(v, dict):
            if item not in v:
                raise KeyError(item)
            return Json(v[item])
        if isinstance(v, list):
            return Json(v[item])
        raise TypeError(f"Json value {v!r} is not indexable")

    def get(self, item, default=None):
        try:
            return self[item]
        except (KeyError, IndexError, TypeError):
            return default

    def __iter__(self) -> Iterator["Json"]:
        if isinstance(self._value, list):
            return (Json(v) for v in self._value)
        if isinstance(self._value, dict):
            return iter(self._value)
        raise TypeError("Json value is not iterable")

    def __len__(self) -> int:
        return len(self._value)

    def __contains__(self, item) -> bool:
        return item in self._value

    # -- converters (mirror pw.Json API) -----------------------------------
    def as_int(self) -> int:
        if isinstance(self._value, bool) or not isinstance(self._value, int):
            raise ValueError(f"Cannot convert {self!r} to int")
        return self._value

    def as_float(self) -> float:
        if isinstance(self._value, bool) or not isinstance(self._value, (int, float)):
            raise ValueError(f"Cannot convert {self!r} to float")
        return float(self._value)

    def as_str(self) -> str:
        if not isinstance(self._value, str):
            raise ValueError(f"Cannot convert {self!r} to str")
        return self._value

    def as_bool(self) -> bool:
        if not isinstance(self._value, bool):
            raise ValueError(f"Cannot convert {self!r} to bool")
        return self._value

    def as_list(self) -> list:
        if not isinstance(self._value, list):
            raise ValueError(f"Cannot convert {self!r} to list")
        return self._value

    def as_dict(self) -> dict:
        if not isinstance(self._value, dict):
            raise ValueError(f"Cannot convert {self!r} to dict")
        return self._value

    # -- dunder ------------------------------------------------------------
    def __eq__(self, other):
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self):
        return hash(self.dumps())

    def __repr__(self):
        return f"pw.Json({self._value!r})"

    def __str__(self):
        return self.dumps()

    def __bool__(self):
        return bool(self._value)

    NULL: "Json"


Json.NULL = Json(None)


def _default(obj):
    import numpy as np

    if isinstance(obj, Json):
        return obj.value
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return str(obj)
