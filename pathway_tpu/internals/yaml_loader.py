"""Declarative YAML pipeline loader
(reference: python/pathway/internals/yaml_loader.py — `$var` references,
`!pw.` object tags, env interpolation)."""

from __future__ import annotations

import importlib
import os
import re
from typing import Any, IO

import yaml

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")


class _PwTag:
    def __init__(self, path: str, kwargs: dict):
        self.path = path
        self.kwargs = kwargs

    def instantiate(self, variables: dict):
        target = _resolve_symbol(self.path)
        kwargs = {k: _materialize(v, variables) for k, v in self.kwargs.items()}
        if callable(target):
            return target(**kwargs) if kwargs else (
                target() if _requires_call(target) else target)
        return target


def _requires_call(target) -> bool:
    return isinstance(target, type)


def _resolve_symbol(path: str):
    """Resolve `pw.xpacks.llm.embedders.SentenceTransformerEmbedder`-style paths."""
    parts = path.split(".")
    if parts[0] in ("pw", "pathway"):
        import pathway_tpu as root

        obj: Any = root
        parts = parts[1:]
    else:
        obj = importlib.import_module(parts[0])
        parts = parts[1:]
    for p in parts:
        if hasattr(obj, p):
            obj = getattr(obj, p)
        else:
            obj = importlib.import_module(f"{obj.__name__}.{p}")
    return obj


def _pw_constructor(loader, tag_suffix, node):
    if isinstance(node, yaml.MappingNode):
        kwargs = loader.construct_mapping(node, deep=True)
    else:
        kwargs = {}
    return _PwTag(tag_suffix, kwargs)


class _Loader(yaml.SafeLoader):
    pass


yaml.add_multi_constructor("!pw", lambda l, s, n: _pw_constructor(l, "pw" + s, n),
                           Loader=_Loader)
yaml.add_multi_constructor("!", lambda l, s, n: _pw_constructor(l, s, n),
                           Loader=_Loader)


def _interpolate_env(text: str) -> str:
    return _ENV_RE.sub(lambda m: os.environ.get(m.group(1), m.group(0)), text)


def _materialize(value: Any, variables: dict) -> Any:
    if isinstance(value, _PwTag):
        return value.instantiate(variables)
    if isinstance(value, str) and value.startswith("$"):
        name = value[1:]
        if name in variables:
            return _materialize(variables[name], variables)
        return value
    if isinstance(value, dict):
        return {k: _materialize(v, variables) for k, v in value.items()}
    if isinstance(value, list):
        return [_materialize(v, variables) for v in value]
    return value


def load_yaml(stream: str | IO) -> Any:
    if hasattr(stream, "read"):
        text = stream.read()
    else:
        text = stream
    text = _interpolate_env(text)
    raw = yaml.load(text, Loader=_Loader)
    if not isinstance(raw, dict):
        return raw
    variables = {k: v for k, v in raw.items() if k.startswith("$")}
    variables = {k[1:]: v for k, v in variables.items()}
    out = {}
    for k, v in raw.items():
        if k.startswith("$"):
            continue
        out[k] = _materialize(v, variables)
    return out
