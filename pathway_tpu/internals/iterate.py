"""pw.iterate — fixed-point iteration
(reference: internals/parse_graph.py:153 add_iterate + dataflow.rs:3668)."""

from __future__ import annotations

from typing import Callable

from pathway_tpu.internals.table import Plan, Table
from pathway_tpu.internals.universe import Universe


class IterateShared:
    def __init__(self, input_tables, iterated_placeholders, extra_placeholders,
                 body_outputs, result_tables, limit):
        self.input_tables = input_tables
        self.iterated_placeholders = iterated_placeholders
        self.extra_placeholders = extra_placeholders
        self.body_outputs = body_outputs
        self.result_tables = result_tables
        self.limit = limit


class _IterateResultNamespace:
    def __init__(self, mapping: dict):
        self._mapping = mapping
        for k, v in mapping.items():
            setattr(self, k, v)

    def __getitem__(self, k):
        return self._mapping[k]

    def __iter__(self):
        return iter(self._mapping.values())

    def keys(self):
        return self._mapping.keys()


def iterate(func: Callable, iteration_limit: int | None = None, **kwargs):
    """Iterate `func` to fixpoint over the tables passed as kwargs.

    Tables returned by `func` under the same name as an input are fed back;
    other inputs are loop-invariant ("extra"). Returns the converged tables
    (single Table if `func` returned one, else a namespace by name).
    """
    from pathway_tpu.internals.compat import iterate_universe

    placeholders = {}
    for name, t in list(kwargs.items()):
        # pw.iterate_universe(t) marks a universe-iterated input; the
        # fixpoint semantics here iterate whole tables, which subsumes it
        if isinstance(t, iterate_universe):
            t = t.table
            kwargs[name] = t
        if not isinstance(t, Table):
            raise TypeError(f"iterate argument {name} must be a Table")
        placeholders[name] = Table(
            Plan("iter_placeholder", source_name=name), t.schema, Universe(),
            name=f"iter_{name}")

    result = func(**placeholders)

    single = False
    if isinstance(result, Table):
        # convention: a single returned table iterates the first input
        first = next(iter(kwargs))
        result_map = {first: result}
        single = True
    elif isinstance(result, dict):
        result_map = dict(result)
    elif hasattr(result, "_asdict"):
        result_map = dict(result._asdict())
    elif isinstance(result, tuple):
        result_map = {name: t for name, t in zip(kwargs, result)}
    else:
        raise TypeError("iterate body must return Table(s)")

    iterated_names = [n for n in kwargs if n in result_map]
    extra_names = [n for n in kwargs if n not in result_map]

    shared = IterateShared(
        input_tables=[kwargs[n] for n in iterated_names]
        + [kwargs[n] for n in extra_names],
        iterated_placeholders=[placeholders[n] for n in iterated_names],
        extra_placeholders=[placeholders[n] for n in extra_names],
        body_outputs=[result_map[n] for n in iterated_names],
        result_tables=list(result_map.values()),
        limit=iteration_limit,
    )

    outs = {}
    for i, (name, body_table) in enumerate(result_map.items()):
        plan = Plan("iterate_result", shared=shared, index=i)
        outs[name] = Table(plan, body_table.schema, Universe(),
                           name=f"iterated_{name}")
    if single:
        return next(iter(outs.values()))
    return _IterateResultNamespace(outs)
