"""Universe relation solver.

Reference: python/pathway/internals/universe_solver.py — a python-sat
constraint solver deciding subset/equality relations between universes
(key sets), powering ``with_universe_of`` validation and same-universe
operator checks. Only three kinds of facts are ever asserted (subset,
equality = two subsets, disjointness) and only those are queried, so a
relation graph with query-time transitive closure decides the asserted
entailments without a SAT dependency:

- subset: reachability in the directed superset graph;
- equality: subset both ways;
- disjointness: declared pairs, inherited downward (a ⊆ x, b ⊆ y,
  x ⊥ y ⇒ a ⊥ b).

One deliberate approximation vs the reference solver: union results
(concat/update_rows) record only the LOWER bounds input ⊆ result — the
upper bound "result ⊆ S whenever every input ⊆ S" is not derived, so
such checks fall back to runtime-keyed behavior instead of static proof.

Query-time closure also fixes the eager-snapshot design this replaces:
a promise recorded on a parent universe now holds for subuniverses
created EARLIER, matching the reference solver's behavior.
"""

from __future__ import annotations

import weakref

_PRUNE_EVERY = 4096  # fact insertions between garbage sweeps


class UniverseSolver:
    def __init__(self):
        self._supersets: dict[int, set[int]] = {}
        self._disjoint: set[frozenset] = set()
        # live Universe objects by id — dead ones get spliced out of the
        # relation graph (reachability-preserving), so a long-lived
        # process doesn't accumulate relations for dead pipelines forever
        self._registry: "weakref.WeakValueDictionary[int, object]" = (
            weakref.WeakValueDictionary())
        self._adds_since_prune = 0

    def reset(self) -> None:
        """Drop all relations — called by ParseGraph.clear()."""
        self._supersets.clear()
        self._disjoint.clear()
        self._adds_since_prune = 0

    def register(self, universe) -> None:
        self._registry[universe.id] = universe

    def _prune(self) -> None:
        """Splice garbage-collected universes out of the graph while
        preserving every entailment between LIVE universes: a dead node's
        incoming edges are rewired to its outgoing set, and disjoint
        pairs naming it are conservatively re-attributed to its
        predecessors (a ⊆ x†, x† ⊥ y still implies a ⊥ y)."""
        live = set(self._registry.keys())
        dead = [uid for uid in list(self._supersets) if uid not in live]
        for d in dead:
            outs = self._supersets.pop(d, set())
            outs.discard(d)
            preds = [sub for sub, sups in self._supersets.items()
                     if d in sups]
            for sub in preds:
                sups = self._supersets[sub]
                sups.discard(d)
                sups |= outs
            if self._disjoint:
                stale = [p for p in self._disjoint if d in p]
                for pair in stale:
                    self._disjoint.discard(pair)
                    (other,) = tuple(pair - {d}) or (d,)
                    for sub in preds:
                        self._disjoint.add(frozenset((sub, other)))
        self._adds_since_prune = 0

    # -- facts ------------------------------------------------------------
    def add_subset(self, sub_id: int, sup_id: int) -> None:
        self._supersets.setdefault(sub_id, set()).add(sup_id)
        self._adds_since_prune += 1
        if self._adds_since_prune >= _PRUNE_EVERY:
            self._prune()

    def add_equal(self, a_id: int, b_id: int) -> None:
        self.add_subset(a_id, b_id)
        self.add_subset(b_id, a_id)

    def add_disjoint(self, a_id: int, b_id: int) -> None:
        self._disjoint.add(frozenset((a_id, b_id)))

    # -- queries ----------------------------------------------------------
    def _ancestors(self, uid: int) -> set[int]:
        seen = {uid}
        stack = [uid]
        while stack:
            for sup in self._supersets.get(stack.pop(), ()):
                if sup not in seen:
                    seen.add(sup)
                    stack.append(sup)
        return seen

    def is_subset(self, sub_id: int, sup_id: int) -> bool:
        return sup_id in self._ancestors(sub_id)

    def are_equal(self, a_id: int, b_id: int) -> bool:
        return a_id == b_id or (
            self.is_subset(a_id, b_id) and self.is_subset(b_id, a_id))

    def are_disjoint(self, a_id: int, b_id: int) -> bool:
        if not self._disjoint:
            return False
        anc_a = self._ancestors(a_id)
        anc_b = self._ancestors(b_id)
        for pair in self._disjoint:
            x, y = tuple(pair) if len(pair) == 2 else (next(iter(pair)),) * 2
            if (x in anc_a and y in anc_b) or (y in anc_a and x in anc_b):
                return True
        return False


GLOBAL_SOLVER = UniverseSolver()
