"""Universe relation solver.

Reference: python/pathway/internals/universe_solver.py — a python-sat
constraint solver deciding subset/equality relations between universes
(key sets), powering ``with_universe_of`` validation and same-universe
operator checks. Only three kinds of facts are ever asserted (subset,
equality = two subsets, disjointness) and only those are queried, so a
relation graph with query-time transitive closure decides the asserted
entailments without a SAT dependency:

- subset: reachability in the directed superset graph;
- equality: subset both ways;
- disjointness: declared pairs, inherited downward (a ⊆ x, b ⊆ y,
  x ⊥ y ⇒ a ⊥ b).

One deliberate approximation vs the reference solver: union results
(concat/update_rows) record only the LOWER bounds input ⊆ result — the
upper bound "result ⊆ S whenever every input ⊆ S" is not derived, so
such checks fall back to runtime-keyed behavior instead of static proof.

Query-time closure also fixes the eager-snapshot design this replaces:
a promise recorded on a parent universe now holds for subuniverses
created EARLIER, matching the reference solver's behavior.
"""

from __future__ import annotations


class UniverseSolver:
    def __init__(self):
        self._supersets: dict[int, set[int]] = {}
        self._disjoint: set[frozenset] = set()

    def reset(self) -> None:
        """Drop all relations — called by ParseGraph.clear() so a
        long-lived process (notebook, server) doesn't accumulate
        relations for dead pipelines forever."""
        self._supersets.clear()
        self._disjoint.clear()

    # -- facts ------------------------------------------------------------
    def add_subset(self, sub_id: int, sup_id: int) -> None:
        self._supersets.setdefault(sub_id, set()).add(sup_id)

    def add_equal(self, a_id: int, b_id: int) -> None:
        self.add_subset(a_id, b_id)
        self.add_subset(b_id, a_id)

    def add_disjoint(self, a_id: int, b_id: int) -> None:
        self._disjoint.add(frozenset((a_id, b_id)))

    # -- queries ----------------------------------------------------------
    def _ancestors(self, uid: int) -> set[int]:
        seen = {uid}
        stack = [uid]
        while stack:
            for sup in self._supersets.get(stack.pop(), ()):
                if sup not in seen:
                    seen.add(sup)
                    stack.append(sup)
        return seen

    def is_subset(self, sub_id: int, sup_id: int) -> bool:
        return sup_id in self._ancestors(sub_id)

    def are_equal(self, a_id: int, b_id: int) -> bool:
        return a_id == b_id or (
            self.is_subset(a_id, b_id) and self.is_subset(b_id, a_id))

    def are_disjoint(self, a_id: int, b_id: int) -> bool:
        if not self._disjoint:
            return False
        anc_a = self._ancestors(a_id)
        anc_b = self._ancestors(b_id)
        for pair in self._disjoint:
            x, y = tuple(pair) if len(pair) == 2 else (next(iter(pair)),) * 2
            if (x in anc_a and y in anc_b) or (y in anc_a and x in anc_b):
                return True
        return False


GLOBAL_SOLVER = UniverseSolver()
