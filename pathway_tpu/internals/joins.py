"""Join DSL (reference: python/pathway/internals/joins.py, 1,419 LoC).

``t1.join(t2, t1.a == t2.b, how="left").select(...)`` — JoinResult carries
both sides + the on-condition; select/reduce lower to the engine
JoinOperator (result id = hash of side ids, reference dataflow.rs:2371).

>>> import pathway_tpu as pw
>>> l = pw.debug.table_from_markdown('''
... k | v
... a | 1
... b | 2
... ''')
>>> r = pw.debug.table_from_markdown('''
... k | w
... a | 10
... c | 30
... ''')
>>> pw.debug.compute_and_print(
...     l.join_left(r, l.k == r.k).select(l.k, l.v, r.w),
...     include_id=False)
k | v | w
a | 1 | 10
b | 2 |
"""

from __future__ import annotations

import enum

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.table import Plan, Table
from pathway_tpu.internals.type_inference import infer_dtype
from pathway_tpu.internals.universe import Universe


class JoinMode(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


class JoinResult:
    def __init__(self, left: Table, right: Table,
                 on: list[tuple[ex.ColumnExpression, ex.ColumnExpression]],
                 mode: str, id_expr=None, exact_match: bool = False):
        self._left = left
        self._right = right
        self._on = on
        self._mode = mode
        self._id_expr = id_expr

    @classmethod
    def create(cls, left: Table, right: Table, on, mode: str, id_expr,
               left_instance=None, right_instance=None) -> "JoinResult":
        pairs = []
        for cond in on:
            pairs.append(_split_condition(cond, left, right))
        if left_instance is not None and right_instance is not None:
            pairs.append((
                thisclass.resolve_this({"this": left, "left": left}, ex.wrap_arg(left_instance)),
                thisclass.resolve_this({"this": right, "right": right}, ex.wrap_arg(right_instance)),
            ))
        if isinstance(mode, JoinMode):
            mode = mode.value
        return cls(left, right, pairs, mode, id_expr)

    # -- result construction ------------------------------------------------
    def _resolve(self, e):
        proxy = _JoinThisProxy(self._left, self._right, self._mode)
        return thisclass.resolve_this(
            {"left": self._left, "right": self._right, "this": proxy}, e
        )

    def select(self, *args, **kwargs) -> Table:
        exprs: dict[str, ex.ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, thisclass.ThisRef):
                kind = arg._kind
                tab = {"left": self._left, "right": self._right}.get(kind)
                if tab is None:
                    for n in self._left.column_names():
                        exprs[n] = self._left[n]
                    for n in self._right.column_names():
                        if n not in exprs:
                            exprs[n] = self._right[n]
                else:
                    for n in tab.column_names():
                        exprs[n] = tab[n]
            elif isinstance(arg, ex.ColumnReference):
                exprs[arg.name] = self._resolve(arg)
            else:
                raise TypeError(f"bad positional select arg: {arg!r}")
        for name, e in kwargs.items():
            exprs[name] = self._resolve(ex.wrap_arg(e))

        # wrap dtypes Optional for the side that may be missing
        cols = {}
        for name, e in exprs.items():
            d = infer_dtype(e)
            side = _expr_side(e, self._left, self._right)
            if (side == "right" and self._mode in ("left", "outer")) or (
                    side == "left" and self._mode in ("right", "outer")):
                d = dt.Optional(d)
            cols[name] = sch.ColumnSchema(name=name, dtype=d)
        schema = sch.schema_from_columns(cols)
        plan = Plan(
            "join_select",
            left=self._left, right=self._right, on=self._on, mode=self._mode,
            id_expr=self._id_expr, exprs=list(exprs.values()),
            names=list(exprs.keys()),
        )
        universe = Universe()
        if self._id_expr is not None and isinstance(self._id_expr, ex.IdExpression):
            src = self._id_expr.table
            if src is self._left:
                universe = self._left._universe
            elif src is self._right:
                universe = self._right._universe
        return Table(plan, schema, universe)

    def reduce(self, *args, **kwargs) -> Table:
        return self._as_table().reduce(*args, **kwargs)

    def groupby(self, *args, **kwargs):
        resolved = [self._resolve(ex.wrap_arg(a)) for a in args]
        t = self._as_table()
        # re-point references at the materialized table by name
        mapped = []
        for e in resolved:
            if isinstance(e, ex.ColumnReference):
                mapped.append(t[e.name])
            else:
                mapped.append(e)
        return t.groupby(*mapped, **kwargs)

    def filter(self, expr) -> Table:
        return self._as_table().filter(
            _repoint(self._resolve(ex.wrap_arg(expr)), self))

    def _as_table(self) -> Table:
        exprs = {}
        for n in self._left.column_names():
            exprs[n] = self._left[n]
        for n in self._right.column_names():
            if n not in exprs:
                exprs[n] = self._right[n]
        return self.select(**exprs)


class _JoinThisProxy:
    """pw.this inside join select: unambiguous column from either side."""

    def __init__(self, left, right, mode):
        self._left = left
        self._right = right
        self._universe = None

    def __getitem__(self, name):
        in_left = name in self._left.column_names()
        in_right = name in self._right.column_names()
        if in_left and in_right:
            raise KeyError(
                f"column {name!r} exists on both sides; use pw.left/pw.right"
            )
        if in_left:
            return self._left[name]
        if in_right:
            return self._right[name]
        raise KeyError(name)


def _split_condition(cond, left: Table, right: Table):
    if not isinstance(cond, ex.BinaryExpression) or cond._op != "==":
        raise ValueError("join condition must be <left col> == <right col>")
    a, b = cond._left, cond._right
    a = thisclass.resolve_this({"left": left, "right": right, "this": left}, a)
    b = thisclass.resolve_this({"left": left, "right": right, "this": right}, b)
    a_side = _expr_side(a, left, right)
    b_side = _expr_side(b, left, right)
    if a_side == "right" or b_side == "left":
        a, b = b, a
    return (a, b)


def _expr_side(e, left, right):
    tables = set()

    def walk(x):
        if isinstance(x, ex.ColumnReference):
            if x.table is left:
                tables.add("left")
            elif x.table is right:
                tables.add("right")
        for d in getattr(x, "_deps", ()):
            walk(d)

    walk(e)
    if tables == {"left"}:
        return "left"
    if tables == {"right"}:
        return "right"
    return "both" if tables else "none"


def _repoint(expr, join_result):
    return expr
