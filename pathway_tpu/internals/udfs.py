"""UDF system: pw.udf decorator, executors, retries, caching.

Rebuild of the reference's udfs package (python/pathway/internals/udfs/ —
UDF class :68, executors.py:132-250, retries.py, caches.py:35,106). Sync
UDFs are dispatched once per engine batch; async UDFs gather a whole batch
concurrently on the shared event loop with capacity/timeout/retry —
async is concurrent within a batch, batches serialize (reference doc:
udfs/executors.py:160-165).
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import inspect
import os
import pickle
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex


# ---------------------------------------------------------------------------
# retry strategies — one shared implementation with connector supervision
# (internals/retries.py; reference: udfs/retries.py). Re-exported here so
# ``pw.udfs.FixedDelayRetryStrategy`` et al. keep their historical home.
# ---------------------------------------------------------------------------

from pathway_tpu.internals.retries import (  # noqa: F401
    AsyncRetryStrategy,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    NoRetryStrategy,
)


# ---------------------------------------------------------------------------
# cache strategies (reference: udfs/caches.py)
# ---------------------------------------------------------------------------

# process-wide default cache used by UDFs constructed without an explicit
# cache_strategy; activated by server run(with_cache=True, cache_backend=...)
# (reference: run kwargs with_cache/cache_backend wiring UDF-caching
# persistence mode, udfs/caches.py)
_DEFAULT_CACHE: "CacheStrategy | None" = None


def set_default_cache(strategy: "CacheStrategy | None") -> None:
    """Set the cache strategy applied to UDFs that did not pick their own.
    Applies to UDFs prepared after this call."""
    global _DEFAULT_CACHE
    _DEFAULT_CACHE = strategy


def get_default_cache() -> "CacheStrategy | None":
    return _DEFAULT_CACHE


class CacheStrategy:
    def wrap_async(self, fn: Callable) -> Callable:
        raise NotImplementedError

    def wrap_sync(self, fn: Callable) -> Callable:
        raise NotImplementedError

    @staticmethod
    def _key(name: str, args, kwargs) -> str:
        payload = pickle.dumps((name, args, tuple(sorted(kwargs.items()))),
                               protocol=4)
        return hashlib.blake2b(payload, digest_size=16).hexdigest()


class InMemoryCache(CacheStrategy):
    """Unbounded in-memory memoization (reference: async-lru based)."""

    def __init__(self, max_size: int | None = None):
        self.max_size = max_size
        self._store: dict[str, Any] = {}

    def wrap_sync(self, fn):
        name = getattr(fn, "__name__", "fn")

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            key = self._key(name, args, kwargs)
            if key in self._store:
                return self._store[key]
            result = fn(*args, **kwargs)
            self._put(key, result)
            return result

        return wrapper

    def wrap_async(self, fn):
        name = getattr(fn, "__name__", "fn")

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            key = self._key(name, args, kwargs)
            if key in self._store:
                return self._store[key]
            result = await fn(*args, **kwargs)
            self._put(key, result)
            return result

        return wrapper

    def _put(self, key, value):
        if self.max_size is not None and len(self._store) >= self.max_size:
            self._store.pop(next(iter(self._store)))
        self._store[key] = value


class DiskCache(CacheStrategy):
    """Pickle-file cache under PATHWAY_PERSISTENT_STORAGE or ./Cache
    (reference: diskcache-based UDF cache wired into persistence)."""

    def __init__(self, name: str | None = None):
        self.name = name
        self._dir: str | None = None

    def _ensure_dir(self) -> str:
        if self._dir is None:
            base = os.environ.get("PATHWAY_PERSISTENT_STORAGE", "./Cache")
            self._dir = os.path.join(base, "udf_cache", self.name or "default")
            os.makedirs(self._dir, exist_ok=True)
        return self._dir

    def _path(self, key: str) -> str:
        return os.path.join(self._ensure_dir(), key + ".pkl")

    def _get(self, key):
        path = self._path(key)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return True, pickle.load(f)
        return False, None

    def _put(self, key, value):
        path = self._path(key)
        with open(path + ".tmp", "wb") as f:
            pickle.dump(value, f)
        os.replace(path + ".tmp", path)

    def wrap_sync(self, fn):
        name = getattr(fn, "__name__", "fn")

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            key = self._key(name, args, kwargs)
            hit, val = self._get(key)
            if hit:
                return val
            result = fn(*args, **kwargs)
            self._put(key, result)
            return result

        return wrapper

    def wrap_async(self, fn):
        name = getattr(fn, "__name__", "fn")

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            key = self._key(name, args, kwargs)
            hit, val = self._get(key)
            if hit:
                return val
            result = await fn(*args, **kwargs)
            self._put(key, result)
            return result

        return wrapper


DefaultCache = DiskCache


# ---------------------------------------------------------------------------
# executors (reference: udfs/executors.py)
# ---------------------------------------------------------------------------

class Executor:
    kind = "auto"

    def __init__(self, *, capacity: int | None = None, timeout: float | None = None,
                 retry_strategy: AsyncRetryStrategy | None = None):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy


class AutoExecutor(Executor):
    kind = "auto"


class SyncExecutor(Executor):
    kind = "sync"


class AsyncExecutor(Executor):
    kind = "async"


class FullyAsyncExecutor(Executor):
    kind = "fully_async"

    def __init__(self, *, autocommit_duration_ms: int | None = 1500, **kw):
        super().__init__(**kw)
        self.autocommit_duration_ms = autocommit_duration_ms


def auto_executor(**kw) -> Executor:
    return AutoExecutor(**kw)


def sync_executor(**kw) -> Executor:
    return SyncExecutor(**kw)


def async_executor(*, capacity: int | None = None, timeout: float | None = None,
                   retry_strategy: AsyncRetryStrategy | None = None) -> Executor:
    return AsyncExecutor(capacity=capacity, timeout=timeout,
                         retry_strategy=retry_strategy)


def fully_async_executor(*, capacity: int | None = None,
                         timeout: float | None = None,
                         retry_strategy: AsyncRetryStrategy | None = None,
                         autocommit_duration_ms: int | None = 1500) -> Executor:
    return FullyAsyncExecutor(capacity=capacity, timeout=timeout,
                              retry_strategy=retry_strategy,
                              autocommit_duration_ms=autocommit_duration_ms)


def _wrap_async(fn, executor: Executor, cache_strategy: CacheStrategy | None):
    """Apply retry/timeout/capacity/cache layers to an async callable."""
    wrapped = fn
    if executor.retry_strategy is not None:
        strategy = executor.retry_strategy
        inner_r = wrapped

        @functools.wraps(fn)
        async def with_retry(*args, **kwargs):
            return await strategy.invoke(inner_r, *args, **kwargs)

        wrapped = with_retry
    if executor.timeout is not None:
        timeout = executor.timeout
        inner_t = wrapped

        @functools.wraps(fn)
        async def with_timeout(*args, **kwargs):
            return await asyncio.wait_for(inner_t(*args, **kwargs), timeout)

        wrapped = with_timeout
    if executor.capacity is not None:
        capacity = executor.capacity
        sem_holder: list = []
        inner_c = wrapped

        @functools.wraps(fn)
        async def with_capacity(*args, **kwargs):
            if not sem_holder:
                sem_holder.append(asyncio.Semaphore(capacity))
            async with sem_holder[0]:
                return await inner_c(*args, **kwargs)

        wrapped = with_capacity
    if cache_strategy is not None:
        wrapped = cache_strategy.wrap_async(wrapped)
    return wrapped


class UDF:
    """User-defined function usable in expressions: ``my_udf(t.a, t.b)``.

    Subclass and define ``__wrapped__``, or produce via the ``@pw.udf``
    decorator (reference: udfs/__init__.py:68).
    """

    def __init__(self, *, return_type: Any = None, deterministic: bool = False,
                 propagate_none: bool = False, executor: Executor | None = None,
                 cache_strategy: CacheStrategy | None = None,
                 max_batch_size: int | None = None, batch: bool = False,
                 device: bool = False):
        self.return_type = return_type
        self.deterministic = deterministic
        self.propagate_none = propagate_none
        self.executor = executor or AutoExecutor()
        self.cache_strategy = cache_strategy
        self.max_batch_size = max_batch_size
        # batch=True → __wrapped__ receives whole columns (lists) and
        # returns a list (columnar TPU/vectorized dispatch; sync only)
        self.batch = batch
        # device=True → the batch dispatch is accelerator work (jax/XLA):
        # the scheduler may overlap it with the next tick's host work via
        # the device bridge (PATHWAY_DEVICE_INFLIGHT)
        self.device = device
        self._prepared: Callable | None = None

    # subclasses override
    def __wrapped__(self, *args, **kwargs):
        raise NotImplementedError

    @property
    def func(self) -> Callable:
        return type(self).__wrapped__.__get__(self)  # bound

    def _infer_return_type(self, fn) -> Any:
        if self.return_type is not None:
            return self.return_type
        try:
            import typing

            hints = typing.get_type_hints(fn)
        except Exception:
            return dt.ANY
        ret = hints.get("return")
        return ret if ret is not None else dt.ANY

    def _prepare(self):
        if self._prepared is not None:
            return self._prepared, self._is_async
        if self.cache_strategy is None:
            self.cache_strategy = get_default_cache()
        fn = self.func
        is_coro = inspect.iscoroutinefunction(fn) or inspect.iscoroutinefunction(
            getattr(fn, "__wrapped__", None))
        kind = self.executor.kind
        if kind == "auto":
            kind = "async" if is_coro else "sync"
        if kind in ("async", "fully_async"):
            if not is_coro:
                base = fn

                async def as_async(*args, **kwargs):
                    return base(*args, **kwargs)

                fn = as_async
            fn = _wrap_async(fn, self.executor, self.cache_strategy)
            self._is_async = True
        else:
            if is_coro:
                raise TypeError("sync executor cannot run a coroutine function")
            if self.cache_strategy is not None:
                fn = self.cache_strategy.wrap_sync(fn)
            self._is_async = False
        self._prepared = fn
        return fn, self._is_async

    def prepared_async(self) -> Callable:
        """Async callable with this UDF's retry/timeout/capacity/cache
        wrapping applied — for direct (non-column) invocation, e.g. the
        adaptive RAG loop calling a chat model outside the engine."""
        fn, is_async = self._prepare()
        if is_async:
            return fn

        async def as_async(*args, **kwargs):
            return fn(*args, **kwargs)

        return as_async

    def __call__(self, *args, **kwargs) -> ex.ColumnExpression:
        fn, is_async = self._prepare()
        ret = self._infer_return_type(self.func)
        cls: type = ex.ApplyExpression
        if isinstance(self.executor, FullyAsyncExecutor):
            cls = ex.FullyAsyncApplyExpression
        elif is_async:
            cls = ex.AsyncApplyExpression
        if self.batch and cls is not ex.ApplyExpression:
            raise TypeError("batch=True UDFs must be sync")
        return cls(
            fn, ret, *args,
            propagate_none=self.propagate_none,
            deterministic=self.deterministic,
            max_batch_size=self.max_batch_size,
            batch=self.batch,
            device=self.device,
            **kwargs,
        )


class _FunctionUDF(UDF):
    def __init__(self, fn: Callable, **kwargs):
        super().__init__(**kwargs)
        self._fn = fn
        functools.update_wrapper(self, fn)

    @property
    def func(self) -> Callable:
        return self._fn


def udf(fun: Callable | None = None, /, *, return_type: Any = None,
        deterministic: bool = False, propagate_none: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        max_batch_size: int | None = None, batch: bool = False,
        device: bool = False):
    """Decorator turning a Python function into a column UDF."""

    def wrapper(f):
        return _FunctionUDF(
            f, return_type=return_type, deterministic=deterministic,
            propagate_none=propagate_none, executor=executor,
            cache_strategy=cache_strategy, max_batch_size=max_batch_size,
            batch=batch, device=device,
        )

    if fun is not None:
        return wrapper(fun)
    return wrapper


# coerce async results synchronously (used by vector store etc.)
def coerce_async(fn: Callable) -> Callable:
    if inspect.iscoroutinefunction(fn):
        return fn

    import functools

    @functools.wraps(fn)  # keep name/doc/annotations for type inference
    async def as_async(*args, **kwargs):
        return fn(*args, **kwargs)

    return as_async



# -- deprecated public aliases/helpers (reference udfs __all__) -------------

def udf_async(fun=None, *, capacity=None, timeout=None,
              retry_strategy=None, cache_strategy=None, **kwargs):
    """Deprecated alias of ``udf`` for async callables; the reference's
    capacity/timeout/retry_strategy kwargs map onto an async executor."""
    if capacity is not None or timeout is not None \
            or retry_strategy is not None:
        kwargs.setdefault("executor", async_executor(
            capacity=capacity, timeout=timeout,
            retry_strategy=retry_strategy))
    if cache_strategy is not None:
        kwargs.setdefault("cache_strategy", cache_strategy)
    return udf(fun, **kwargs) if fun is not None else udf(**kwargs)


class UDFSync(UDF):
    """Deprecated alias of UDF (sync path)."""


class UDFAsync(UDF):
    """Deprecated alias of UDF (async path)."""


def _rewrapped(fn, options: dict):
    exec_ = async_executor(
        capacity=options.get("capacity"),
        timeout=options.get("timeout"),
        retry_strategy=options.get("retry_strategy"))
    return _wrap_async(coerce_async(fn), exec_,
                       options.get("cache_strategy"))


def async_options(**options):
    """Decorator applying async-execution options (capacity/timeout/
    retry_strategy/cache_strategy) to a coroutine function
    (reference: udfs.async_options)."""

    def wrapper(fn):
        return _rewrapped(fn, options)

    return wrapper


def with_capacity(fn, capacity: int):
    return _rewrapped(fn, {"capacity": capacity})


def with_timeout(fn, timeout: float):
    return _rewrapped(fn, {"timeout": timeout})


def with_retry_strategy(fn, retry_strategy):
    return _rewrapped(fn, {"retry_strategy": retry_strategy})


def with_cache_strategy(fn, cache_strategy):
    return _rewrapped(fn, {"cache_strategy": cache_strategy})
