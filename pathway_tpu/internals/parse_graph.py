"""Global registry of outputs/sinks collected as user code runs
(reference: python/pathway/internals/parse_graph.py — here the Table plans
form the DAG themselves; the registry only tracks run-time bindings).

Each output is recorded as an :class:`OutputBinding` carrying not just the
binder closure (consumed by ``pw.run``) but also the bound table and sink
metadata, so the static analyzer (internals/static_check/) can reason about
which tables reach a sink and whether the sink's declared format can carry
the table's schema — without executing anything. A weak registry of every
constructed Table powers the dead-dataflow check.
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class OutputBinding:
    """One registered sink: binder fn(runner) plus static metadata."""

    binder: Callable
    table: Any = None  # the Table routed to this sink (None: opaque binder)
    sink: str = "sink"  # connector name, e.g. "fs", "postgres", "subscribe"
    format: str | None = None  # sink serialization format when declared


class ParseGraph:
    def __init__(self):
        # each binding's binder: fn(runner) -> None, attaches sinks/subscribers
        self.outputs: list[OutputBinding] = []
        self.has_streaming_sources = False
        # every Table constructed since the last clear(), weakly held —
        # the static analyzer's universe for dead-dataflow detection
        self._tables: "weakref.WeakSet[Any]" = weakref.WeakSet()

    @property
    def output_binders(self) -> list[Callable]:
        return [o.binder for o in self.outputs]

    def add_output(self, binder: Callable, *, table: Any = None,
                   sink: str = "sink", format: str | None = None) -> None:
        self.outputs.append(
            OutputBinding(binder, table=table, sink=sink, format=format))

    def register_table(self, table: Any) -> None:
        self._tables.add(table)

    def tables(self) -> list[Any]:
        """Live tables constructed since the last clear()."""
        return list(self._tables)

    def clear(self) -> None:
        self.outputs.clear()
        self.has_streaming_sources = False
        self._tables = weakref.WeakSet()
        from pathway_tpu.internals.universe_solver import GLOBAL_SOLVER

        GLOBAL_SOLVER.reset()


G = ParseGraph()
