"""Global registry of outputs/sinks collected as user code runs
(reference: python/pathway/internals/parse_graph.py — here the Table plans
form the DAG themselves; the registry only tracks run-time bindings)."""

from __future__ import annotations

from typing import Any, Callable


class ParseGraph:
    def __init__(self):
        # each binder: fn(runner) -> None, attaches sinks/subscribers
        self.output_binders: list[Callable] = []
        self.has_streaming_sources = False

    def add_output(self, binder: Callable) -> None:
        self.output_binders.append(binder)

    def clear(self) -> None:
        self.output_binders.clear()
        self.has_streaming_sources = False
        from pathway_tpu.internals.universe_solver import GLOBAL_SOLVER

        GLOBAL_SOLVER.reset()


G = ParseGraph()
