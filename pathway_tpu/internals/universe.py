"""Universes — key-set identity of tables
(reference: python/pathway/internals/universe.py + universe_solver.py).

We track universe identity and explicit promises instead of running the
reference's SAT solver; operations requiring same/sub-universes check
identity or a recorded promise and otherwise defer to keyed engine ops,
which are correct regardless (keys align or don't at runtime).
"""

from __future__ import annotations

import itertools

_ids = itertools.count()


class Universe:
    __slots__ = ("id", "supersets")

    def __init__(self):
        self.id = next(_ids)
        self.supersets: set[int] = {self.id}

    def subuniverse(self) -> "Universe":
        u = Universe()
        u.supersets |= self.supersets
        return u

    def is_subset_of(self, other: "Universe") -> bool:
        return other.id in self.supersets

    def is_equal_to(self, other: "Universe") -> bool:
        return self is other or (
            self.is_subset_of(other) and other.is_subset_of(self)
        )

    def promise_is_subset_of(self, other: "Universe") -> None:
        self.supersets |= other.supersets

    def __repr__(self):
        return f"<Universe {self.id}>"
