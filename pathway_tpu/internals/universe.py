"""Universes — key-set identity of tables
(reference: python/pathway/internals/universe.py + universe_solver.py).

Relations (subset / equality / disjointness) live in the process-wide
``UniverseSolver`` (internals/universe_solver.py) and are decided by
query-time transitive closure, so a promise recorded on a parent holds
for subuniverses created before OR after the promise — the entailment
behavior of the reference's SAT-based solver, without the python-sat
dependency.
"""

from __future__ import annotations

import itertools

from pathway_tpu.internals.universe_solver import GLOBAL_SOLVER

_ids = itertools.count()


class Universe:
    __slots__ = ("id", "__weakref__")

    def __init__(self):
        self.id = next(_ids)
        GLOBAL_SOLVER.register(self)

    def subuniverse(self) -> "Universe":
        u = Universe()
        GLOBAL_SOLVER.add_subset(u.id, self.id)
        return u

    def is_subset_of(self, other: "Universe") -> bool:
        return GLOBAL_SOLVER.is_subset(self.id, other.id)

    def is_equal_to(self, other: "Universe") -> bool:
        return self is other or GLOBAL_SOLVER.are_equal(self.id, other.id)

    def is_disjoint_from(self, other: "Universe") -> bool:
        return GLOBAL_SOLVER.are_disjoint(self.id, other.id)

    def promise_is_subset_of(self, other: "Universe") -> None:
        GLOBAL_SOLVER.add_subset(self.id, other.id)

    def promise_is_disjoint_from(self, other: "Universe") -> None:
        GLOBAL_SOLVER.add_disjoint(self.id, other.id)

    def __repr__(self):
        return f"<Universe {self.id}>"
