"""Class (row) transformers — the ``@pw.transformer`` legacy API
(reference: internals/row_transformer.py:294 + engine complex_columns;
graph_runner/row_transformer_operator_handler.py).

A transformer declares inner ``ClassArg`` classes, one per input table:
``input_attribute()`` fields mirror input columns; ``@output_attribute``
methods compute new columns and may chase pointers into any of the
transformer's tables (``self.transformer.other[ptr].attr``), including
references into *output* attributes of other rows.

Execution model here: the transformer's tables are gathered whole (one
batched dispatch — the engine's incremental whole-table fold, like
apply_all_rows), attributes are evaluated lazily with memoization
host-side, and results are re-keyed to the source rows. The reference
evaluates the same dependency graph row-by-row inside the engine
(complex_columns); capability and observable semantics match, granularity
of incrementality is whole-table per changed input batch."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.keys import Pointer, hash_values
from pathway_tpu.internals.table import Table


class _InputAttribute:
    """Descriptor: reads the row's input column through the evaluator."""

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj._ev.value(obj._class_name, obj.id, self.name)


class _ComputedAttribute:
    """Descriptor for @output_attribute / @attribute: accessing it yields
    the computed (memoized) value, not the function."""

    def __init__(self, fn: Callable, kind: str):
        self.fn = fn
        self._pw_kind = kind

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj._ev.value(obj._class_name, obj.id, self.name)


def input_attribute(type: Any = float) -> Any:  # noqa: A002
    return _InputAttribute()


def output_attribute(fn: Callable) -> _ComputedAttribute:
    return _ComputedAttribute(fn, "output")


def attribute(fn: Callable) -> _ComputedAttribute:
    """Computed helper attribute (not emitted as an output column)."""
    return _ComputedAttribute(fn, "attribute")


def method(fn: Callable) -> Callable:
    fn._pw_kind = "method"
    return fn


def input_method(type: Any = float) -> Callable:  # noqa: A002
    def deco(fn):
        fn._pw_kind = "method"
        return fn

    return deco


class ClassArg:
    """Base class for transformer inner classes (reference ClassArg:148)."""

    def __init__(self, evaluator: "_Evaluator", class_name: str, key: Pointer):
        self._ev = evaluator
        self._class_name = class_name
        self.id = key

    def pointer_from(self, *args, optional=False):
        return hash_values(*args)

    @property
    def transformer(self):
        return self._ev.namespace


class _TableIndex:
    def __init__(self, evaluator: "_Evaluator", class_name: str):
        self._ev = evaluator
        self._class_name = class_name

    def __getitem__(self, key: Pointer) -> ClassArg:
        return self._ev.proxy(self._class_name, key)


class _ClassNamespace:
    """``self.transformer.<table>[ptr]`` → row proxy of another table."""

    def __init__(self, evaluator: "_Evaluator"):
        self._ev = evaluator

    def __getattr__(self, name: str) -> _TableIndex:
        return _TableIndex(self._ev, name)


class _Evaluator:
    """Lazy, memoized evaluation of all attributes over materialized rows."""

    def __init__(self, classes: dict, tables: dict):
        # tables: class_name → {key → {col: value}}
        self.classes = classes
        self.tables = tables
        self._memo: dict[tuple, Any] = {}
        self._in_progress: set[tuple] = set()
        self.namespace = _ClassNamespace(self)

    def proxy(self, class_name: str, key: Pointer) -> ClassArg:
        return self.classes[class_name](self, class_name, key)

    def value(self, class_name: str, key, name: str):
        row = self.tables[class_name].get(key)
        if row is not None and name in row:
            return row[name]
        member = getattr(self.classes[class_name], name, None)
        if isinstance(member, _ComputedAttribute):
            memo_key = (class_name, key, name)
            if memo_key in self._memo:
                return self._memo[memo_key]
            if memo_key in self._in_progress:
                raise RecursionError(
                    f"cyclic attribute dependency at {class_name}.{name}")
            self._in_progress.add(memo_key)
            try:
                result = member.fn(self.proxy(class_name, key))
            finally:
                self._in_progress.discard(memo_key)
            self._memo[memo_key] = result
            return result
        raise AttributeError(
            f"transformer class {class_name!r}: row {key} has no "
            f"attribute {name!r}")


def _output_names(cls) -> list[str]:
    return [n for n, m in vars(cls).items()
            if isinstance(m, _ComputedAttribute) and m._pw_kind == "output"]


def transformer(cls) -> "_TransformerFactory":
    classes = {name: member for name, member in vars(cls).items()
               if isinstance(member, type) and issubclass(member, ClassArg)}
    return _TransformerFactory(cls.__name__, classes)


class _TransformerFactory:
    def __init__(self, name: str, classes: dict[str, type]):
        self.name = name
        self.classes = classes

    def __call__(self, **tables: Table):
        import pathway_tpu.internals.reducers_frontend as reducers

        missing = set(self.classes) - set(tables)
        if missing:
            raise TypeError(f"transformer {self.name} missing tables: "
                            f"{sorted(missing)}")

        # gather every input table whole (one sorted_tuple fold per table)
        order = list(self.classes)
        col_names = {}
        base = None
        for idx, cname in enumerate(order):
            t = tables[cname]
            names = t.column_names()
            col_names[cname] = names
            p = t.select(row=ex.apply(
                lambda rid, *vals: (int(rid), *vals), t.id,
                *[t[n] for n in names]))
            rt = p.reduce(rows=reducers.sorted_tuple(p.row))
            if base is None:
                base = rt.select(**{f"_pw_{idx}": rt.rows})
            else:
                jr = base.join(rt, ex.wrap_arg(0) == ex.wrap_arg(0),
                               id=base.id)
                base = jr.select(
                    **{c: base[c] for c in base.column_names()},
                    **{f"_pw_{idx}": rt.rows})

        classes = self.classes
        cols = col_names

        def run_all(*packed_rows):
            state = {}
            for cname, rows in zip(order, packed_rows):
                state[cname] = {
                    Pointer(r[0]): dict(zip(cols[cname], r[1:]))
                    for r in rows
                }
            ev = _Evaluator(classes, state)
            out = []
            for cname in order:
                names = _output_names(classes[cname])
                table_out = []
                for key in state[cname]:
                    vals = tuple(ev.value(cname, key, n) for n in names)
                    table_out.append((int(key), *vals))
                out.append(tuple(table_out))
            return tuple(out)

        results = base.select(out=ex.apply(
            run_all, *[base[f"_pw_{i}"] for i in range(len(order))]))

        class _Result:
            pass

        result = _Result()
        for idx, cname in enumerate(order):
            out_attrs = _output_names(classes[cname])
            per_table = results.select(rows=ex.apply(
                lambda o, _i=idx: o[_i], results.out))
            flat = per_table.flatten(per_table.rows)
            keyed = flat.select(
                _pw_id=ex.apply(lambda r: Pointer(r[0]), flat.rows),
                **{n: ex.apply(lambda r, _j=j: r[_j + 1], flat.rows)
                   for j, n in enumerate(out_attrs)})
            setattr(result, cname,
                    keyed.with_id(keyed._pw_id).without("_pw_id"))
        return result
