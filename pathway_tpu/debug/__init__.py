"""pw.debug — static table construction + deterministic printing
(reference: python/pathway/debug/__init__.py:48-489).

`table_from_markdown` + `compute_and_print` are the backbone of the test
harness (SURVEY §4: the markdown-table → captured-diff-stream pattern):

>>> import pathway_tpu as pw
>>> t = pw.debug.table_from_markdown(\'\'\'
... city   | temp
... Lagos  | 33
... Oslo   | 4
... \'\'\')
>>> pw.debug.compute_and_print(
...     t.select(t.city, f=t.temp * 9 // 5 + 32), include_id=False)
city | f
Lagos | 91
Oslo | 39
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np
import pandas as pd

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import (Pointer, hash_values,
                                        hash_values_uncached)
from pathway_tpu.internals.runner import GraphRunner, run_tables
from pathway_tpu.internals.table import Plan, Table
from pathway_tpu.internals.universe import Universe

_SPECIAL = ("_time", "_diff", "__time__", "__diff__")


def _parse_value(tok: str) -> Any:
    tok = tok.strip()
    if tok in ("", "None"):
        return None
    if tok == "True":
        return True
    if tok == "False":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    if len(tok) >= 2 and tok[0] in "\"'" and tok[-1] == tok[0]:
        return tok[1:-1]
    return tok


def _split_cells(line: str) -> list[str]:
    """Split a markdown row into cells, preserving a leading empty cell
    (the reference's implicit-id header format: ``  | owner | pet``)."""
    cells = [c.strip() for c in line.strip().split("|")]
    while cells and cells[-1] == "":  # trailing pipe
        cells.pop()
    return cells


def table_from_markdown(txt: str, *, id_from=None, unsafe_trusted_ids=False,
                        schema: type[sch.Schema] | None = None,
                        _stream: bool = False) -> Table:
    lines = [l for l in txt.strip().splitlines()
             if l.strip() and not set(l.strip()) <= {"-", "|", " ", "+"}]
    header = _split_cells(lines[0])
    has_id = bool(header) and header[0] in ("", "id")
    if has_id:
        header = ["id"] + header[1:]
    rows_raw = []
    for line in lines[1:]:
        toks = _split_cells(line)
        rows_raw.append([_parse_value(t) for t in toks])
    col_names = [h for h in header if h not in _SPECIAL and h != "id"]
    time_idx = next((i for i, h in enumerate(header) if h in ("_time", "__time__")), None)
    diff_idx = next((i for i, h in enumerate(header) if h in ("_diff", "__diff__")), None)
    name_idx = {h: i for i, h in enumerate(header)}

    keys, rows, times, diffs = [], [], [], []
    for rix, raw in enumerate(rows_raw):
        if has_id:
            keys.append(hash_values("md-id", raw[0]))
        elif id_from:
            keys.append(hash_values(*[raw[name_idx[c]] for c in id_from]))
        elif diff_idx is not None:
            # with retractions, identical rows must share a key so -1 cancels +1
            keys.append(hash_values(
                "md-val", *[raw[name_idx[c]] if name_idx[c] < len(raw) else None
                            for c in col_names]))
        else:
            keys.append(hash_values("md-row", rix))
        rows.append(tuple(raw[name_idx[c]] if name_idx[c] < len(raw) else None
                          for c in col_names))
        times.append(int(raw[time_idx]) if time_idx is not None else 0)
        diffs.append(int(raw[diff_idx]) if diff_idx is not None else 1)

    if schema is not None:
        the_schema = schema
        dtypes = [the_schema[c].dtype for c in col_names]
        rows = [tuple(dt.coerce_value(v, d) for v, d in zip(r, dtypes))
                for r in rows]
    else:
        cols = {}
        for i, c in enumerate(col_names):
            vals = [r[i] for r in rows]
            cols[c] = sch.ColumnSchema(name=c, dtype=_infer_col_dtype(vals))
        the_schema = sch.schema_from_columns(cols)
        dtypes = [the_schema[c].dtype for c in col_names]
        rows = [tuple(dt.coerce_value(v, d) for v, d in zip(r, dtypes))
                for r in rows]

    plan = Plan("static", keys=keys, rows=rows,
                times=times if (time_idx is not None or _stream) else None,
                diffs=diffs if diff_idx is not None else None)
    return Table(plan, the_schema, Universe())


def _infer_col_dtype(vals) -> dt.DType:
    non_null = [v for v in vals if v is not None]
    opt = len(non_null) < len(vals)
    if not non_null:
        return dt.ANY
    types = {type(v) for v in non_null}
    if types <= {bool}:
        base = dt.BOOL
    elif types <= {int}:
        base = dt.INT
    elif types <= {int, float}:
        base = dt.FLOAT if float in types else dt.INT
    elif types <= {str}:
        base = dt.STR
    else:
        base = dt.ANY
    return dt.Optional(base) if opt else base


# alias used pervasively in reference tests
parse_to_table = table_from_markdown


def table_from_rows(schema: type[sch.Schema], rows: list[tuple],
                    unsafe_trusted_ids: bool = False, is_stream: bool = False) -> Table:
    """rows: tuples of column values, optionally + (time, diff) when is_stream."""
    col_names = schema.column_names()
    keys, data, times, diffs = [], [], [], []
    for rix, row in enumerate(rows):
        if is_stream:
            *vals, t, d = row
        else:
            vals, t, d = list(row), 0, 1
        # rix makes every key unique, so skip the memo cache; values are
        # hashed natively (_encode_value covers every engine type, with a
        # repr fallback for exotic objects) — an extra repr() per value
        # here was ~15% of the ETL source path
        keys.append(hash_values_uncached("row", rix, *vals))
        data.append(tuple(vals))
        times.append(int(t))
        diffs.append(int(d))
    plan = Plan("static", keys=keys, rows=data,
                times=times if is_stream else None,
                diffs=diffs if is_stream else None)
    return Table(plan, schema, Universe())


def table_from_pandas(df: pd.DataFrame, *, id_from=None,
                      unsafe_trusted_ids: bool = False,
                      schema: type[sch.Schema] | None = None) -> Table:
    if schema is None:
        schema = sch.schema_from_pandas(df, id_from=id_from)
    col_names = schema.column_names()
    keys, rows = [], []
    for rix, (idx, row) in enumerate(df.iterrows()):
        if id_from:
            keys.append(hash_values(*[row[c] for c in id_from]))
        else:
            keys.append(hash_values("md-row", rix))
        rows.append(tuple(dt.normalize_scalar(row[c]) if c in df.columns else None
                          for c in col_names))
    plan = Plan("static", keys=keys, rows=rows, times=None, diffs=None)
    return Table(plan, schema, Universe())


def table_to_pandas(table: Table, *, include_id: bool = True) -> pd.DataFrame:
    [cap] = run_tables(table)
    state = cap.snapshot()
    names = table.column_names()
    records = []
    index = []
    for key in sorted(state, key=int):
        row = state[key]
        index.append(key)
        records.append(dict(zip(names, row)))
    df = pd.DataFrame.from_records(records, columns=names)
    if include_id:
        df.index = index
    return df


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, str):
        return v
    if isinstance(v, Pointer):
        return str(v)
    return repr(v)


def table_to_markdown(table: Table, *, include_id: bool = True,
                      n_rows: int | None = None) -> str:
    """Bounded snapshot rendered as the markdown-ish table format
    ``table_from_markdown`` parses (round-trippable)."""
    [cap] = run_tables(table)
    state = cap.snapshot()
    names = table.column_names()
    items = sorted(state.items(), key=lambda kv: _row_sort_key(kv[1], kv[0]))
    if n_rows is not None:
        items = items[:n_rows]
    cols = (["id"] if include_id else []) + names
    lines = [" | ".join(cols)]
    for key, row in items:
        cells = ([str(key)] if include_id else []) + [_fmt(v) for v in row]
        lines.append(" | ".join(cells))
    return "\n".join(lines)


def compute_and_print(table: Table, *, include_id: bool = True,
                      short_pointers: bool = True, n_rows: int | None = None,
                      squash_updates: bool = True, terminate_on_error: bool = True,
                      file=None) -> None:
    print(table_to_markdown(table, include_id=include_id, n_rows=n_rows),
          file=file)


def _row_sort_key(row, key):
    out = []
    for v in row:
        if isinstance(v, (bool, int, float)) and not isinstance(v, Pointer):
            out.append((0, float(v), ""))
        elif isinstance(v, str):
            out.append((1, 0.0, v))
        else:
            out.append((2, 0.0, repr(v)))
    out.append((3, float(int(key) % 10**9), ""))
    return tuple(out)


def compute_and_print_update_stream(table: Table, *, include_id: bool = True,
                                    short_pointers: bool = True,
                                    n_rows: int | None = None,
                                    terminate_on_error: bool = True,
                                    file=None) -> None:
    [cap] = run_tables(table)
    names = table.column_names()
    events = cap.consolidated_events()
    events.sort(key=lambda e: (e[2], _row_sort_key(e[1], e[0])))
    if n_rows is not None:
        events = events[:n_rows]
    cols = (["id"] if include_id else []) + names + ["__time__", "__diff__"]
    lines = [" | ".join(cols)]
    for key, row, time, diff in events:
        cells = ([str(key)] if include_id else []) + [_fmt(v) for v in row] + [
            str(time), str(diff)]
        lines.append(" | ".join(cells))
    print("\n".join(lines), file=file)


class StreamGenerator:
    """Programmatic multi-batch stream builder for tests
    (reference: debug/__init__.py StreamGenerator — batches become
    consecutive engine timestamps; the by-workers variant merges worker
    shards, since sharding here is by key, not by emitting worker)."""

    def __init__(self):
        self._count = 0

    def _next_name(self) -> str:
        self._count += 1
        return f"stream_generator_{self._count}"

    def table_from_list_of_batches(self, batches: list[list[dict]],
                                   schema: type[sch.Schema]) -> Table:
        """Each inner list lands at one (increasing) logical time."""
        names = schema.column_names()
        rows = []
        for t, batch in enumerate(batches):
            for values in batch:
                rows.append(tuple(values[n] for n in names) + (t + 1, 1))
        table = table_from_rows(schema, rows, is_stream=True)
        table._name = self._next_name()
        return table

    def table_from_list_of_batches_by_workers(
            self, batches: list[dict[int, list[dict]]],
            schema: type[sch.Schema]) -> Table:
        merged = [[values for shard in batch.values() for values in shard]
                  for batch in batches]
        return self.table_from_list_of_batches(merged, schema)

    def table_from_markdown(self, table: str) -> Table:
        """Markdown with a ``_time`` (and optional ``_diff``) column."""
        return table_from_markdown(table)


def table_to_dicts(table: Table):
    """(keys, {column -> {key -> value}}) of the table's final state
    (reference: debug/__init__.py:61)."""
    [cap] = run_tables(table)
    state = cap.snapshot()
    keys = list(state.keys())
    names = table.column_names()
    columns = {
        name: {key: state[key][i] for key in keys}
        for i, name in enumerate(names)
    }
    return keys, columns


def table_from_parquet(path, id_from=None, unsafe_trusted_ids=False) -> Table:
    """Parquet file → table via pandas (reference: debug/__init__.py:457)."""
    df = pd.read_parquet(path)
    return table_from_pandas(df, id_from=id_from,
                             unsafe_trusted_ids=unsafe_trusted_ids)


def table_to_parquet(table: Table, filename):
    """Table's final state → Parquet via pandas
    (reference: debug/__init__.py:474)."""
    df = table_to_pandas(table, include_id=False)
    return df.to_parquet(filename)
