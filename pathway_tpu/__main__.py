"""``python -m pathway_tpu`` → the CLI (reference: `pathway` console script)."""

from pathway_tpu.cli import main

main()
