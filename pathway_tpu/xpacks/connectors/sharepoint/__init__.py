"""pw.xpacks.connectors.sharepoint — SharePoint document reader
(reference: python/pathway/xpacks/connectors/sharepoint/__init__.py:249 —
a polling ConnectorSubject listing a folder over the SharePoint REST API;
there it is entitlement-gated and driven through the office365 package).

Here the REST protocol is spoken directly (``/_api/web/GetFolderByServer
RelativeUrl(...)``): folder listing, recursive descent, ``$value``
downloads, modified-time change detection with retractions. No license
gate. Authentication is pluggable like pw.io.gdrive: pass ``access_token``
or ``token_provider`` (an Azure AD bearer token for the site); the
reference's certificate flow (tenant/client_id/cert_path/thumbprint)
requires RSA signing and is gated on `msal` being installed.
"""

from __future__ import annotations

import time as _time

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Plan, Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._datasource import (CollectSession, DataSource,
                                         Session, apply_connector_policy)


def _token_provider_from_cert(url, tenant, client_id, cert_path, thumbprint):
    try:
        import msal  # type: ignore
    except ImportError as e:
        raise ImportError(
            "certificate authentication needs msal (RSA-signed client "
            "assertions); pass access_token= or token_provider= instead — "
            "the SharePoint REST protocol itself runs without it") from e
    from urllib.parse import urlparse

    host = urlparse(url).netloc
    app = msal.ConfidentialClientApplication(
        client_id,
        authority=f"https://login.microsoftonline.com/{tenant}",
        client_credential={
            "private_key": open(cert_path).read(),
            "thumbprint": thumbprint,
        })

    def provider():
        result = app.acquire_token_for_client(
            scopes=[f"https://{host.split('/')[0]}/.default"])
        if "access_token" not in result:
            raise RuntimeError(f"sharepoint auth failed: {result}")
        return result["access_token"]

    return provider


class SharePointSource(DataSource):
    name = "sharepoint"

    def __init__(self, schema, *, url: str, root_path: str, token_provider,
                 mode: str, recursive: bool, object_size_limit: int | None,
                 with_metadata: bool, refresh_interval: int,
                 autocommit_duration_ms=1500):
        super().__init__(schema, autocommit_duration_ms)
        self.url = url.rstrip("/")
        self.root_path = root_path
        self.token_provider = token_provider
        self.mode = mode
        self.recursive = recursive
        self.object_size_limit = object_size_limit
        self.with_metadata = with_metadata
        self.refresh_interval = refresh_interval
        self._seq = 0

    def _headers(self) -> dict:
        tok = self.token_provider()
        h = {"Accept": "application/json;odata=verbose"}
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _get(self, http, api_path: str, **kw):
        resp = http.get(f"{self.url}/_api/web/{api_path}",
                        headers=self._headers(), timeout=60, **kw)
        resp.raise_for_status()
        return resp

    def _list_folder(self, http, folder: str) -> tuple[list[dict], list[str]]:
        """(files, subfolder server-relative urls)."""
        enc = folder.replace("'", "''")
        files = self._get(
            http, f"GetFolderByServerRelativeUrl('{enc}')/Files"
        ).json()["d"]["results"]
        subfolders = []
        if self.recursive:
            for f in self._get(
                    http, f"GetFolderByServerRelativeUrl('{enc}')/Folders"
            ).json()["d"]["results"]:
                name = f.get("Name", "")
                if not name.startswith("Forms"):
                    subfolders.append(f["ServerRelativeUrl"])
        return files, subfolders

    def _scan(self, http) -> dict[str, dict]:
        out: dict[str, dict] = {}
        stack = [self.root_path]
        seen = set()
        while stack:
            folder = stack.pop()
            if folder in seen:
                continue
            seen.add(folder)
            files, subfolders = self._list_folder(http, folder)
            stack.extend(subfolders)
            for f in files:
                size = int(f.get("Length") or 0)
                if self.object_size_limit is not None \
                        and size > self.object_size_limit:
                    continue
                out[f["ServerRelativeUrl"]] = f
        return out

    def _download(self, http, server_relative_url: str) -> bytes:
        enc = server_relative_url.replace("'", "''")
        return self._get(
            http, f"GetFileByServerRelativeUrl('{enc}')/$value").content

    def _meta(self, f: dict) -> Json:
        return Json({
            "path": f.get("ServerRelativeUrl"),
            "name": f.get("Name"),
            "size": int(f.get("Length") or 0),
            "created_at": f.get("TimeCreated"),
            "modified_at": f.get("TimeLastModified"),
        })

    def _poll_once(self, http, session, emitted: dict) -> None:
        listing = self._scan(http)
        for path in list(emitted):
            if path not in listing:
                _mt, key, row = emitted.pop(path)
                session.push(key, row, -1)
        for path, f in listing.items():
            mtime = f.get("TimeLastModified")
            prev = emitted.get(path)
            if prev is not None and prev[0] == mtime:
                continue
            content = self._download(http, path)
            values = {"data": content}
            if self.with_metadata:
                values["_metadata"] = self._meta(f)
            key, row = self.row_to_engine(values, self._seq)
            self._seq += 1
            if prev is not None:
                session.push(prev[1], prev[2], -1)
            session.push(key, row, 1)
            emitted[path] = (mtime, key, row)

    def run(self, session: Session) -> None:
        import logging

        import requests

        http = requests.Session()
        emitted: dict[str, tuple] = {}
        backoff = 1.0
        while not session.stop_requested:
            try:
                self._poll_once(http, session, emitted)
                backoff = 1.0
            except (requests.RequestException, OSError) as e:
                if self.mode != "streaming":
                    raise
                logging.getLogger(__name__).warning(
                    "sharepoint poll failed (%s); retrying in %.0fs",
                    e, backoff)
                if not session.sleep(backoff):
                    return
                backoff = min(backoff * 2, 60.0)
                continue
            if self.mode != "streaming":
                return
            if not session.sleep(self.refresh_interval):
                return


def read(url: str, *,
         tenant: str | None = None,
         client_id: str | None = None,
         cert_path: str | None = None,
         thumbprint: str | None = None,
         root_path: str,
         mode: str = "streaming",
         recursive: bool = True,
         object_size_limit: int | None = None,
         with_metadata: bool = False,
         refresh_interval: int = 30,
         access_token: str | None = None,
         token_provider=None,
         name: str | None = None,
         persistent_id: str | None = None,
         autocommit_duration_ms: int | None = 1500,
         connector_policy=None) -> Table:
    """Read a SharePoint directory (recursively) or file as binary `data`
    rows (reference signature, sharepoint/__init__.py:249-262, plus the
    pluggable-auth extension)."""
    if mode not in ("streaming", "static"):
        raise ValueError(f"Unrecognized connector mode: {mode}")
    if token_provider is None:
        if access_token is not None:
            token_provider = lambda: access_token  # noqa: E731
        elif cert_path is not None:
            token_provider = _token_provider_from_cert(
                url, tenant, client_id, cert_path, thumbprint)
        else:
            raise ValueError(
                "pass access_token/token_provider, or the certificate "
                "flow's tenant/client_id/cert_path/thumbprint")
    if with_metadata:
        schema = sch.schema_from_types(data=dt.BYTES, _metadata=Json)
    else:
        schema = sch.schema_from_types(data=dt.BYTES)
    source = SharePointSource(
        schema, url=url, root_path=root_path, token_provider=token_provider,
        mode=mode, recursive=recursive,
        object_size_limit=object_size_limit, with_metadata=with_metadata,
        refresh_interval=refresh_interval,
        autocommit_duration_ms=autocommit_duration_ms)
    source.persistent_id = persistent_id or name
    apply_connector_policy(source, {}, policy=connector_policy)
    if mode == "static":
        sess = CollectSession()
        source.run(sess)
        keys = list(sess.state)
        rows = [sess.state[k] for k in keys]
        return Table(Plan("static", keys=keys, rows=rows, times=None,
                          diffs=None), schema, Universe(),
                     name=name or "sharepoint_static")
    return Table(Plan("input", datasource=source), schema, Universe(),
                 name=name or "sharepoint_input")
