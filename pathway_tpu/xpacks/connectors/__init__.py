"""pw.xpacks.connectors — enterprise connectors (reference:
python/pathway/xpacks/connectors)."""

from pathway_tpu.xpacks.connectors import sharepoint  # noqa: F401
