"""Dependency-free document text extraction: PDF, DOCX, PPTX, HTML.

Backs ParseUnstructured when the `unstructured` package is absent
(reference: xpacks/llm/parsers.py ParseUnstructured — there the heavy
lifting is the unstructured-io library; here the common formats are parsed
directly: PDF content streams are tokenized after FlateDecode, OOXML is
zip+XML via the stdlib, HTML via html.parser).

PDF scope: simple-font text operators (Tj/TJ/'/") in FlateDecode or plain
streams — covers machine-generated text PDFs; CID-keyed/Type0 subset fonts
need a full CMap implementation and come out garbled (the reference's
answer there is also an external library).
"""

from __future__ import annotations

import io
import re
import zipfile
import zlib
from html.parser import HTMLParser
from xml.etree import ElementTree


# ---------------------------------------------------------------------------
# format sniffing
# ---------------------------------------------------------------------------

def detect_format(raw: bytes) -> str:
    if raw[:5] == b"%PDF-":
        return "pdf"
    if raw[:2] == b"PK":
        try:
            with zipfile.ZipFile(io.BytesIO(raw)) as z:
                names = set(z.namelist())
        except zipfile.BadZipFile:
            return "binary"
        if "word/document.xml" in names:
            return "docx"
        if any(n.startswith("ppt/slides/") for n in names):
            return "pptx"
        if any(n.startswith("xl/") for n in names):
            return "xlsx"
        return "zip"
    head = raw[:1024].lstrip().lower()
    if head.startswith(b"<!doctype html") or head.startswith(b"<html") \
            or b"<body" in head:
        return "html"
    return "text"


# ---------------------------------------------------------------------------
# PDF
# ---------------------------------------------------------------------------

_STREAM_RE = re.compile(rb"<<(.*?)>>\s*stream\r?\n", re.DOTALL)
_STRING_TOKEN = re.compile(
    rb"\((?:\\.|[^\\()])*\)"      # (literal string) with escapes
    rb"|<[0-9A-Fa-f\s]*>"          # <hex string>
    rb"|\[|\]"
    rb"|[A-Za-z'\"*]+"             # operators
    rb"|[-+.0-9]+"                 # numbers
)
_ESCAPES = {
    ord("n"): "\n", ord("r"): "\r", ord("t"): "\t", ord("b"): "\b",
    ord("f"): "\f", ord("("): "(", ord(")"): ")", ord("\\"): "\\",
}


def _decode_pdf_string(tok: bytes) -> str:
    if tok.startswith(b"<"):
        hexstr = re.sub(rb"\s", b"", tok[1:-1])
        if len(hexstr) % 2:
            hexstr += b"0"
        try:
            return bytes.fromhex(hexstr.decode()).decode(
                "latin-1", errors="replace")
        except ValueError:
            return ""
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == 0x5C and i + 1 < len(body):  # backslash
            nxt = body[i + 1]
            if nxt in _ESCAPES:
                out.append(_ESCAPES[nxt])
                i += 2
                continue
            if 0x30 <= nxt <= 0x37:  # octal \ddd
                j = i + 1
                digits = b""
                while j < len(body) and len(digits) < 3 \
                        and 0x30 <= body[j] <= 0x37:
                    digits += bytes([body[j]])
                    j += 1
                out.append(chr(int(digits, 8)))
                i = j
                continue
            i += 1
            continue
        out.append(chr(c))
        i += 1
    return "".join(out)


def _extract_content_text(content: bytes) -> str:
    """Tokenize one content stream, keeping text-showing operators."""
    lines: list[str] = []
    current: list[str] = []
    pending: list[str] = []  # strings seen since the last operator
    for m in _STRING_TOKEN.finditer(content):
        tok = m.group(0)
        c = tok[:1]
        if c == b"(" or c == b"<":
            pending.append(_decode_pdf_string(tok))
        elif c.isalpha() or tok in (b"'", b'"'):
            op = tok
            if op in (b"Tj", b"TJ"):
                current.extend(pending)
            elif op in (b"'", b'"'):
                # move-to-next-line + show
                if current:
                    lines.append("".join(current))
                    current = []
                current.extend(pending)
            elif op in (b"Td", b"TD", b"T*"):
                if current:
                    lines.append("".join(current))
                    current = []
            elif op == b"ET":
                if current:
                    lines.append("".join(current))
                    current = []
            pending = []
        elif tok in (b"[", b"]"):
            continue
        # numbers: ignored (kerning/positions)
    if current:
        lines.append("".join(current))
    return "\n".join(line for line in lines if line.strip())


def extract_pdf(raw: bytes) -> list[str]:
    """Text of each content stream (≈ page) in document order."""
    pages: list[str] = []
    pos = 0
    while True:
        m = _STREAM_RE.search(raw, pos)
        if m is None:
            break
        start = m.end()
        end = raw.find(b"endstream", start)
        if end < 0:
            break
        data = raw[start:end].rstrip(b"\r\n")
        header = m.group(1)
        if b"FlateDecode" in header:
            try:
                data = zlib.decompress(data)
            except zlib.error:
                pos = end + 9
                continue
        if b"BT" in data:
            text = _extract_content_text(data)
            if text:
                pages.append(text)
        pos = end + 9
    return pages


# ---------------------------------------------------------------------------
# OOXML (docx / pptx) + HTML
# ---------------------------------------------------------------------------

_W_NS = "{http://schemas.openxmlformats.org/wordprocessingml/2006/main}"
_A_NS = "{http://schemas.openxmlformats.org/drawingml/2006/main}"


def extract_docx(raw: bytes) -> list[str]:
    """Paragraph texts from word/document.xml."""
    with zipfile.ZipFile(io.BytesIO(raw)) as z:
        tree = ElementTree.fromstring(z.read("word/document.xml"))
    out = []
    for para in tree.iter(f"{_W_NS}p"):
        text = "".join(t.text or "" for t in para.iter(f"{_W_NS}t"))
        if text.strip():
            out.append(text)
    return out


def extract_pptx(raw: bytes) -> list[str]:
    """One text blob per slide, in slide order."""
    with zipfile.ZipFile(io.BytesIO(raw)) as z:
        slides = sorted(
            (n for n in z.namelist()
             if re.fullmatch(r"ppt/slides/slide\d+\.xml", n)),
            key=lambda n: int(re.search(r"\d+", n).group()))
        out = []
        for name in slides:
            tree = ElementTree.fromstring(z.read(name))
            texts = [t.text or "" for t in tree.iter(f"{_A_NS}t")]
            blob = "\n".join(t for t in texts if t.strip())
            if blob:
                out.append(blob)
    return out


class _TextHTMLParser(HTMLParser):
    _SKIP = {"script", "style", "head", "noscript", "template"}
    _BREAKS = {"p", "div", "br", "li", "tr", "h1", "h2", "h3", "h4", "h5",
               "h6", "section", "article", "table"}

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.parts: list[str] = []
        self._skip_depth = 0

    def handle_starttag(self, tag, attrs):
        if tag in self._SKIP:
            self._skip_depth += 1
        elif tag in self._BREAKS:
            self.parts.append("\n")

    def handle_endtag(self, tag):
        if tag in self._SKIP and self._skip_depth:
            self._skip_depth -= 1
        elif tag in self._BREAKS:
            self.parts.append("\n")

    def handle_data(self, data):
        if not self._skip_depth and data.strip():
            self.parts.append(data)


def extract_html(raw: bytes) -> list[str]:
    parser = _TextHTMLParser()
    parser.feed(raw.decode("utf-8", errors="replace"))
    text = "".join(parser.parts)
    return [line.strip() for line in text.splitlines() if line.strip()]


# ---------------------------------------------------------------------------
# unified entry
# ---------------------------------------------------------------------------

def extract_elements(raw: bytes) -> list[tuple[str, dict]]:
    """[(text, metadata)] for any supported format — the shape
    ParseUnstructured's elements mode returns."""
    fmt = detect_format(raw)
    if fmt == "pdf":
        return [(text, {"page_number": i + 1, "category": "Page",
                        "filetype": "pdf"})
                for i, text in enumerate(extract_pdf(raw))]
    if fmt == "docx":
        return [(text, {"category": "Paragraph", "filetype": "docx"})
                for text in extract_docx(raw)]
    if fmt == "pptx":
        return [(text, {"page_number": i + 1, "category": "Slide",
                        "filetype": "pptx"})
                for i, text in enumerate(extract_pptx(raw))]
    if fmt == "html":
        return [(text, {"category": "Text", "filetype": "html"})
                for text in extract_html(raw)]
    if fmt in ("xlsx", "zip", "binary"):
        # decoding known-binary formats as UTF-8 would index mojibake as
        # if it were text — fail loudly like the pre-fallback behavior
        raise ValueError(
            f"unsupported document format {fmt!r}: the dependency-free "
            "extractors cover pdf/docx/pptx/html/plain text; install "
            "`unstructured` for other formats")
    return [(raw.decode("utf-8", errors="replace"),
             {"category": "Text", "filetype": "text"})]
