"""Dependency-free document text extraction: PDF, DOCX, PPTX, HTML.

Backs ParseUnstructured when the `unstructured` package is absent
(reference: xpacks/llm/parsers.py ParseUnstructured — there the heavy
lifting is the unstructured-io library; here the common formats are parsed
directly: PDF content streams are tokenized after FlateDecode, OOXML is
zip+XML via the stdlib, HTML via html.parser).

PDF scope: simple-font text operators (Tj/TJ/'/") in FlateDecode or plain
streams — covers machine-generated text PDFs; CID-keyed/Type0 subset fonts
need a full CMap implementation and come out garbled (the reference's
answer there is also an external library).
"""

from __future__ import annotations

import io
import re
import zipfile
import zlib
from html.parser import HTMLParser
from xml.etree import ElementTree


# ---------------------------------------------------------------------------
# format sniffing
# ---------------------------------------------------------------------------

def detect_format(raw: bytes) -> str:
    if raw[:5] == b"%PDF-":
        return "pdf"
    if raw[:2] == b"PK":
        try:
            with zipfile.ZipFile(io.BytesIO(raw)) as z:
                names = set(z.namelist())
        except zipfile.BadZipFile:
            return "binary"
        if "word/document.xml" in names:
            return "docx"
        if any(n.startswith("ppt/slides/") for n in names):
            return "pptx"
        if any(n.startswith("xl/") for n in names):
            return "xlsx"
        return "zip"
    head = raw[:1024].lstrip().lower()
    if head.startswith(b"<!doctype html") or head.startswith(b"<html") \
            or b"<body" in head:
        return "html"
    return "text"


# ---------------------------------------------------------------------------
# PDF
# ---------------------------------------------------------------------------

_STREAM_RE = re.compile(rb"<<(.*?)>>\s*stream\r?\n", re.DOTALL)
_STRING_TOKEN = re.compile(
    rb"\((?:\\.|[^\\()])*\)"      # (literal string) with escapes
    rb"|<[0-9A-Fa-f\s]*>"          # <hex string>
    rb"|\[|\]"
    rb"|[A-Za-z'\"*]+"             # operators
    rb"|[-+.0-9]+"                 # numbers
)
_ESCAPES = {
    ord("n"): "\n", ord("r"): "\r", ord("t"): "\t", ord("b"): "\b",
    ord("f"): "\f", ord("("): "(", ord(")"): ")", ord("\\"): "\\",
}


def _decode_pdf_string(tok: bytes) -> str:
    if tok.startswith(b"<"):
        hexstr = re.sub(rb"\s", b"", tok[1:-1])
        if len(hexstr) % 2:
            hexstr += b"0"
        try:
            return bytes.fromhex(hexstr.decode()).decode(
                "latin-1", errors="replace")
        except ValueError:
            return ""
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == 0x5C and i + 1 < len(body):  # backslash
            nxt = body[i + 1]
            if nxt in _ESCAPES:
                out.append(_ESCAPES[nxt])
                i += 2
                continue
            if 0x30 <= nxt <= 0x37:  # octal \ddd
                j = i + 1
                digits = b""
                while j < len(body) and len(digits) < 3 \
                        and 0x30 <= body[j] <= 0x37:
                    digits += bytes([body[j]])
                    j += 1
                out.append(chr(int(digits, 8)))
                i = j
                continue
            i += 1
            continue
        out.append(chr(c))
        i += 1
    return "".join(out)


def _extract_content_text(content: bytes) -> str:
    """Tokenize one content stream, keeping text-showing operators."""
    lines: list[str] = []
    current: list[str] = []
    pending: list[str] = []  # strings seen since the last operator
    for m in _STRING_TOKEN.finditer(content):
        tok = m.group(0)
        c = tok[:1]
        if c == b"(" or c == b"<":
            pending.append(_decode_pdf_string(tok))
        elif c.isalpha() or tok in (b"'", b'"'):
            op = tok
            if op in (b"Tj", b"TJ"):
                current.extend(pending)
            elif op in (b"'", b'"'):
                # move-to-next-line + show
                if current:
                    lines.append("".join(current))
                    current = []
                current.extend(pending)
            elif op in (b"Td", b"TD", b"T*", b"Tm"):
                if current:
                    lines.append("".join(current))
                    current = []
            elif op == b"ET":
                if current:
                    lines.append("".join(current))
                    current = []
            pending = []
        elif tok in (b"[", b"]"):
            continue
        # numbers: ignored (kerning/positions)
    if current:
        lines.append("".join(current))
    return "\n".join(line for line in lines if line.strip())


def _iter_content_streams(raw: bytes):
    """Decompressed content streams containing text blocks, in document
    order — the ONE stream walk both text and table extraction share."""
    pos = 0
    while True:
        m = _STREAM_RE.search(raw, pos)
        if m is None:
            return
        start = m.end()
        end = raw.find(b"endstream", start)
        if end < 0:
            return
        data = raw[start:end].rstrip(b"\r\n")
        if b"FlateDecode" in m.group(1):
            try:
                data = zlib.decompress(data)
            except zlib.error:
                pos = end + 9
                continue
        if b"BT" in data:
            yield data
        pos = end + 9


def extract_pdf(raw: bytes) -> list[str]:
    """Text of each content stream (≈ page) in document order."""
    pages: list[str] = []
    for data in _iter_content_streams(raw):
        text = _extract_content_text(data)
        if text:
            pages.append(text)
    return pages


# ---------------------------------------------------------------------------
# PDF tables (positional layout analysis)
# ---------------------------------------------------------------------------

def _positioned_items(content: bytes) -> list[tuple[float, float, str]]:
    """(x, y, text) for every text-showing op, tracking the text-positioning
    operators (Tm/Td/TD/T*) — the coordinates machine-generated tables are
    laid out with. Rotation/scaling are ignored (tables are axis-aligned)."""
    items: list[tuple[float, float, str]] = []
    lx = ly = 0.0   # current line origin
    leading = 12.0  # TL; TD sets it to -ty
    operands: list[float] = []
    pending: list[str] = []
    for m in _STRING_TOKEN.finditer(content):
        tok = m.group(0)
        c = tok[:1]
        if c == b"(" or c == b"<":
            pending.append(_decode_pdf_string(tok))
            continue
        if tok in (b"[", b"]"):
            continue
        if not (c.isalpha() or tok in (b"'", b'"')):
            try:
                operands.append(float(tok))
            except ValueError:
                pass
            continue
        op = tok
        if op == b"Tm" and len(operands) >= 6:
            lx, ly = operands[-2], operands[-1]
        elif op in (b"Td", b"TD") and len(operands) >= 2:
            lx += operands[-2]
            ly += operands[-1]
            if op == b"TD":
                leading = -operands[-1] or leading
        elif op == b"TL" and operands:
            leading = operands[-1]
        elif op == b"T*":
            ly -= leading
        elif op in (b"'", b'"'):
            ly -= leading
            if pending:
                items.append((lx, ly, "".join(pending)))
        elif op in (b"Tj", b"TJ"):
            if pending:
                items.append((lx, ly, "".join(pending)))
        elif op == b"BT":
            lx = ly = 0.0
        operands = []
        pending = []
    return items


def _detect_tables(items: list[tuple[float, float, str]],
                   y_tol: float = 3.0, x_tol: float = 6.0
                   ) -> list[list[list[str]]]:
    """Tables from positioned text: cluster items into visual rows by y,
    take runs of >= 2 consecutive rows with >= 2 cells each, and assign
    cells to columns clustered over the run's x starts."""
    if not items:
        return []
    # visual rows: same-y items, top to bottom
    rows: list[tuple[float, list[tuple[float, str]]]] = []
    for x, y, text in sorted(items, key=lambda it: (-it[1], it[0])):
        if not text.strip():
            continue
        if rows and abs(rows[-1][0] - y) <= y_tol:
            rows[-1][1].append((x, text))
        else:
            rows.append((y, [(x, text)]))
    tables: list[list[list[str]]] = []
    run: list[list[tuple[float, str]]] = []

    def flush_run():
        if len(run) < 2:
            return
        # columns: cluster x starts across the run
        xs = sorted({x for cells in run for x, _ in cells})
        cols: list[float] = []
        for x in xs:
            if not cols or x - cols[-1] > x_tol:
                cols.append(x)
        if len(cols) < 2:
            return
        out_rows = []
        for cells in run:
            out = [""] * len(cols)
            for x, text in sorted(cells):
                ci = min(range(len(cols)), key=lambda i: abs(cols[i] - x))
                out[ci] = (out[ci] + " " + text).strip()
            out_rows.append(out)
        tables.append(out_rows)

    for _y, cells in rows:
        if len(cells) >= 2:
            run.append(cells)
        else:
            flush_run()
            run = []
    flush_run()
    return tables


def extract_pdf_tables(raw: bytes) -> list[dict]:
    """[{page, rows}] — structured cell rows for every table-shaped layout
    region (reference scope: openparse's table extraction,
    xpacks/llm/_openparse_utils.py). Pages are numbered exactly like
    extract_pdf numbers them: streams yielding no text don't count."""
    out: list[dict] = []
    page = 0
    for data in _iter_content_streams(raw):
        if not _extract_content_text(data):
            continue
        page += 1
        for rows in _detect_tables(_positioned_items(data)):
            out.append({"page": page, "rows": rows})
    return out


def _rows_to_markdown(rows: list[list[str]]) -> str:
    lines = [" | ".join(r) for r in rows]
    if len(lines) > 1:
        lines.insert(1, " | ".join("---" for _ in rows[0]))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# OOXML (docx / pptx) + HTML
# ---------------------------------------------------------------------------

_W_NS = "{http://schemas.openxmlformats.org/wordprocessingml/2006/main}"
_A_NS = "{http://schemas.openxmlformats.org/drawingml/2006/main}"


def extract_docx(raw: bytes, skip_table_paragraphs: bool = False
                 ) -> list[str]:
    """Paragraph texts from word/document.xml. With
    ``skip_table_paragraphs`` the paragraphs living inside w:tbl cells are
    left to extract_docx_tables — element extraction must not index the
    same cell text twice."""
    with zipfile.ZipFile(io.BytesIO(raw)) as z:
        tree = ElementTree.fromstring(z.read("word/document.xml"))
    in_table: set[int] = set()
    if skip_table_paragraphs:
        for tbl in tree.iter(f"{_W_NS}tbl"):
            for p in tbl.iter(f"{_W_NS}p"):
                in_table.add(id(p))
    out = []
    for para in tree.iter(f"{_W_NS}p"):
        if id(para) in in_table:
            continue
        text = "".join(t.text or "" for t in para.iter(f"{_W_NS}t"))
        if text.strip():
            out.append(text)
    return out


def extract_docx_tables(raw: bytes) -> list[list[list[str]]]:
    """Structured cell rows for every w:tbl in the document."""
    with zipfile.ZipFile(io.BytesIO(raw)) as z:
        tree = ElementTree.fromstring(z.read("word/document.xml"))
    tables = []
    for tbl in tree.iter(f"{_W_NS}tbl"):
        rows = []
        for tr in tbl.iter(f"{_W_NS}tr"):
            cells = []
            for tc in tr.iter(f"{_W_NS}tc"):
                cells.append("".join(
                    t.text or "" for t in tc.iter(f"{_W_NS}t")).strip())
            if cells:
                rows.append(cells)
        if rows:
            tables.append(rows)
    return tables


def extract_pptx(raw: bytes) -> list[str]:
    """One text blob per slide, in slide order."""
    with zipfile.ZipFile(io.BytesIO(raw)) as z:
        slides = sorted(
            (n for n in z.namelist()
             if re.fullmatch(r"ppt/slides/slide\d+\.xml", n)),
            key=lambda n: int(re.search(r"\d+", n).group()))
        out = []
        for name in slides:
            tree = ElementTree.fromstring(z.read(name))
            texts = [t.text or "" for t in tree.iter(f"{_A_NS}t")]
            blob = "\n".join(t for t in texts if t.strip())
            if blob:
                out.append(blob)
    return out


class _TextHTMLParser(HTMLParser):
    _SKIP = {"script", "style", "head", "noscript", "template"}
    _BREAKS = {"p", "div", "br", "li", "tr", "h1", "h2", "h3", "h4", "h5",
               "h6", "section", "article", "table"}

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.parts: list[str] = []
        self._skip_depth = 0

    def handle_starttag(self, tag, attrs):
        if tag in self._SKIP:
            self._skip_depth += 1
        elif tag in self._BREAKS:
            self.parts.append("\n")

    def handle_endtag(self, tag):
        if tag in self._SKIP and self._skip_depth:
            self._skip_depth -= 1
        elif tag in self._BREAKS:
            self.parts.append("\n")

    def handle_data(self, data):
        if not self._skip_depth and data.strip():
            self.parts.append(data)


def extract_html(raw: bytes) -> list[str]:
    parser = _TextHTMLParser()
    parser.feed(raw.decode("utf-8", errors="replace"))
    text = "".join(parser.parts)
    return [line.strip() for line in text.splitlines() if line.strip()]


# ---------------------------------------------------------------------------
# unified entry
# ---------------------------------------------------------------------------

def extract_elements(raw: bytes) -> list[tuple[str, dict]]:
    """[(text, metadata)] for any supported format — the shape
    ParseUnstructured's elements mode returns."""
    fmt = detect_format(raw)
    if fmt == "pdf":
        # one walk: page text and tables together, with table cell lines
        # removed from the page body so cell text is indexed exactly once
        out: list[tuple[str, dict]] = []
        page = 0
        for data in _iter_content_streams(raw):
            text = _extract_content_text(data)
            if not text:
                continue
            page += 1
            tables = _detect_tables(_positioned_items(data))
            cells = {c for rows in tables for row in rows for c in row}
            body = "\n".join(line for line in text.splitlines()
                             if line.strip() not in cells)
            if body.strip():
                out.append((body, {"page_number": page, "category": "Page",
                                   "filetype": "pdf"}))
            for rows in tables:
                out.append((_rows_to_markdown(rows),
                            {"page_number": page, "category": "Table",
                             "filetype": "pdf", "rows": rows}))
        return out
    if fmt == "docx":
        out = [(text, {"category": "Paragraph", "filetype": "docx"})
               for text in extract_docx(raw, skip_table_paragraphs=True)]
        for rows in extract_docx_tables(raw):
            out.append((_rows_to_markdown(rows),
                        {"category": "Table", "filetype": "docx",
                         "rows": rows}))
        return out
    if fmt == "pptx":
        return [(text, {"page_number": i + 1, "category": "Slide",
                        "filetype": "pptx"})
                for i, text in enumerate(extract_pptx(raw))]
    if fmt == "html":
        return [(text, {"category": "Text", "filetype": "html"})
                for text in extract_html(raw)]
    if fmt in ("xlsx", "zip", "binary"):
        # decoding known-binary formats as UTF-8 would index mojibake as
        # if it were text — fail loudly like the pre-fallback behavior
        raise ValueError(
            f"unsupported document format {fmt!r}: the dependency-free "
            "extractors cover pdf/docx/pptx/html/plain text; install "
            "`unstructured` for other formats")
    return [(raw.decode("utf-8", errors="replace"),
             {"category": "Text", "filetype": "text"})]
