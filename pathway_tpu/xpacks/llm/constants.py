"""Shared defaults (reference: xpacks/llm/constants.py)."""

DEFAULT_VISION_MODEL = "gpt-4o"
