"""VectorStoreServer — the live document-indexing pipeline + REST serving.

Rebuild of the reference's vector store (xpacks/llm/vector_store.py:41-745):
document sources → parser UDF → post-processors → splitter → flatten →
embedder → TPU KNN index, with retrieve / statistics / inputs REST
endpoints answered against the live index (query_as_of_now). The embedding
+ index path is the BASELINE.md headline workload; with
``JaxEncoderEmbedder`` the whole forward runs batched on the MXU.
"""

from __future__ import annotations

import json as _json
import threading
from typing import Any, Callable

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json
from pathway_tpu.stdlib.indexing import (
    DataIndex,
    default_usearch_knn_document_index,
)
from pathway_tpu.xpacks.llm._utils import _unwrap_udf


class VectorStoreServer:
    """Builds the live indexing graph over one or more document sources
    (reference vector_store.py:41,214-292).

    Sources must have columns ``data`` (bytes/str) and optionally
    ``_metadata`` (Json). ``embedder`` is a UDF str → vector;
    ``parser`` maps raw bytes → list[(text, meta)]; ``splitter`` maps
    text → list[(chunk, meta)].
    """

    def __init__(self, *docs, embedder, parser: Callable | None = None,
                 splitter: Callable | None = None,
                 doc_post_processors: list[Callable] | None = None,
                 index_factory=None, index_builder: Callable | None = None):
        self.docs = list(docs)
        self.embedder = embedder
        self.parser = parser
        self.splitter = splitter
        self.doc_post_processors = doc_post_processors or []
        self.index_factory = index_factory
        self.index_builder = index_builder

        if hasattr(embedder, "get_embedding_dimension"):
            self.embedding_dimension = embedder.get_embedding_dimension()
        else:
            probe = _unwrap_udf(embedder)(".")
            self.embedding_dimension = int(np.asarray(probe).shape[0])

        self._graph = self._build_graph()

    # ------------------------------------------------------------------
    @classmethod
    def from_langchain_components(cls, *docs, embedder, parser=None,
                                  splitter=None, **kwargs):
        """Adapter for LangChain embeddings + text splitters (reference
        vector_store.py:97)."""
        emb = embedder

        @udfs.udf
        async def lc_embed(text: str) -> np.ndarray:
            return np.asarray(await emb.aembed_query(text))

        lc_splitter = None
        if splitter is not None:
            def lc_split(text: str) -> list[tuple[str, dict]]:
                return [(chunk, {}) for chunk in splitter.split_text(text)]
            lc_splitter = lc_split
        return cls(*docs, embedder=lc_embed, parser=parser,
                   splitter=lc_splitter, **kwargs)

    @classmethod
    def from_llamaindex_components(cls, *docs, transformations, parser=None,
                                   **kwargs):
        """Adapter for a LlamaIndex transformation stack (reference
        vector_store.py:141): last transformation must be an embedder."""
        from pathway_tpu.xpacks.llm._utils import _import_or_raise

        _import_or_raise("llama_index.core", "from_llamaindex_components")
        from llama_index.core.base.embeddings.base import BaseEmbedding
        from llama_index.core.ingestion.pipeline import run_transformations
        from llama_index.core.schema import MetadataMode, TextNode

        if not transformations:
            raise ValueError("transformations cannot be empty")
        if not isinstance(transformations[-1], BaseEmbedding):
            raise ValueError(
                "last transformation must be an embedder, got "
                f"{type(transformations[-1])}")
        embedder = transformations[-1]
        pre = list(transformations[:-1])

        @udfs.udf
        async def li_embed(text: str) -> np.ndarray:
            return np.asarray(await embedder.aget_text_embedding(text))

        def li_split(text: str) -> list[tuple[str, dict]]:
            nodes = run_transformations([TextNode(text=text)], pre)
            return [(node.get_content(metadata_mode=MetadataMode.NONE),
                     node.extra_info or {}) for node in nodes]

        return cls(*docs, embedder=li_embed, splitter=li_split, **kwargs)

    # ------------------------------------------------------------------
    def _build_graph(self) -> dict:
        if not self.docs:
            raise ValueError(
                "Please provide at least one data source, e.g. read files "
                "from disk: pw.io.fs.read('./sample_docs', format='binary')")
        docs = self.docs[0]
        if len(self.docs) > 1:
            docs = docs.concat_reindex(*self.docs[1:])
        if "_metadata" not in docs.column_names():
            docs = docs.with_columns(_metadata=Json({}))

        if self.parser is None and self.splitter is None and \
                not self.doc_post_processors:
            # identity pipeline (pre-chunked text, the default config):
            # parse and split are 1:1 passthroughs, so the parse→flatten→
            # split→flatten→project chain collapses to one projection —
            # no per-doc Json packing, no flatten key derivation. When the
            # column is already str even the decode apply disappears.
            from pathway_tpu.internals import dtype as _dt

            # exactly STR: an Optional[str] column must keep the apply
            # (str(None) == "None" is what the parser path indexes; a raw
            # None text row would be dropped by the index operator)
            data_dtype = docs.schema._dtypes().get("data")
            if data_dtype == _dt.STR:
                text_expr = pw.this.data
            else:
                text_expr = pw.apply_with_type(
                    lambda data: data.decode("utf-8", "replace")
                    if isinstance(data, bytes) else str(data),
                    str, pw.this.data)
            chunks = docs.select(text=text_expr, metadata=pw.this._metadata)
            return self._finish_graph(docs, chunks)

        parser = _unwrap_udf(self.parser) if self.parser is not None \
            else lambda data: [(data.decode("utf-8", "replace")
                                if isinstance(data, bytes) else str(data), {})]

        @pw.udf
        def parse_doc(data, metadata) -> list[Json]:
            base = metadata.value if isinstance(metadata, Json) else \
                (metadata or {})
            out = []
            for text, meta in parser(data):
                m = dict(base)
                m.update(meta or {})
                out.append(Json({"text": text, "metadata": m}))
            return out

        parsed = docs.select(docs=parse_doc(pw.this.data, pw.this._metadata))
        parsed = parsed.flatten(pw.this.docs)

        post_procs = [_unwrap_udf(p) for p in self.doc_post_processors]

        @pw.udf
        def post_proc(doc: Json) -> Json:
            val = doc.value
            text, meta = val["text"], val["metadata"]
            for p in post_procs:
                text, meta = p(text, meta)
            return Json({"text": text, "metadata": meta})

        if post_procs:
            parsed = parsed.select(docs=post_proc(pw.this.docs))

        splitter = _unwrap_udf(self.splitter) if self.splitter is not None \
            else lambda text: [(text, {})]

        @pw.udf
        def split_doc(doc: Json) -> list[Json]:
            val = doc.value
            out = []
            for chunk, meta in splitter(val["text"]):
                m = dict(val["metadata"])
                m.update(meta or {})
                out.append(Json({"text": chunk, "metadata": m}))
            return out

        chunks = parsed.select(chunks=split_doc(pw.this.docs))
        chunks = chunks.flatten(pw.this.chunks)
        chunks = chunks.select(
            text=pw.apply_with_type(
                lambda j: str(j.value["text"]), str, pw.this.chunks),
            metadata=pw.apply_with_type(
                lambda j: Json(j.value["metadata"]), Json, pw.this.chunks),
        )
        return self._finish_graph(docs, chunks)

    def _finish_graph(self, docs, chunks) -> dict:
        if self.index_builder is not None:
            index = self.index_builder(chunks)
        elif self.index_factory is not None:
            index = DataIndex(
                chunks,
                self.index_factory.build_inner(
                    chunks.text, chunks.metadata,
                    embedder=self.embedder,
                    dimensions=self.embedding_dimension))
        else:
            index = default_usearch_knn_document_index(
                chunks.text, chunks, embedder=self.embedder,
                dimensions=self.embedding_dimension,
                metadata_column=chunks.metadata)

        stats = docs.reduce(
            count=pw.reducers.count(),
            last_modified=pw.reducers.max(pw.apply_with_type(
                lambda m: (m.value or {}).get("modified_at", 0)
                if isinstance(m, Json) else 0, int, pw.this._metadata)),
            paths=pw.reducers.tuple(pw.apply_with_type(
                lambda m: str((m.value or {}).get("path", ""))
                if isinstance(m, Json) else "", str, pw.this._metadata)),
        )
        return {"docs": docs, "chunks": chunks, "index": index,
                "stats": stats}

    @property
    def index(self) -> DataIndex:
        return self._graph["index"]

    # ------------------------------------------------------------------
    # query endpoints (reference vector_store.py:294-456)
    # ------------------------------------------------------------------
    class StatisticsQuerySchema(pw.Schema):
        pass

    class QueryResultSchema(pw.Schema):
        result: Any

    class FilterSchema(pw.Schema):
        metadata_filter: str | None
        filepath_globpattern: str | None

    class RetrieveQuerySchema(pw.Schema):
        query: str
        k: int
        metadata_filter: str | None
        filepath_globpattern: str | None

    class InputsQuerySchema(pw.Schema):
        metadata_filter: str | None
        filepath_globpattern: str | None

    def statistics_query(self, info_queries) -> "pw.Table":
        stats = self._graph["stats"]

        @pw.udf
        def format_stats(count, last_modified) -> Json:
            return Json({"file_count": count,
                         "last_modified": last_modified})

        return info_queries.join_left(stats, id=info_queries.id).select(
            result=format_stats(stats.count, stats.last_modified))

    def inputs_query(self, input_queries) -> "pw.Table":
        stats = self._graph["stats"]

        @pw.udf
        def format_inputs(paths, metadata_filter, filepath_globpattern) -> Json:
            import fnmatch

            out = list(paths or ())
            if filepath_globpattern:
                out = [p for p in out
                       if fnmatch.fnmatch(p, str(filepath_globpattern))]
            return Json(out)

        return input_queries.join_left(stats, id=input_queries.id).select(
            result=format_inputs(stats.paths, input_queries.metadata_filter,
                                 input_queries.filepath_globpattern))

    @staticmethod
    def merge_filters(metadata_filter, filepath_globpattern) -> str | None:
        """Combine a JMESPath filter with a path glob (reference :342)."""
        parts = []
        if metadata_filter:
            parts.append(str(metadata_filter))
        if filepath_globpattern:
            parts.append(f"globmatch(`{filepath_globpattern}`, path)")
        return " && ".join(parts) if parts else None

    def retrieve_query(self, retrieval_queries) -> "pw.Table":
        q = retrieval_queries.with_columns(
            metadata_filter=pw.apply_with_type(
                VectorStoreServer.merge_filters, pw.internals.dtype.ANY,
                pw.this.metadata_filter, pw.this.filepath_globpattern))
        res = self.index.query_as_of_now(
            q.query, number_of_matches=q.k, collapse_rows=True,
            metadata_filter=q.metadata_filter)

        @pw.udf
        def format_matches(texts, metadatas, scores) -> Json:
            out = []
            for t, m, s in zip(texts or (), metadatas or (), scores or ()):
                meta = m.value if isinstance(m, Json) else (m or {})
                out.append({"text": t, "metadata": meta,
                            "dist": float(s) if s is not None else None})
            return Json(out)

        return res.select(result=format_matches(
            res.text, res.metadata, res._pw_index_reply_score))

    # ------------------------------------------------------------------
    def run_server(self, host: str = "0.0.0.0", port: int = 8780, *,
                   threaded: bool = False, with_cache: bool = True,
                   cache_backend=None, **run_kwargs):
        """Expose /v1/retrieve, /v1/statistics, /v1/inputs and run
        (reference vector_store.py:461-566). with_cache memoizes UDF calls
        without an explicit cache_strategy (DiskCache by default)."""
        from pathway_tpu.internals import udfs

        if with_cache:
            backend = cache_backend if isinstance(
                cache_backend, udfs.CacheStrategy) else udfs.DefaultCache()
            udfs.set_default_cache(backend)
        webserver = pw.io.http.PathwayWebserver(host=host, port=port)

        def serve(route, schema, handler):
            queries, writer = pw.io.http.rest_connector(
                webserver=webserver, route=route, schema=schema,
                methods=("GET", "POST"), delete_completed_queries=True)
            writer(handler(queries))

        serve("/v1/retrieve", self.RetrieveQuerySchema, self.retrieve_query)
        serve("/v1/statistics", self.StatisticsQuerySchema,
              self.statistics_query)
        serve("/v1/inputs", self.InputsQuerySchema, self.inputs_query)

        def run():
            pw.run(**run_kwargs)

        if threaded:
            thread = threading.Thread(
                target=run, name="VectorStoreServer", daemon=True)
            thread.start()
            return thread
        run()

    def __repr__(self) -> str:
        return f"VectorStoreServer({self._graph['chunks']!r})"


def parse_slides(data: Any) -> list[tuple[str, dict]]:
    """Default slide-deck parser: one document PER SLIDE (pptx) or per
    page (pdf), stdlib-only (zipfile + XML / content-stream extraction
    from ``_doc_extract``). Slide decks carry their structure in pages,
    so the page is the retrieval unit — no splitter runs downstream."""
    from pathway_tpu.xpacks.llm._doc_extract import (detect_format,
                                                     extract_pdf,
                                                     extract_pptx)

    raw = data if isinstance(data, bytes) else str(data).encode()
    fmt = detect_format(raw)
    if fmt == "pptx":
        pages = extract_pptx(raw)
    elif fmt == "pdf":
        pages = extract_pdf(raw)
    else:  # not a deck: index the whole text as a single one-page doc
        pages = [raw.decode("utf-8", "replace")]
    total = len(pages)
    return [(text, {"page": i + 1, "total_pages": total,
                    "parser": "slides"})
            for i, text in enumerate(pages)]


class SlidesVectorStoreServer(VectorStoreServer):
    """Slide-deck flavour of :class:`VectorStoreServer` (reference
    vector_store.py SlidesVectorStoreServer): each slide/page is an
    indexed document with page-position metadata, there is no default
    splitter (the slide IS the chunk), and ``/v1/inputs`` answers with
    the full per-document metadata dicts — a slide UI needs page counts
    and previews, not bare paths — minus ``excluded_response_metadata``
    (bulky payloads like rendered page images)."""

    excluded_response_metadata = ["b64_image", "image_base64"]

    def __init__(self, *docs, embedder, parser: Callable | None = None,
                 splitter: Callable | None = None, **kwargs):
        super().__init__(*docs, embedder=embedder,
                         parser=parser if parser is not None
                         else parse_slides,
                         splitter=splitter, **kwargs)

    def inputs_query(self, input_queries) -> "pw.Table":
        docs = self._graph["docs"]
        metas = docs.reduce(metas=pw.reducers.tuple(pw.this._metadata))
        excluded = tuple(self.excluded_response_metadata)

        @pw.udf
        def format_inputs(metas, metadata_filter, filepath_globpattern) \
                -> Json:
            import fnmatch

            out = []
            for m in metas or ():
                d = dict(m.value) if isinstance(m, Json) else dict(m or {})
                if filepath_globpattern and not fnmatch.fnmatch(
                        str(d.get("path", "")), str(filepath_globpattern)):
                    continue
                for k in excluded:
                    d.pop(k, None)
                out.append(d)
            return Json(out)

        return input_queries.join_left(metas, id=input_queries.id).select(
            result=format_inputs(metas.metas, input_queries.metadata_filter,
                                 input_queries.filepath_globpattern))

    def __repr__(self) -> str:
        return f"SlidesVectorStoreServer({self._graph['chunks']!r})"


class VectorStoreClient:
    """Blocking HTTP client for VectorStoreServer (reference :627)."""

    def __init__(self, host: str | None = None, port: int | None = None,
                 url: str | None = None, timeout: int = 15,
                 additional_headers: dict | None = None):
        if url is None:
            if host is None:
                raise ValueError("either url or host must be given")
            url = f"http://{host}:{port or 8780}"
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.additional_headers = additional_headers or {}

    def _post(self, route: str, payload: dict):
        import urllib.request

        req = urllib.request.Request(
            self.url + route, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **self.additional_headers})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return _json.loads(resp.read())

    def query(self, query: str, k: int = 3, metadata_filter: str | None = None,
              filepath_globpattern: str | None = None) -> list[dict]:
        return self._post("/v1/retrieve", {
            "query": query, "k": k, "metadata_filter": metadata_filter,
            "filepath_globpattern": filepath_globpattern})

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        return self._post("/v1/statistics", {})

    def get_input_files(self, metadata_filter: str | None = None,
                        filepath_globpattern: str | None = None):
        return self._post("/v1/inputs", {
            "metadata_filter": metadata_filter,
            "filepath_globpattern": filepath_globpattern})
