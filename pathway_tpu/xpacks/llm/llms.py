"""LLM chat wrappers — UDFs mapping prompt columns to completions.

Reference: xpacks/llm/llms.py (BaseChat:27, OpenAIChat:84, LiteLLMChat:310,
HFPipelineChat:438, CohereChat:541). All are async-capable UDFs with
capacity/retry/cache, so a whole engine batch of prompts is in flight
concurrently. ``HFPipelineChat`` runs a local transformers pipeline (torch
CPU in this image); network providers are lazily imported.
"""

from __future__ import annotations

import asyncio
from typing import Any

from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json
from pathway_tpu.xpacks.llm._utils import _import_or_raise


class BaseChat(udfs.UDF):
    """Chat model base (reference llms.py:27). Input is either a plain
    prompt string or a list of {role, content} messages."""

    def __init__(self, *, capacity: int | None = None,
                 retry_strategy: udfs.AsyncRetryStrategy | None = None,
                 cache_strategy: udfs.CacheStrategy | None = None,
                 model: str | None = None, **call_kwargs):
        executor = udfs.async_executor(capacity=capacity,
                                       retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        call_kwargs["model"] = model
        self.kwargs = {k: v for k, v in call_kwargs.items() if v is not None}

    @staticmethod
    def _as_messages(prompt) -> list[dict]:
        if isinstance(prompt, Json):
            prompt = prompt.value
        if isinstance(prompt, str):
            return [{"role": "user", "content": prompt}]
        if isinstance(prompt, (list, tuple)):
            return [m.value if isinstance(m, Json) else m for m in prompt]
        raise TypeError(f"prompt must be str or messages, got {type(prompt)}")


class OpenAIChat(BaseChat):
    """OpenAI chat completions (reference llms.py:84)."""

    def __init__(self, model: str | None = "gpt-3.5-turbo",
                 api_key: str | None = None, base_url: str | None = None,
                 **kwargs):
        super().__init__(model=model, **kwargs)
        self._client_kwargs = {"api_key": api_key, "base_url": base_url}
        self._client = None

    def _get_client(self):
        if self._client is None:
            openai = _import_or_raise("openai", "OpenAIChat")
            kw = {k: v for k, v in self._client_kwargs.items()
                  if v is not None}
            self._client = openai.AsyncOpenAI(**kw)
        return self._client

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        resp = await self._get_client().chat.completions.create(
            messages=self._as_messages(messages), **{**self.kwargs, **kwargs})
        return resp.choices[0].message.content


class LiteLLMChat(BaseChat):
    """Any provider through litellm (reference llms.py:310)."""

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        litellm = _import_or_raise("litellm", "LiteLLMChat")
        resp = await litellm.acompletion(
            messages=self._as_messages(messages), **{**self.kwargs, **kwargs})
        return resp.choices[0].message.content


class CohereChat(BaseChat):
    """Cohere chat with RAG citations (reference llms.py:541): returns
    (response_text, cited_documents)."""

    def __init__(self, model: str | None = "command", **kwargs):
        super().__init__(model=model, **kwargs)

    async def __wrapped__(self, messages, documents=None, **kwargs) -> tuple:
        cohere = _import_or_raise("cohere", "CohereChat")
        msgs = self._as_messages(messages)
        docs = [d.value if isinstance(d, Json) else dict(d)
                for d in (documents or [])]
        client = cohere.AsyncClient()
        resp = await client.chat(
            message=msgs[-1]["content"],
            chat_history=msgs[:-1],
            documents=docs or None,
            **{**self.kwargs, **kwargs})
        cited = [dict(d) for d in (resp.documents or [])] \
            if getattr(resp, "documents", None) else []
        return resp.text, cited


class HFPipelineChat(BaseChat):
    """Local HuggingFace transformers pipeline (reference llms.py:438) —
    runs on host CPU/torch; batches serialize through one pipeline."""

    def __init__(self, model: str | None = None, device: str = "cpu",
                 call_kwargs: dict = {}, **kwargs):
        super().__init__(model=None, **kwargs)
        transformers = _import_or_raise("transformers", "HFPipelineChat")
        self.pipeline = transformers.pipeline(
            "text-generation", model=model, device=device)
        self.tokenizer = self.pipeline.tokenizer
        self.call_kwargs = dict(call_kwargs)

    def crop_to_max_length(self, input_string: str,
                           max_prompt_length: int = 500) -> str:
        tokens = self.tokenizer.tokenize(input_string)
        if len(tokens) > max_prompt_length:
            tokens = tokens[-max_prompt_length:]
            return self.tokenizer.convert_tokens_to_string(tokens)
        return input_string

    async def __wrapped__(self, messages, **kwargs) -> str | None:
        msgs = self._as_messages(messages)
        call_kwargs = {**self.call_kwargs, **kwargs}
        call_kwargs.setdefault("return_full_text", False)
        prompt: Any = msgs
        if getattr(self.tokenizer, "chat_template", None) is None:
            prompt = "\n".join(m["content"] for m in msgs)
        out = await asyncio.to_thread(self.pipeline, prompt, **call_kwargs)
        first = out[0] if isinstance(out, list) else out
        text = first.get("generated_text")
        if isinstance(text, list):  # chat-format output
            text = text[-1].get("content")
        return text


@udfs.udf
def prompt_chat_single_qa(question: str) -> Json:
    """Column UDF wrapping a plain question into a single-turn message list
    (reference llms.py prompt_chat_single_qa)."""
    return Json([{"role": "user", "content": str(question)}])
