"""REST servers wiring QA handlers to routes.

Reference: xpacks/llm/servers.py (BaseRestServer.serve:22, QARestServer:81,
QASummaryRestServer:134). Each route → (schema, handler): rest_connector
turns requests into a query table, the handler builds the result table,
response_writer resolves the awaiting HTTP request.

Serving SLO observability rides along for free (README "Serving SLO"):
every request gets an id at webserver ingress, echoed back in the
``X-Pathway-Request-Id`` response header, and — when the flight recorder
is on (``with_http_server=True`` auto-enables it) — a per-stage latency
decomposition on ``/metrics`` (``pathway_tpu_query_e2e_latency_ms``
quantiles + SLO burn rate), ``/status.slow_queries`` and the Perfetto
trace's request track. Tune the target with ``PATHWAY_SLO_E2E_MS``.
"""

from __future__ import annotations

import threading

import pathway_tpu as pw


class BaseRestServer:
    def __init__(self, host: str, port: int, **rest_kwargs):
        self.host = host
        self.port = port
        self.webserver = pw.io.http.PathwayWebserver(host=host, port=port)
        self.rest_kwargs = rest_kwargs

    def serve(self, route: str, schema: type[pw.Schema], handler,
              **additional_kwargs) -> None:
        queries, writer = pw.io.http.rest_connector(
            webserver=self.webserver, route=route, schema=schema,
            methods=("GET", "POST"), delete_completed_queries=True,
            **additional_kwargs)
        writer(handler(queries))

    def run(self, *, threaded: bool = False, with_cache: bool = True,
            cache_backend=None, terminate_on_error: bool = True, **kwargs):
        """Start the pipeline (blocking, or on a daemon thread).

        with_cache=True memoizes LLM/embedder UDF calls that did not pick
        their own cache_strategy: ``cache_backend`` may be a
        udfs.CacheStrategy (DiskCache persists across restarts, the
        default, matching the reference's UdfCaching persistence mode)."""
        from pathway_tpu.internals import udfs

        if with_cache:
            backend = cache_backend if isinstance(
                cache_backend, udfs.CacheStrategy) else udfs.DefaultCache()
            udfs.set_default_cache(backend)

        def run():
            pw.run(terminate_on_error=terminate_on_error, **kwargs)

        if threaded:
            thread = threading.Thread(target=run, daemon=True,
                                      name=type(self).__name__)
            thread.start()
            return thread
        run()


class QARestServer(BaseRestServer):
    """Routes for answer/retrieve/statistics/list_documents
    (reference servers.py:81)."""

    def __init__(self, host: str, port: int, rag_question_answerer,
                 **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.serve("/v1/pw_ai_answer",
                   rag_question_answerer.AnswerQuerySchema,
                   rag_question_answerer.answer_query)
        self.serve("/v1/retrieve",
                   rag_question_answerer.RetrieveQuerySchema,
                   rag_question_answerer.retrieve)
        self.serve("/v1/statistics",
                   rag_question_answerer.StatisticsQuerySchema,
                   rag_question_answerer.statistics)
        self.serve("/v1/pw_list_documents",
                   rag_question_answerer.indexer.InputsQuerySchema,
                   rag_question_answerer.indexer.inputs_query)


class QASummaryRestServer(QARestServer):
    """QARestServer + summarization route (reference servers.py:134)."""

    def __init__(self, host: str, port: int, rag_question_answerer,
                 **rest_kwargs):
        super().__init__(host, port, rag_question_answerer, **rest_kwargs)
        self.serve("/v1/pw_ai_summary",
                   rag_question_answerer.SummarizeQuerySchema,
                   rag_question_answerer.summarize_query)
