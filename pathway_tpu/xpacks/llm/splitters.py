"""Text splitters (reference: xpacks/llm/splitters.py — null_splitter,
TokenCountSplitter with tiktoken).

Splitters are UDFs: ``str → list[tuple[str, dict]]`` (chunk, metadata), so
``table.select(chunks=splitter(pw.this.text))`` followed by ``flatten``
fans chunks out into rows.
"""

from __future__ import annotations

import re
import unicodedata

from pathway_tpu.internals import udfs


def null_splitter(txt: str) -> list[tuple[str, dict]]:
    """No-op splitter: one chunk per document (reference :?null_splitter)."""
    return [(txt, {})]


def _default_tokenizer(text: str) -> list[str]:
    # whitespace+punct tokenization approximating a BPE token count;
    # tiktoken (absent here) would give ~0.75 words/token for English
    return re.findall(r"\w+|[^\w\s]", text)


class TokenCountSplitter(udfs.UDF):
    """Split text into chunks of [min_tokens, max_tokens] tokens, preferring
    sentence/punctuation boundaries (reference TokenCountSplitter uses
    tiktoken token ids; here token = word-level unit from a pluggable
    tokenizer, e.g. models.tokenizer.HashTokenizer.encode)."""

    CHARS_PER_TOKEN = 5  # only used for encoding-less length estimates

    def __init__(self, min_tokens: int = 50, max_tokens: int = 500,
                 encoding_name: str = "cl100k_base", tokenize=None, **kwargs):
        super().__init__(**kwargs)
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.encoding_name = encoding_name
        self._tokenize = tokenize or _default_tokenizer

    def chunk(self, txt: str, metadata: dict | None = None) -> list[tuple[str, dict]]:
        text = unicodedata.normalize("NFKC", txt or "")
        metadata = metadata or {}
        if not text.strip():
            return []
        # split into sentences, then greedily pack into chunks
        sentences = re.split(r"(?<=[.!?\n])\s+", text)
        chunks: list[str] = []
        cur: list[str] = []
        cur_tokens = 0
        for sent in sentences:
            n = len(self._tokenize(sent))
            if n > self.max_tokens:
                # hard-split an oversized sentence by tokens
                words = self._tokenize(sent)
                if cur:
                    chunks.append(" ".join(cur))
                    cur, cur_tokens = [], 0
                for i in range(0, len(words), self.max_tokens):
                    chunks.append(" ".join(words[i:i + self.max_tokens]))
                continue
            if cur and cur_tokens + n > self.max_tokens:
                # flush even below min_tokens: an undersized chunk beats an
                # oversized one (which the embedder would silently truncate)
                chunks.append(" ".join(cur))
                cur, cur_tokens = [], 0
            cur.append(sent)
            cur_tokens += n
        if cur:
            tail = " ".join(cur)
            if chunks and cur_tokens < self.min_tokens:
                chunks[-1] = chunks[-1] + " " + tail
            else:
                chunks.append(tail)
        return [(c, dict(metadata)) for c in chunks if c.strip()]

    def __wrapped__(self, txt: str, **kwargs) -> list[tuple[str, dict]]:
        return self.chunk(txt, kwargs.get("metadata"))
