"""pw.xpacks.llm — LLM/RAG toolkit (reference: python/pathway/xpacks/llm/).

Submodules import lazily so the heavyweight model stacks (torch/flax) load
only when used.
"""

from __future__ import annotations

import importlib

__all__ = [
    "embedders", "llms", "parsers", "splitters", "rerankers",
    "vector_store", "question_answering", "servers",
    "prompts", "constants", "_typing", "_utils",
]


def __getattr__(name):
    if name in __all__:
        return importlib.import_module(f"pathway_tpu.xpacks.llm.{name}")
    raise AttributeError(name)
