"""Shared helpers for the LLM xpack (reference: xpacks/llm/_utils.py)."""

from __future__ import annotations

import importlib
import importlib.util
from typing import Any, Callable

from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json


def _check_model_accessibility(*args, **kwargs):  # reference no-op analogue
    return True


def _is_available(module: str) -> bool:
    try:
        return importlib.util.find_spec(module) is not None
    except (ImportError, ValueError):
        return False


def _import_or_raise(module: str, feature: str):
    if not _is_available(module):
        raise ImportError(
            f"{feature} requires the `{module}` package, which is not "
            f"available in this environment.")
    return importlib.import_module(module)


def _coerce_sync(fn: Callable) -> Callable:
    """Run a coroutine function synchronously (for client helper calls)."""
    import asyncio
    import inspect

    if not inspect.iscoroutinefunction(fn):
        return fn

    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))

    return wrapper


def _extract_value(value: Any) -> Any:
    if isinstance(value, Json):
        return value.value
    return value


def _unwrap_udf(fn: Any) -> Callable:
    """Accept either a plain function or a pw.UDF and return a callable."""
    if isinstance(fn, udfs.UDF):
        return _coerce_sync(fn.func)
    return _coerce_sync(fn)


def get_embedding_dimension(embedder) -> int:
    """Output dimension of any embedder (UDF or plain fn), probing with one
    call when it can't tell us (reference embedders.py:63)."""
    import numpy as np

    if hasattr(embedder, "get_embedding_dimension"):
        return int(embedder.get_embedding_dimension())
    result = np.asarray(_unwrap_udf(embedder)("."))
    if result.ndim == 2:
        result = result[0]
    return int(result.shape[0])
