"""Document parsers — UDFs mapping raw bytes to [(text, metadata)] chunks.

Reference: xpacks/llm/parsers.py (ParseUtf8, ParseUnstructured,
ParseOpenParse — PDF layout/tables/vision). ``ParseUtf8`` is native;
``ParseUnstructured`` uses the unstructured-io library when installed and
otherwise falls back to the in-repo extractors (_doc_extract.py: PDF
content-stream tokenizing, DOCX/PPTX zip+XML, HTML) — so the common
document formats parse with zero optional dependencies. ``ParseOpenParse``
similarly falls back to per-page PDF extraction when openparse is absent.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import udfs


def _as_text(contents: Any) -> str:
    if isinstance(contents, bytes):
        return contents.decode("utf-8", errors="replace")
    return str(contents)


class ParseUtf8(udfs.UDF):
    """Decode raw bytes as UTF-8 → one chunk (reference ParseUtf8)."""

    def __wrapped__(self, contents: Any, **kwargs) -> list[tuple[str, dict]]:
        return [(_as_text(contents), {})]


class ParseUnstructured(udfs.UDF):
    """unstructured-io parser (reference ParseUnstructured): splits any
    document type into elements; chunking modes single/elements/paged."""

    def __init__(self, mode: str = "single", post_processors=None,
                 **partition_kwargs):
        super().__init__()
        if mode not in ("single", "elements", "paged"):
            raise ValueError(f"invalid mode {mode!r}")
        self.mode = mode
        self.post_processors = post_processors or []
        self.partition_kwargs = partition_kwargs

    def __wrapped__(self, contents: Any, **kwargs) -> list[tuple[str, dict]]:
        raw = contents if isinstance(contents, bytes) \
            else str(contents).encode()
        try:
            from unstructured.partition import auto as partition
        except ImportError:
            return self._fallback(raw)
        import io

        elements = partition.partition(
            file=io.BytesIO(raw), **{**self.partition_kwargs, **kwargs})
        for proc in self.post_processors:
            elements = [proc(e) for e in elements]
        if self.mode == "single":
            return [("\n\n".join(str(e) for e in elements), {})]
        out = []
        if self.mode == "paged":
            pages: dict[int, list] = {}
            for e in elements:
                page = getattr(e.metadata, "page_number", 1) or 1
                pages.setdefault(page, []).append(str(e))
            for page, texts in sorted(pages.items()):
                out.append(("\n\n".join(texts), {"page_number": page}))
            return out
        for e in elements:  # elements mode
            meta = e.metadata.to_dict() if hasattr(e, "metadata") else {}
            meta["category"] = type(e).__name__
            out.append((str(e), meta))
        return out

    def _fallback(self, raw: bytes) -> list[tuple[str, dict]]:
        from pathway_tpu.xpacks.llm._doc_extract import extract_elements

        elements = extract_elements(raw)
        # post_processors written for unstructured Elements receive plain
        # text here (no Element objects exist without the library) —
        # str -> str processors like clean_extra_whitespace work unchanged
        for proc in self.post_processors:
            elements = [(proc(text), meta) for text, meta in elements]
        if self.mode == "single":
            return [("\n\n".join(text for text, _m in elements), {})]
        if self.mode == "paged":
            pages: dict[int, list] = {}
            for text, meta in elements:
                page = meta.get("page_number", 1)
                pages.setdefault(page, []).append(text)
            return [("\n\n".join(texts), {"page_number": page})
                    for page, texts in sorted(pages.items())]
        return elements


class ParseOpenParse(udfs.UDF):
    """openparse PDF layout parser (reference ParseOpenParse +
    _openparse_utils.py): nodes with text/tables, optional vision LLM for
    images. Requires the `openparse` package."""

    def __init__(self, table_args: dict | None = None,
                 parse_images: bool = False, llm=None, **kwargs):
        super().__init__(**kwargs)
        self.table_args = table_args
        self.parse_images = parse_images
        self.llm = llm

    def __wrapped__(self, contents: Any, **kwargs) -> list[tuple[str, dict]]:
        raw = contents if isinstance(contents, bytes) \
            else str(contents).encode()
        try:
            import openparse
        except ImportError:
            # layout/tables need openparse; plain text still extracts
            from pathway_tpu.xpacks.llm._doc_extract import extract_pdf

            return [(text, {"page_number": i + 1})
                    for i, text in enumerate(extract_pdf(raw))]
        import tempfile

        parser = openparse.DocumentParser(table_args=self.table_args)
        with tempfile.NamedTemporaryFile(suffix=".pdf") as f:
            f.write(raw)
            f.flush()
            doc = parser.parse(f.name)
        return [(node.text, {"bbox": [list(b) for b in getattr(
            node, "bbox", [])]}) for node in doc.nodes]
