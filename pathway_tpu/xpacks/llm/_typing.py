"""Type aliases for the llm xpack (reference: xpacks/llm/_typing.py)."""

from typing import Callable, Iterable, TypeAlias, Union

import pathway_tpu as pw

Doc: TypeAlias = dict[str, str | dict]

DocTransformerCallable: TypeAlias = Union[
    Callable[[Iterable[Doc]], Iterable[Doc]],
    Callable[[Iterable[Doc], float], Iterable[Doc]],
]

DocTransformer: TypeAlias = Union[pw.udfs.UDF, DocTransformerCallable]
