"""Embedders — UDFs mapping text columns to embedding vectors.

Same API family as the reference (xpacks/llm/embedders.py: OpenAIEmbedder:83,
LiteLLMEmbedder:178, SentenceTransformerEmbedder:268, GeminiEmbedder:328;
dimension probing via one call :63), plus the TPU-native flagship:
``JaxEncoderEmbedder`` runs pathway_tpu/models/encoder.py under jit with
**columnar batch dispatch** (UDF batch=True) — whole engine batches are
tokenized and encoded in one device call, never per row.
"""

from __future__ import annotations

import asyncio
from time import perf_counter as _perf_counter
from typing import Any

import numpy as np

from pathway_tpu.internals import udfs
from pathway_tpu.xpacks.llm._utils import _import_or_raise


class BaseEmbedder(udfs.UDF):
    """Embedder base: callable on a column; knows its output dimension."""

    def get_embedding_dimension(self, **kwargs) -> int:
        """Probe the dimension with one call (reference embedders.py:63)."""
        result = self.func(".", **kwargs)
        if asyncio.iscoroutine(result):
            result = asyncio.run(result)
        arr = np.asarray(result)
        if arr.ndim == 2:  # batch embedder probed with a single item
            arr = arr[0]
        return int(arr.shape[0])


class JaxEncoderEmbedder(BaseEmbedder):
    """TPU-native embedder over the flagship JAX encoder.

    Tokenizes with models.tokenizer (HashTokenizer by default, or a local HF
    tokenizer), bf16 forward under jit, sequence-length bucketing to bound
    recompilation. This replaces the reference's torch
    SentenceTransformerEmbedder as the local-model path.
    """

    _BUCKETS = (32, 64, 128, 256, 512)

    def __init__(self, *, model: str | None = None, config=None,
                 params=None, tokenizer=None,
                 seed: int = 0, max_len: int = 512,
                 ragged: bool | None = None,
                 call_kwargs: dict = {}, **kwargs):
        kwargs.setdefault("batch", True)
        kwargs.setdefault("deterministic", True)
        kwargs.setdefault("device", True)  # pipeline via the device bridge
        super().__init__(**kwargs)
        import os

        import jax

        from pathway_tpu.warmup import maybe_enable_compilation_cache

        # opt-in persistent XLA cache (PATHWAY_COMPILATION_CACHE): the ~18
        # bucket shapes compile once per machine, not once per process
        maybe_enable_compilation_cache()

        from pathway_tpu.models.encoder import EncoderConfig, encode, \
            init_params
        from pathway_tpu.models.tokenizer import HashTokenizer

        if model is not None:
            # name-based convenience, like the reference's
            # SentenceTransformerEmbedder(model=...): loads the checkpoint
            # (weights + config + WordPiece vocab) from the local HF cache
            from pathway_tpu.models.hf_loader import load_model

            params, config, tokenizer = load_model(model)
        self.config = config or EncoderConfig.bge_small()
        self.params = params if params is not None else init_params(
            jax.random.PRNGKey(seed), self.config)
        self.tokenizer = tokenizer or HashTokenizer(
            vocab_size=self.config.vocab_size, max_len=max_len)
        self.max_len = min(max_len, self.config.max_len)
        cfg = self.config
        self._encode = jax.jit(
            lambda p, ids, mask: encode(p, ids, mask, config=cfg))
        # packed hot path: int16 ids + per-row lengths instead of int32
        # ids + a (B, S) bool mask — a quarter of the host→device bytes;
        # the mask is rebuilt on device (iota < len). Usable whenever the
        # vocab fits int16 (BGE's 30522 does). One implementation
        # (device_producer) serves both this jit and the fused ingest.
        self._encode_packed = jax.jit(self.device_producer)
        self._pack_ids = self.config.vocab_size <= 32767
        # ragged batching (PATHWAY_RAGGED_ENCODER=1 or ragged=True):
        # variable-length docs pack back-to-back into fixed-width
        # sequences with a doc-map vector instead of per-width padding —
        # the ~18 width-bucket compiles collapse to the handful of
        # sequence-count buckets in ragged_buckets()
        if ragged is None:
            ragged = os.environ.get(
                "PATHWAY_RAGGED_ENCODER", "0").lower() in (
                "1", "true", "on", "yes")
        self.ragged = bool(ragged)
        from pathway_tpu.internals.config import _env_int

        self._ragged_max_seqs = max(1, _env_int("PATHWAY_RAGGED_MAX_SEQS", 8))
        # docs-per-sequence cap bounds the padded doc dimension of a chunk
        # (W//16: a doc is never shorter than CLS+token+SEP anyway)
        self._ragged_doc_cap = max(1, self.max_len // 16)
        self._encode_ragged = jax.jit(self.ragged_device_producer)

    def _bucket(self, n: int) -> int:
        """Pad target for a batch whose longest row has ``n`` tokens.
        MXU time scales with padded tokens, so buckets are multiples of
        16 up to 64 then multiples of 32 — tight enough to not waste
        ~30% of the forward on padding (pow-2 buckets would), coarse
        enough to bound recompilation at ~18 shapes."""
        if n <= 64:
            b = max(16, -(-n // 16) * 16)
        else:
            b = -(-n // 32) * 32
        return min(b, self.max_len)

    def bucket_widths(self) -> list[int]:
        """Every padded width ``_bucket`` can produce for this ``max_len``
        (~18 shapes at 512) — the exact compile set ``pw.warmup`` walks so
        a warmed process (or a persistent-cache hit) never compiles the
        encoder inside a serving tick."""
        widths: list[int] = []
        w = 16
        while w <= min(64, self.max_len):
            widths.append(w)
            w += 16
        w = 96
        while w < self.max_len:
            widths.append(w)
            w += 32
        if self.max_len not in widths:
            widths.append(self.max_len)
        return widths

    def pack_tokens(self, texts: list[str]) -> tuple[np.ndarray, np.ndarray]:
        """Tokenize + bucket-pad, returning ``(ids, lens)`` ready for the
        packed device producer — int16 ids when the vocab fits."""
        ids, mask = self.tokenizer.batch(
            [t or "." for t in texts], max_len=self.max_len)
        pad_to = self._bucket(ids.shape[1])
        if ids.shape[1] < pad_to:
            ids = np.pad(ids, ((0, 0), (0, pad_to - ids.shape[1])))
        else:
            ids, mask = ids[:, :pad_to], mask[:, :pad_to]
        lens = mask.sum(axis=1).astype(np.int32)
        return ids.astype(np.int16 if self._pack_ids else np.int32), lens

    def device_producer(self, params, ids, lens):
        """Pure (traceable) forward over packed tokens: mask rebuilt on
        device. ops/knn.py's fused ingest composes this with the slab
        scatter into ONE donated dispatch."""
        import jax.numpy as jnp

        from pathway_tpu.models.encoder import encode

        ids32 = ids.astype(jnp.int32)
        mask = jnp.arange(ids32.shape[1])[None, :] < lens[:, None]
        return encode(params, ids32, mask, config=self.config)

    def ragged_device_producer(self, params, ids, doc_map, pos_ids,
                               doc_seq, doc_off):
        """Pure (traceable) forward over a ragged-packed chunk
        (models/encoder.py encode_ragged) — the fused-ingest producer of
        the ragged path, returning (n_docs_padded, hidden)."""
        from pathway_tpu.models.encoder import encode_ragged

        return encode_ragged(params, ids, doc_map, pos_ids, doc_seq,
                             doc_off, config=self.config)

    def ragged_buckets(self) -> list[int]:
        """Sequence-count buckets the ragged path can dispatch: powers of
        two up to the per-chunk cap (full chunks all share ONE shape).
        This is the ENTIRE ragged compile set — len ≤ 6 vs ~18 width
        buckets — and the set ``pw.warmup`` walks when ragged is on."""
        out, b = [], 1
        while b < self._ragged_max_seqs:
            out.append(b)
            b *= 2
        out.append(self._ragged_max_seqs)
        return out

    def pack_ragged(self, texts: list[str]) -> list[tuple]:
        """Greedy first-fit packing of tokenized docs into fixed-width
        sequences, chunked at ``_ragged_max_seqs`` sequences per dispatch.

        Returns ``[(args, n_docs, n_docs_padded), ...]`` per chunk, docs
        in input order, where ``args = (ids, doc_map, pos_ids, doc_seq,
        doc_off)`` feed ragged_device_producer and ``n_docs_padded`` is
        its static output row count (pad rows carry doc_map -1 and are
        dropped by the caller / the fused scatter)."""
        ids, mask = self.tokenizer.batch(
            [t or "." for t in texts], max_len=self.max_len)
        lens = mask.sum(axis=1).astype(np.int64)
        W, cap = self.max_len, self._ragged_doc_cap
        # assign each doc a (sequence, offset) first-fit in order
        seq_of = np.empty(len(texts), np.int64)
        off_of = np.empty(len(texts), np.int64)
        seq, fill, docs_in_seq = 0, 0, 0
        for d, n in enumerate(lens):
            n = int(n)
            if fill + n > W or docs_in_seq >= cap:
                seq, fill, docs_in_seq = seq + 1, 0, 0
            seq_of[d], off_of[d] = seq, fill
            fill += n
            docs_in_seq += 1
        n_seqs_total = seq + 1
        chunks: list[tuple] = []
        max_seqs = self._ragged_max_seqs
        buckets = self.ragged_buckets()
        d0 = 0
        for s0 in range(0, n_seqs_total, max_seqs):
            s1 = min(s0 + max_seqs, n_seqs_total)
            n_seqs = next(b for b in buckets if b >= s1 - s0)
            d1 = d0
            while d1 < len(texts) and seq_of[d1] < s1:
                d1 += 1
            n_docs = d1 - d0
            n_pad = n_seqs * cap
            c_ids = np.zeros((n_seqs, W), np.int32)
            c_map = np.full((n_seqs, W), -1, np.int32)
            c_pos = np.zeros((n_seqs, W), np.int32)
            c_dseq = np.zeros((n_pad,), np.int32)
            c_doff = np.zeros((n_pad,), np.int32)
            for j, d in enumerate(range(d0, d1)):
                n = int(lens[d])
                s, o = int(seq_of[d]) - s0, int(off_of[d])
                c_ids[s, o:o + n] = ids[d, :n]
                c_map[s, o:o + n] = j
                c_pos[s, o:o + n] = np.arange(n)
                c_dseq[j], c_doff[j] = s, o
            chunks.append(((c_ids, c_map, c_pos, c_dseq, c_doff),
                           n_docs, n_pad))
            d0 = d1
        return chunks

    def ragged_warmup_operands(self, n_seqs: int) -> tuple[tuple, int]:
        """Synthetic ragged chunk at bucket ``n_seqs`` with every padded
        doc slot real — warmup compiles the exact (n_seqs, W) dispatch
        shape without caring about content."""
        W, cap = self.max_len, self._ragged_doc_cap
        tok = W // cap
        n_docs = n_seqs * cap
        ids = np.zeros((n_seqs, W), np.int32)
        doc_map = np.repeat(np.arange(n_docs, dtype=np.int32),
                            tok).reshape(n_seqs, cap * tok)
        if cap * tok < W:
            doc_map = np.pad(doc_map, ((0, 0), (0, W - cap * tok)),
                             constant_values=-1)
        pos = np.tile(np.arange(tok, dtype=np.int32), cap)[None, :]
        pos = np.pad(np.repeat(pos, n_seqs, 0),
                     ((0, 0), (0, W - cap * tok)))
        dseq = np.repeat(np.arange(n_seqs, dtype=np.int32), cap)
        doff = np.tile(np.arange(cap, dtype=np.int32) * tok, n_seqs)
        return (ids, doc_map, pos, dseq, doff), n_docs

    def encode_batch_device(self, texts: list[str]):
        """Tokenize + encoder forward, returning the (B, hidden) embedding
        still ON DEVICE (a jax array, dispatch left asynchronous). The
        fused index path (ops/knn.py DeviceEmbeddingKnnIndex) scatters it
        straight into the HBM slab — embeddings never visit the host."""
        import jax.numpy as jnp

        # residency is established EXPLICITLY (jnp.asarray) rather than by
        # letting the jit dispatch transfer its numpy operands implicitly:
        # same bytes over PCIe either way, but the explicit form stays
        # legal under the device sanitizer's steady-state transfer guard
        # (engine/device_sanitizer.py) and under PWT404's discipline
        from pathway_tpu.engine.profiler import current_profiler

        prof = current_profiler()
        cfg = self.config
        if self.ragged:
            outs = []
            for args, n_docs, _n_pad in self.pack_ragged(texts):
                t0 = _perf_counter() if prof is not None else 0.0
                outs.append(self._encode_ragged(
                    self.params, *(jnp.asarray(a) for a in args))[:n_docs])
                if prof is not None:
                    from pathway_tpu.engine.profiler import \
                        segment_attention_cost

                    b, s = args[0].shape  # packed (n_seqs, W) token ids
                    flops, nbytes = segment_attention_cost(
                        int(b), int(s), hidden=cfg.hidden,
                        intermediate=cfg.intermediate, layers=cfg.layers)
                    prof.record_dispatch(
                        "segment_attention", flops, nbytes,
                        (_perf_counter() - t0) * 1e3)
            return outs[0] if len(outs) == 1 else jnp.concatenate(outs, 0)
        ids, lens = self.pack_tokens(texts)
        t0 = _perf_counter() if prof is not None else 0.0
        out = self._encode_packed(self.params, jnp.asarray(ids),
                                  jnp.asarray(lens))
        if prof is not None:
            from pathway_tpu.engine.profiler import encoder_cost

            b, s = ids.shape
            flops, nbytes = encoder_cost(
                int(b), int(s), hidden=cfg.hidden,
                intermediate=cfg.intermediate, layers=cfg.layers)
            prof.record_dispatch("encoder_forward", flops, nbytes,
                                 (_perf_counter() - t0) * 1e3)
        return out

    def embed_batch(self, texts: list[str]) -> np.ndarray:
        return np.asarray(self.encode_batch_device(texts))

    def __wrapped__(self, texts: list[str], **kwargs) -> list[np.ndarray]:
        # ONE device→host transfer for the whole batch, then zero-copy row
        # views into it (ndarray iteration yields views, never copies) —
        # per-row np.array(...) slicing would re-allocate B×hidden floats
        # per tick on the hot path. The fused on-device ingest
        # (ops/knn.py) bypasses this entirely: embeddings stay in HBM.
        return list(self.embed_batch(list(texts)))

    def get_embedding_dimension(self, **kwargs) -> int:
        return int(self.config.hidden)


class SentenceTransformerEmbedder(BaseEmbedder):
    """Local sentence-transformers model (torch) — reference :268-326.
    Prefer JaxEncoderEmbedder on TPU; this exists for checkpoint parity."""

    def __init__(self, model: str, *, call_kwargs: dict = {},
                 device: str = "cpu", **kwargs):
        kwargs.setdefault("batch", True)
        super().__init__(**kwargs)
        st = _import_or_raise("sentence_transformers",
                              "SentenceTransformerEmbedder")
        self.model = st.SentenceTransformer(model, device=device)
        self.kwargs = call_kwargs

    def __wrapped__(self, texts: list[str], **kwargs) -> list[np.ndarray]:
        out = self.model.encode(list(texts), **{**self.kwargs, **kwargs})
        return [np.asarray(v) for v in out]


class _RemoteEmbedder(BaseEmbedder):
    """Shared shape of the network embedders: async UDF with retry/cache."""

    def __init__(self, *, capacity: int | None = None,
                 retry_strategy: udfs.AsyncRetryStrategy | None = None,
                 cache_strategy: udfs.CacheStrategy | None = None,
                 model: str | None = None, **call_kwargs):
        executor = udfs.async_executor(capacity=capacity,
                                       retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        call_kwargs["model"] = model
        self.kwargs = {k: v for k, v in call_kwargs.items() if v is not None}


class OpenAIEmbedder(_RemoteEmbedder):
    """OpenAI /embeddings API (reference embedders.py:83)."""

    def __init__(self, model: str | None = "text-embedding-3-small",
                 api_key: str | None = None, base_url: str | None = None,
                 **kwargs):
        super().__init__(model=model, **kwargs)
        self._client_kwargs = {"api_key": api_key, "base_url": base_url}
        self._client = None

    def _get_client(self):
        if self._client is None:
            openai = _import_or_raise("openai", "OpenAIEmbedder")
            kw = {k: v for k, v in self._client_kwargs.items()
                  if v is not None}
            self._client = openai.AsyncOpenAI(**kw)
        return self._client

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        resp = await self._get_client().embeddings.create(
            input=[input or "."], **{**self.kwargs, **kwargs})
        return np.array(resp.data[0].embedding)


class LiteLLMEmbedder(_RemoteEmbedder):
    """Any provider through litellm (reference embedders.py:178)."""

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        litellm = _import_or_raise("litellm", "LiteLLMEmbedder")
        resp = await litellm.aembedding(
            input=[input or "."], **{**self.kwargs, **kwargs})
        return np.array(resp.data[0]["embedding"])


class GeminiEmbedder(_RemoteEmbedder):
    """Google Generative AI embeddings (reference embedders.py:328)."""

    def __init__(self, model: str | None = "models/embedding-001",
                 api_key: str | None = None, **kwargs):
        super().__init__(model=model, **kwargs)
        self._api_key = api_key

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        genai = _import_or_raise("google.generativeai", "GeminiEmbedder")
        if self._api_key:
            genai.configure(api_key=self._api_key)
        resp = await asyncio.to_thread(
            genai.embed_content, content=input or ".",
            **{**self.kwargs, **kwargs})
        return np.array(resp["embedding"])


class ClipEmbedder(BaseEmbedder):
    """Multimodal embedder over the in-repo CLIP dual encoder
    (models/clip.py) — the TPU-native counterpart of the reference's
    multimodal template (BASELINE config 4: CLIP image+text into one
    index). ``__call__`` embeds text columns; ``image()`` embeds binary
    image columns into the SAME space, so one KNN index serves cross-modal
    retrieval."""

    def __init__(self, *, config=None, params=None, tokenizer=None,
                 seed: int = 0, **kwargs):
        kwargs.setdefault("batch", True)
        kwargs.setdefault("deterministic", True)
        kwargs.setdefault("device", True)  # pipeline via the device bridge
        super().__init__(**kwargs)
        import jax

        from pathway_tpu.models import clip as _clip
        from pathway_tpu.models.tokenizer import HashTokenizer

        self.config = config or _clip.ClipConfig()
        self.params = params if params is not None else \
            _clip.init_clip_params(jax.random.PRNGKey(seed), self.config)
        self.tokenizer = tokenizer or HashTokenizer(
            vocab_size=self.config.text.vocab_size,
            max_len=self.config.text.max_len)
        cfg = self.config
        self._encode_text = jax.jit(
            lambda p, ids, mask: _clip.encode_text(p, ids, mask,
                                                   config=cfg))
        self._encode_image = jax.jit(
            lambda p, px: _clip.encode_image(p, px, config=cfg))
        self._clip = _clip

    _BUCKETS = JaxEncoderEmbedder._BUCKETS

    def embed_text_batch(self, texts: list[str]) -> np.ndarray:
        max_len = self.config.text.max_len
        ids, mask = self.tokenizer.batch(
            [t or "." for t in texts], max_len=max_len)
        # bucket-pad like JaxEncoderEmbedder: varying batch widths would
        # otherwise recompile the jitted text tower per new width
        pad_to = max_len
        for b in self._BUCKETS:
            if ids.shape[1] <= b:
                pad_to = min(b, max_len)
                break
        if ids.shape[1] < pad_to:
            pad = pad_to - ids.shape[1]
            ids = np.pad(ids, ((0, 0), (0, pad)))
            mask = np.pad(mask, ((0, 0), (0, pad)))
        else:
            ids, mask = ids[:, :pad_to], mask[:, :pad_to]
        return np.asarray(self._encode_text(self.params, ids, mask))

    def embed_image_batch(self, images: list) -> np.ndarray:
        px = np.stack([
            self._clip.load_image(im, config=self.config)
            if isinstance(im, bytes) else np.asarray(im, np.float32)
            for im in images
        ])
        return np.asarray(self._encode_image(self.params, px))

    def __wrapped__(self, texts: list[str], **kwargs) -> list[np.ndarray]:
        # zero-copy row views of the single batch transfer
        return list(self.embed_text_batch(list(texts)))

    def image(self):
        """A UDF embedding image bytes/arrays into the shared space."""
        outer = self

        class _ImageUDF(BaseEmbedder):
            def __init__(self):
                super().__init__(batch=True, deterministic=True,
                                 device=True)

            def __wrapped__(self, images: list, **kwargs):
                # zero-copy row views of the single batch transfer
                return list(outer.embed_image_batch(list(images)))

            def get_embedding_dimension(self, **kwargs) -> int:
                return int(outer.config.embed_dim)

        return _ImageUDF()

    def get_embedding_dimension(self, **kwargs) -> int:
        return int(self.config.embed_dim)
