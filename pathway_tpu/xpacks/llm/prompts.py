"""Prompt templates for RAG question answering.

Fresh implementations of the prompt-building roles in the reference
(xpacks/llm/prompts.py / question_answering.py:88-152): short/long QA
prompts over retrieved context, citation-style answers and summaries. The
"No information found" sentinel is load-bearing: the adaptive RAG loop
re-asks with more documents when the model emits it
(question_answering.py:88-153).
"""

from __future__ import annotations

from typing import Iterable

NO_INFO_ANSWER = "No information found."


def _join_docs(docs: Iterable) -> str:
    texts = []
    for d in docs:
        if isinstance(d, dict):
            texts.append(str(d.get("text", d)))
        else:
            texts.append(str(d))
    return "\n\n".join(f"[doc {i + 1}]\n{t}" for i, t in enumerate(texts))


def prompt_short_qa(context_docs, query: str,
                    additional_rules: str = "") -> str:
    return (
        "Answer the question based only on the documents below. Reply with "
        f'a short answer (a few words). If the documents do not contain the '
        f'answer, reply exactly "{NO_INFO_ANSWER}".'
        f"{additional_rules}\n\nDocuments:\n{_join_docs(context_docs)}\n\n"
        f"Question: {query}\nAnswer:"
    )


def prompt_qa(context_docs, query: str,
              information_not_found_response: str = NO_INFO_ANSWER,
              additional_rules: str = "") -> str:
    return (
        "You are answering a question using only the documents provided "
        "below. Quote the relevant parts when helpful. If the documents do "
        "not contain the answer, reply exactly "
        f'"{information_not_found_response}".'
        f"{additional_rules}\n\nDocuments:\n{_join_docs(context_docs)}\n\n"
        f"Question: {query}\nAnswer:"
    )


def prompt_qa_geometric_rag(context_docs, query: str,
                            information_not_found_response: str = NO_INFO_ANSWER,
                            additional_rules: str = "",
                            strict_prompt: bool = False) -> str:
    """Strict variant used by the adaptive strategy: the model must not
    guess, so escalation on the sentinel is sound. ``strict_prompt``
    tightens the output contract further for small open-source models
    (reference: prompts.prompt_qa_geometric_rag's strict mode)."""
    if strict_prompt:
        additional_rules += (
            " Respond with the answer text alone — no preamble, no "
            "explanation, no quotation marks around the whole answer.")
    return (
        "Use ONLY the documents below to answer. Do not use prior "
        "knowledge. If the answer is not contained in the documents, reply "
        f'exactly "{information_not_found_response}" and nothing else.'
        f"{additional_rules}\n\nDocuments:\n{_join_docs(context_docs)}\n\n"
        f"Question: {query}\nAnswer:"
    )


def prompt_summarize(texts: Iterable[str]) -> str:
    joined = "\n\n".join(str(t) for t in texts)
    return (
        "Summarize the following texts into a single concise summary that "
        f"keeps the key facts.\n\nTexts:\n{joined}\n\nSummary:"
    )


def prompt_rerank(doc: str, query: str) -> str:
    return (
        "Rate how relevant the document is to the query on a scale of 1 to "
        "5, where 5 means highly relevant. Reply with ONLY the number.\n\n"
        f"Document:\n{doc}\n\nQuery: {query}\nScore:"
    )
