"""RAG question answering — standard and adaptive strategies.

Reference: xpacks/llm/question_answering.py (BaseRAGQuestionAnswerer:280,
AdaptiveRAGQuestionAnswerer:478, geometric escalation
answer_with_geometric_rag_strategy(_from_index):88,153, RAGClient:645).

The adaptive strategy is the cost-saving loop: ask with n docs; if the
model answers the "No information found" sentinel, re-ask with n*factor
docs (prompts.prompt_qa_geometric_rag makes the sentinel reliable).
"""

from __future__ import annotations

import json as _json
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json
from pathway_tpu.xpacks.llm import llms, prompts
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


async def answer_with_geometric_rag_strategy(
        questions: list[str], documents: list[list[str]],
        llm_chat_model: llms.BaseChat, n_starting_documents: int,
        factor: int, max_iterations: int,
        strict_prompt: bool = False) -> list[str]:
    """Per-question geometric escalation over already-retrieved docs
    (reference question_answering.py:88)."""
    chat = llm_chat_model.prepared_async() \
        if isinstance(llm_chat_model, udfs.UDF) \
        else udfs.coerce_async(llm_chat_model)
    answers: list[str] = []
    for question, docs in zip(questions, documents):
        n = n_starting_documents
        answer = prompts.NO_INFO_ANSWER
        for _ in range(max_iterations):
            context = docs[:n]
            prompt = prompts.prompt_qa_geometric_rag(
                context, question, strict_prompt=strict_prompt)
            result = await chat([{"role": "user", "content": prompt}])
            if result and prompts.NO_INFO_ANSWER.lower() not in \
                    str(result).lower():
                answer = str(result)
                break
            if n >= len(docs):
                break
            n *= factor
        answers.append(answer)
    return answers


def answer_with_geometric_rag_strategy_from_index(
        questions, index, documents_column,
        llm_chat_model: llms.BaseChat, n_starting_documents: int,
        factor: int, max_iterations: int,
        metadata_filter=None, strict_prompt: bool = False):
    """Retrieval + escalation in one expression (reference :153).

    ``questions`` is a column of question strings; the index is queried
    ONCE for the maximum document count the escalation could need
    (n_starting_documents * factor^(max_iterations-1)), and the geometric
    loop then runs locally over that retrieved list — each extra iteration
    costs an LLM call but no retrieval. Returns an answer column; a
    question with no answer yields None."""
    from pathway_tpu.internals import expression as ex

    max_documents = n_starting_documents * factor ** (max_iterations - 1)
    if isinstance(documents_column, ex.ColumnReference):
        documents_column_name = documents_column.name
    else:
        documents_column_name = documents_column

    retrieved = index.query_as_of_now(
        questions, number_of_matches=max_documents, collapse_rows=True,
        metadata_filter=metadata_filter)
    docs = retrieved.select(
        _pw_documents=pw.coalesce(pw.this[documents_column_name], ()))

    @pw.udf
    async def escalate(question, documents) -> str | None:
        doc_list = [str(d) for d in (documents or ())]
        answers = await answer_with_geometric_rag_strategy(
            [str(question)], [doc_list], llm_chat_model,
            n_starting_documents, factor, max_iterations,
            strict_prompt=strict_prompt)
        answer = answers[0]
        return None if answer == prompts.NO_INFO_ANSWER else answer

    question_view = questions.table.ix(pw.this.id, context=docs)
    result = docs.select(
        answer=escalate(getattr(question_view, questions.name),
                        pw.this._pw_documents))
    return result.answer


class BaseRAGQuestionAnswerer:
    """Standard RAG: retrieve k docs, build prompt, one LLM call; REST
    endpoints answer/retrieve/statistics/summarize
    (reference question_answering.py:280)."""

    def __init__(self, llm: llms.BaseChat, indexer: VectorStoreServer, *,
                 default_llm_name: str | None = None,
                 short_prompt_template=prompts.prompt_short_qa,
                 long_prompt_template=prompts.prompt_qa,
                 summarize_template=prompts.prompt_summarize,
                 search_topk: int = 6):
        self.llm = llm
        self.indexer = indexer
        self.default_llm_name = default_llm_name
        self.short_prompt_template = short_prompt_template
        self.long_prompt_template = long_prompt_template
        self.summarize_template = summarize_template
        self.search_topk = search_topk
        self.server = None
        self._pending_endpoints: list[tuple] = []

    # -- schemas (reference :300-340 — optional fields carry defaults so
    # a minimal POST body works, e.g. just {"prompt": ...}) --------------
    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None = pw.column_definition(default_value=None)
        model: str | None = pw.column_definition(default_value=None)
        response_type: str = pw.column_definition(default_value="short")

    class SummarizeQuerySchema(pw.Schema):
        text_list: Any
        model: str | None = pw.column_definition(default_value=None)

    class RetrieveQuerySchema(pw.Schema):
        query: str
        k: int = pw.column_definition(default_value=3)
        metadata_filter: str | None = pw.column_definition(
            default_value=None)
        filepath_globpattern: str | None = pw.column_definition(
            default_value=None)

    class StatisticsQuerySchema(pw.Schema):
        pass

    # -- endpoint logic ------------------------------------------------
    def answer_query(self, queries: "pw.Table") -> "pw.Table":
        retrieved = self.indexer.index.query_as_of_now(
            queries.prompt, number_of_matches=self.search_topk,
            collapse_rows=True, metadata_filter=queries.filters)
        short_t, long_t = self.short_prompt_template, self.long_prompt_template

        @pw.udf
        def build_prompt(texts, prompt, response_type) -> str:
            docs = list(texts or ())
            if response_type == "short":
                return short_t(docs, prompt)
            return long_t(docs, prompt)

        with_prompt = retrieved.select(
            rag_prompt=build_prompt(pw.this.text, queries.ix(
                pw.this.id, context=retrieved).prompt, queries.ix(
                pw.this.id, context=retrieved).response_type))
        return with_prompt.select(
            result=self.llm(llms.prompt_chat_single_qa(pw.this.rag_prompt)))

    def summarize_query(self, summarize_queries: "pw.Table") -> "pw.Table":
        template = self.summarize_template

        @pw.udf
        def build(text_list) -> str:
            items = text_list.value if isinstance(text_list, Json) \
                else text_list
            return template([str(t) for t in (items or [])])

        q = summarize_queries.select(prompt=build(pw.this.text_list))
        return q.select(
            result=self.llm(llms.prompt_chat_single_qa(pw.this.prompt)))

    def retrieve(self, queries: "pw.Table") -> "pw.Table":
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: "pw.Table") -> "pw.Table":
        return self.indexer.statistics_query(queries)

    # -- serving (reference :462-476) ----------------------------------
    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        from pathway_tpu.xpacks.llm.servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **rest_kwargs)

    def run_server(self, host: str = "0.0.0.0", port: int = 8000, *,
                   threaded: bool = False, with_cache: bool = True,
                   **kwargs):
        if self.server is None:
            self.build_server(host, port)
        return self.server.run(threaded=threaded, with_cache=with_cache,
                               **kwargs)


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Geometric doc-count escalation (reference :478): start with
    n_starting_documents, multiply by factor on the no-info sentinel."""

    def __init__(self, llm: llms.BaseChat, indexer: VectorStoreServer, *,
                 n_starting_documents: int = 2, factor: int = 2,
                 max_iterations: int = 4, strict_prompt: bool = False,
                 **kwargs):
        max_docs = n_starting_documents * factor ** (max_iterations - 1)
        kwargs.setdefault("search_topk", max_docs)
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, queries: "pw.Table") -> "pw.Table":
        retrieved = self.indexer.index.query_as_of_now(
            queries.prompt, number_of_matches=self.search_topk,
            collapse_rows=True, metadata_filter=queries.filters)
        llm_model = self.llm
        n0, factor, max_iter = (self.n_starting_documents, self.factor,
                                self.max_iterations)
        strict = self.strict_prompt

        @pw.udf
        async def adaptive_answer(texts, prompt) -> str:
            docs = [str(t) for t in (texts or ())]
            answers = await answer_with_geometric_rag_strategy(
                [str(prompt)], [docs], llm_model, n0, factor, max_iter,
                strict_prompt=strict)
            return answers[0]

        return retrieved.select(
            result=adaptive_answer(pw.this.text, queries.ix(
                pw.this.id, context=retrieved).prompt))


class DeckRetriever(BaseRAGQuestionAnswerer):
    """Slide-deck retrieval server (reference :598): retrieval-only — the
    answer route takes retrieval-shaped queries (query/k/filters)."""

    excluded_response_metadata = ["b64_image"]

    AnswerQuerySchema = BaseRAGQuestionAnswerer.RetrieveQuerySchema

    def answer_query(self, queries: "pw.Table") -> "pw.Table":
        return self.retrieve(queries)


class RAGClient:
    """HTTP client for the QA REST servers (reference :645)."""

    def __init__(self, host: str | None = None, port: int | None = None,
                 url: str | None = None, timeout: int = 90,
                 additional_headers: dict | None = None):
        if url is None:
            if host is None:
                raise ValueError("either url or host must be given")
            url = f"http://{host}:{port or 8000}"
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.additional_headers = additional_headers or {}

    def _post(self, route: str, payload: dict):
        import urllib.request

        req = urllib.request.Request(
            self.url + route, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **self.additional_headers})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return _json.loads(resp.read())

    def answer(self, prompt: str, filters: str | None = None,
               model: str | None = None, response_type: str = "long"):
        return self._post("/v1/pw_ai_answer", {
            "prompt": prompt, "filters": filters, "model": model,
            "response_type": response_type})

    pw_ai_answer = answer

    def summarize(self, text_list: list[str], model: str | None = None):
        return self._post("/v1/pw_ai_summary", {
            "text_list": text_list, "model": model})

    pw_ai_summary = summarize

    def retrieve(self, query: str, k: int = 6,
                 metadata_filter: str | None = None,
                 filepath_globpattern: str | None = None):
        return self._post("/v1/retrieve", {
            "query": query, "k": k, "metadata_filter": metadata_filter,
            "filepath_globpattern": filepath_globpattern})

    def statistics(self):
        return self._post("/v1/statistics", {})

    def list_documents(self, metadata_filter: str | None = None,
                       filepath_globpattern: str | None = None):
        return self._post("/v1/pw_list_documents", {
            "metadata_filter": metadata_filter,
            "filepath_globpattern": filepath_globpattern})
