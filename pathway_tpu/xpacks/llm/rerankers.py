"""Rerankers — score (doc, query) pairs for retrieval refinement.

Reference: xpacks/llm/rerankers.py (rerank_topk_filter:15, LLMReranker:54,
CrossEncoderReranker:182, EncoderReranker:247, FlashRankReranker:315).
``EncoderReranker`` composes with any embedder UDF — pair it with
``JaxEncoderEmbedder`` for the TPU-native path (batched bf16 forward,
cosine on device-normalized embeddings).
"""

from __future__ import annotations

import re

import numpy as np

from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json
from pathway_tpu.xpacks.llm import llms, prompts
from pathway_tpu.xpacks.llm._utils import _import_or_raise


@udfs.udf
def rerank_topk_filter(docs: list, scores: list[float],
                       k: int = 5) -> tuple[list, list[float]]:
    """Keep the k best-scored docs (reference rerankers.py:15)."""
    order = np.argsort(scores)[::-1][:k]
    return ([docs[i] for i in order], [float(scores[i]) for i in order])


class LLMReranker(udfs.UDF):
    """LLM-as-judge 1-5 relevance score (reference rerankers.py:54)."""

    def __init__(self, llm: llms.BaseChat, *,
                 retry_strategy: udfs.AsyncRetryStrategy | None = None,
                 cache_strategy: udfs.CacheStrategy | None = None,
                 use_logit_bias: bool | None = None, **kwargs):
        executor = udfs.async_executor(retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy,
                         **kwargs)
        self.llm = llm

    async def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        if isinstance(doc, Json):
            doc = str(doc.value.get("text", doc.value)) \
                if isinstance(doc.value, dict) else str(doc.value)
        prompt = prompts.prompt_rerank(str(doc), str(query))
        answer = await self.llm.prepared_async()(
            [{"role": "user", "content": prompt}], **kwargs)
        match = re.search(r"[1-5]", str(answer))
        if match is None:
            raise ValueError(f"reranker got unparsable score: {answer!r}")
        return float(match.group())


class EncoderReranker(udfs.UDF):
    """Bi-encoder cosine similarity reranker (reference rerankers.py:247).
    ``embedder`` is any BaseEmbedder — use JaxEncoderEmbedder for TPU."""

    def __init__(self, embedder, **kwargs):
        kwargs.setdefault("batch", True)
        super().__init__(**kwargs)
        self.embedder = embedder

    def _embed(self, texts: list[str]) -> np.ndarray:
        if hasattr(self.embedder, "embed_batch"):
            return np.asarray(self.embedder.embed_batch(texts))
        from pathway_tpu.xpacks.llm._utils import _unwrap_udf

        f = _unwrap_udf(self.embedder)
        return np.stack([np.asarray(f(t)) for t in texts])

    def __wrapped__(self, docs: list, queries: list, **kwargs) -> list[float]:
        texts = []
        for d in docs:
            if isinstance(d, Json):
                d = d.value.get("text", d.value) \
                    if isinstance(d.value, dict) else d.value
            texts.append(str(d))
        emb = self._embed(texts + [str(q) for q in queries])
        doc_emb, q_emb = emb[:len(texts)], emb[len(texts):]

        def norm(x):
            return x / np.maximum(
                np.linalg.norm(x, axis=-1, keepdims=True), 1e-12)

        return [float(s) for s in np.sum(
            norm(doc_emb) * norm(q_emb), axis=-1)]


class CrossEncoderReranker(udfs.UDF):
    """sentence-transformers CrossEncoder (reference rerankers.py:182)."""

    def __init__(self, model_name: str, *,
                 cache_strategy: udfs.CacheStrategy | None = None, **kwargs):
        kwargs.setdefault("batch", True)
        super().__init__(cache_strategy=cache_strategy, **kwargs)
        st = _import_or_raise("sentence_transformers", "CrossEncoderReranker")
        self.model = st.CrossEncoder(model_name)

    def __wrapped__(self, docs: list, queries: list, **kwargs) -> list[float]:
        pairs = [[str(q), str(d.value.get("text", d.value)
                              if isinstance(d, Json) and isinstance(d.value, dict)
                              else d)]
                 for d, q in zip(docs, queries)]
        return [float(s) for s in self.model.predict(pairs)]


class FlashRankReranker(udfs.UDF):
    """flashrank listwise reranker (reference rerankers.py:315)."""

    def __init__(self, model_name: str = "ms-marco-TinyBERT-L-2-v2",
                 **kwargs):
        super().__init__(**kwargs)
        flashrank = _import_or_raise("flashrank", "FlashRankReranker")
        self.ranker = flashrank.Ranker(model_name=model_name)
        self._flashrank = flashrank

    def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        if isinstance(doc, Json):
            doc = doc.value.get("text", doc.value) \
                if isinstance(doc.value, dict) else doc.value
        req = self._flashrank.RerankRequest(
            query=str(query), passages=[{"text": str(doc)}])
        return float(self.ranker.rerank(req)[0]["score"])
