"""pw.xpacks — extension packs (reference: python/pathway/xpacks/)."""

from pathway_tpu.xpacks import llm  # noqa: F401

__all__ = ["llm"]

from pathway_tpu.xpacks import connectors  # noqa: F401
