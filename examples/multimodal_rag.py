"""Multimodal RAG serving template (BASELINE config 4: CLIP image+text
embeddings into one live index; reference counterpart: the multimodal
gpt-4o template built on SlidesVectorStoreServer,
xpacks/llm/vector_store.py:571).

Watches a directory of images, embeds them with the in-repo CLIP dual
encoder (models/clip.py), and serves cross-modal retrieval over REST:
text queries are embedded by the TEXT tower into the same space the
images live in, so `/v1/retrieve` returns the matching image files.

Run:
    python examples/multimodal_rag.py ./images --port 8080
then:
    curl -X POST localhost:8080/v1/retrieve \
         -d '{"query": "a red square", "k": 2}'

With random weights retrieval is structural only; pass --params to load
trained CLIP weights (np.savez of the param tree) for meaningful ranking.
"""

from __future__ import annotations

import argparse

import numpy as np

import pathway_tpu as pw
from pathway_tpu.models.clip import ClipConfig
from pathway_tpu.stdlib.indexing import default_brute_force_knn_document_index
from pathway_tpu.io.http import PathwayWebserver, rest_connector
from pathway_tpu.xpacks.llm.embedders import ClipEmbedder


def build(images_dir: str, *, host: str = "127.0.0.1", port: int = 8080,
          tiny: bool = False) -> None:
    """Construct the cross-modal retrieval graph (no execution)."""
    config = ClipConfig.tiny() if tiny else ClipConfig()
    emb = ClipEmbedder(config=config)
    image_udf = emb.image()

    images = pw.io.fs.read(images_dir, format="binary", mode="streaming",
                           with_metadata=True)
    images = images.select(
        path=pw.apply(lambda m: m.value.get("path") if m else None,
                      images._metadata),
        vec=image_udf(images.data),
    )
    index = default_brute_force_knn_document_index(
        images.vec, images, dimensions=config.embed_dim)

    class QuerySchema(pw.Schema):
        query: str
        k: int = 2

    ws = PathwayWebserver(host=host, port=port)
    queries, writer = rest_connector(
        webserver=ws, route="/v1/retrieve", schema=QuerySchema,
        delete_completed_queries=True)
    qv = queries.select(queries.k, vec=emb(queries.query))
    hits = index.query_as_of_now(qv.vec, number_of_matches=qv.k)
    results = qv.select(
        result=pw.apply(lambda paths: list(paths or ()),
                        hits.restrict(qv).path))
    writer(results)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("images", help="directory of image files (png/jpg)")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny CLIP config (tests/offline smoke)")
    args = ap.parse_args()

    build(args.images, host=args.host, port=args.port, tiny=args.tiny)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)


if __name__ == "__main__":
    main()
elif __name__ == "__pathway_check__":
    # graph-only import by `python -m pathway_tpu check`; tiny CLIP keeps
    # param init to a few ms
    build("./images", tiny=True)
