"""Adaptive-RAG serving template (reference:
python/pathway/xpacks/llm/question_answering.py:478 AdaptiveRAGQuestionAnswerer
+ templates). Live document indexing + REST question answering with geometric
document-count escalation.

Run:
    python examples/adaptive_rag.py ./docs --port 8080
then:
    curl -X POST localhost:8080/v1/pw_ai_answer \
         -d '{"prompt": "what is a quokka?"}'

Uses the local BGE checkpoint when present in the HF cache; otherwise a
deterministic hash embedder so the template runs anywhere (the reference's
test-suite pattern: fake embedder standing in for the model).
"""

from __future__ import annotations

import argparse
import hashlib

import numpy as np

import pathway_tpu as pw
from pathway_tpu.models.hf_loader import find_local_checkpoint
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer)
from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


def make_embedder(force_hash: bool = False):
    if not force_hash and find_local_checkpoint("BAAI/bge-small-en-v1.5"):
        from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

        return JaxEncoderEmbedder(model="BAAI/bge-small-en-v1.5")

    @pw.udf(deterministic=True)
    def hash_embed(text: str) -> np.ndarray:
        v = np.zeros(64)
        for tok in text.lower().split():
            h = int(hashlib.md5(tok.encode()).hexdigest(), 16)
            v[h % 64] += 1.0
        n = np.linalg.norm(v)
        return v / n if n else v

    return hash_embed


class EchoChat(pw.udfs.UDF):
    """Offline stand-in for an LLM chat: echoes the top context line.
    Swap for pw.xpacks.llm.llms.OpenAIChat(...) with credentials."""

    def __wrapped__(self, messages, **kwargs) -> str:
        if isinstance(messages, list):  # chat-messages form
            text = "\n".join(str(m.get("content", m)) if isinstance(m, dict)
                             else str(m) for m in messages)
        else:
            text = str(messages)
        lines = [l.strip() for l in text.splitlines() if l.strip()]
        docs, in_docs = [], False
        for l in lines:
            low = l.lower()
            if low.startswith("documents"):
                in_docs = True
                continue
            if low.startswith(("question", "answer")):
                in_docs = False
                continue
            if in_docs and not l.startswith("[doc"):
                docs.append(l)
        if not docs:
            return "No information found"
        return f"[context] {max(docs, key=len)[:200]}"


def build(docs_dir: str, *, port: int = 8080,
          force_hash_embedder: bool = False):
    """Construct the adaptive-RAG serving graph; returns the answerer
    (its graph is fully built — only run_server() executes anything)."""
    docs = pw.io.fs.read(docs_dir, format="plaintext_by_file",
                         mode="streaming", with_metadata=True)
    store = VectorStoreServer(
        docs, embedder=make_embedder(force_hash=force_hash_embedder),
        splitter=TokenCountSplitter(max_tokens=120))
    answerer = AdaptiveRAGQuestionAnswerer(
        llm=EchoChat(), indexer=store, n_starting_documents=2, factor=2,
        max_iterations=3)
    answerer.build_server(host="0.0.0.0", port=port)
    return answerer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("docs_dir")
    ap.add_argument("--port", type=int, default=8080)
    args = ap.parse_args()

    answerer = build(args.docs_dir, port=args.port)
    answerer.run_server()


if __name__ == "__main__":
    main()
elif __name__ == "__pathway_check__":
    # graph-only import by `python -m pathway_tpu check`; the hash
    # embedder keeps collection model-free even when checkpoints exist
    build("./docs", force_hash_embedder=True)
