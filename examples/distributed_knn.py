"""Distributed streaming KNN template (BASELINE config 5: multi-worker
distributed KNN over a message stream, pod-scale shard over ICI).

A live stream of documents (Kafka when configured, otherwise a watched
directory standing in for the topic) is embedded and added to a KNN index
whose slab is SHARDED OVER THE DEVICE MESH: with N chips visible, each
holds 1/N of the vectors in HBM and queries fan out over ICI with a
per-shard top-k merge (parallel/sharded_knn.py — the TPU-native
counterpart of the reference's per-worker index instances,
src/external_integration/mod.rs:46). On one chip it degrades to the
single-slab index; the sharding is exercised chipless via the 8-device
virtual CPU mesh (tests/test_parallel.py, dryrun_multichip).

Run:
    python examples/distributed_knn.py ./docs --port 8080
    # or against Kafka:
    python examples/distributed_knn.py --kafka localhost:9092 --topic docs
then:
    curl -X POST localhost:8080/v1/retrieve -d '{"query": "ring attention"}'
"""

from __future__ import annotations

import argparse
import hashlib

import numpy as np

import pathway_tpu as pw
from pathway_tpu.models.hf_loader import find_local_checkpoint
from pathway_tpu.stdlib.indexing import default_brute_force_knn_document_index
from pathway_tpu.io.http import PathwayWebserver, rest_connector


def make_embedder(dim_holder: dict, force_hash: bool = False):
    if not force_hash and find_local_checkpoint("BAAI/bge-small-en-v1.5"):
        from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

        emb = JaxEncoderEmbedder(model="BAAI/bge-small-en-v1.5")
        dim_holder["dim"] = emb.get_embedding_dimension()
        return emb

    dim_holder["dim"] = 64

    @pw.udf(deterministic=True)
    def hash_embed(text: str) -> np.ndarray:
        v = np.zeros(64)
        for tok in str(text).lower().split():
            h = int(hashlib.md5(tok.encode()).hexdigest(), 16)
            v[h % 64] += 1.0
        n = np.linalg.norm(v)
        return v / n if n else v

    return hash_embed


def build(*, docs_dir: str | None = None, kafka: str | None = None,
          topic: str = "docs", host: str = "127.0.0.1", port: int = 8080,
          force_hash_embedder: bool = False) -> None:
    """Construct the sharded-KNN serving graph (no execution)."""
    if kafka:
        docs = pw.io.kafka.read(
            {"bootstrap.servers": kafka, "group.id": "pw-knn"},
            topic=topic, format="plaintext")
    else:
        docs = pw.io.fs.read(docs_dir, format="plaintext_by_file",
                             mode="streaming")

    holder: dict = {}
    embedder = make_embedder(holder, force_hash=force_hash_embedder)
    # mesh='auto': >1 device on the data axis -> slab sharded over ICI
    # with per-shard top-k merge; 1 device -> plain HBM slab. bf16 halves
    # per-chip slab bytes/scan time; dtype="int8" halves them again
    # (~30M vectors/chip at 384 dims)
    index = default_brute_force_knn_document_index(
        docs.data, docs, dimensions=holder["dim"], embedder=embedder,
        mesh="auto", dtype="bfloat16")

    class QuerySchema(pw.Schema):
        query: str
        k: int = 3

    ws = PathwayWebserver(host=host, port=port)
    queries, writer = rest_connector(
        webserver=ws, route="/v1/retrieve", schema=QuerySchema,
        delete_completed_queries=True)
    hits = index.query_as_of_now(queries.query, number_of_matches=queries.k)
    results = queries.select(
        result=pw.apply(lambda t: list(t or ()),
                        hits.restrict(queries).data))
    writer(results)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("docs", nargs="?", help="directory standing in for the "
                    "stream when --kafka is not given")
    ap.add_argument("--kafka", help="bootstrap servers, e.g. localhost:9092")
    ap.add_argument("--topic", default="docs")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()

    if not args.kafka and not args.docs:
        ap.error("pass a docs directory or --kafka")
    build(docs_dir=args.docs, kafka=args.kafka, topic=args.topic,
          host=args.host, port=args.port)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)


if __name__ == "__main__":
    main()
elif __name__ == "__pathway_check__":
    # graph-only import by `python -m pathway_tpu check`; the hash
    # embedder keeps collection model-free even when checkpoints exist
    build(docs_dir="./docs", force_hash_embedder=True)
