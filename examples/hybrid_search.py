"""Hybrid search serving template: BM25 full-text (phrase queries,
stemming) fused with HNSW vector retrieval by reciprocal-rank fusion
(reference: stdlib/indexing/hybrid_index.py HybridIndex + the tantivy and
usearch integrations).

Run:
    python examples/hybrid_search.py ./docs --port 8080
then:
    curl -X POST localhost:8080/search -d '{"query": "ring attention"}'
    curl -X POST localhost:8080/search -d '{"query": "\\"ring attention\\""}'

Quoted segments are phrase queries (adjacency-required on the BM25 leg);
the vector leg uses the native HNSW engine (approximate, sublinear). Both
legs update live as files appear in the watched directory.
"""

from __future__ import annotations

import argparse
import hashlib

import numpy as np

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import DataIndex, TantivyBM25
from pathway_tpu.stdlib.indexing.hybrid_index import HybridDataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import USearchKnn

DIM = 64


@pw.udf(deterministic=True)
def embed(text: str) -> np.ndarray:
    """Deterministic hash embedder so the template runs anywhere; swap for
    JaxEncoderEmbedder(model="BAAI/bge-small-en-v1.5") with the checkpoint."""
    v = np.zeros(DIM)
    for tok in str(text).lower().split():
        h = int(hashlib.md5(tok.encode()).hexdigest(), 16)
        v[h % DIM] += 1.0
    n = np.linalg.norm(v)
    return v / n if n else v


def build(docs_dir: str, port: int, k: int) -> None:
    """Construct the hybrid-search graph (no execution)."""
    docs = pw.io.fs.read(docs_dir, format="plaintext_by_file",
                         mode="streaming", with_metadata=True)
    docs = docs.select(text=pw.this.data)

    # both legs consume the same raw text column: the BM25 leg tokenizes
    # it (phrases included) and the vector leg embeds it index-side
    # (embedder= makes DataIndex embed corpus AND query columns itself)
    text_index = DataIndex(
        docs, TantivyBM25(docs.text, stemming=True))
    vector_index = DataIndex(
        docs, USearchKnn(docs.text, dimensions=DIM, metric="cos",
                         embedder=embed))

    class QuerySchema(pw.Schema):
        query: str

    ws = pw.io.http.PathwayWebserver(host="0.0.0.0", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=ws, route="/search", schema=QuerySchema,
        delete_completed_queries=True)

    fused = HybridDataIndex(docs, [text_index, vector_index])
    res = fused.query_as_of_now(queries.query, number_of_matches=k)
    out = res.select(result=pw.apply(
        lambda ts: list(ts or ()), pw.this.text))
    writer(out)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("docs", help="directory of text files to watch")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--k", type=int, default=3)
    args = ap.parse_args()

    build(args.docs, args.port, args.k)
    print(f"hybrid search at http://0.0.0.0:{args.port}/search "
          f"(BM25 phrase+stem ⊕ HNSW, RRF)")
    pw.run()


if __name__ == "__main__":
    main()
elif __name__ == "__pathway_check__":
    # graph-only import by `python -m pathway_tpu check`
    build("./docs", port=8080, k=3)