"""Streaming ETL template (reference: the WordCount / Kafka-ETL templates,
docs/2.developers/7.templates): tail a directory of JSONLines order events,
join against a dimension file, aggregate revenue per category with a
sliding window, and stream results to CSV — with live dashboard and
Prometheus /metrics.

Run:
    python examples/streaming_etl.py ./orders ./categories.csv ./out.csv
"""

from __future__ import annotations

import argparse

import pathway_tpu as pw


class Order(pw.Schema):
    item: str
    qty: int
    price: float
    ts: int


class Category(pw.Schema):
    item: str
    category: str


def build(orders_dir: str, categories_csv: str, out_csv: str) -> None:
    """Construct the ETL graph (no execution — `pw.run` happens in main)."""
    orders = pw.io.fs.read(orders_dir, format="json", schema=Order,
                           mode="streaming")
    cats = pw.io.fs.read(categories_csv, format="csv",
                         schema=Category, mode="static")

    enriched = orders.join(cats, orders.item == cats.item).select(
        orders.item, orders.qty, orders.price, orders.ts, cats.category,
        revenue=orders.qty * orders.price)
    by_cat = enriched.windowby(
        enriched.ts, window=pw.temporal.sliding(hop=60, duration=300),
        instance=enriched.category).reduce(
        category=pw.this._pw_instance,
        window_start=pw.this._pw_window_start,
        revenue=pw.reducers.sum(pw.this.revenue),
        n_orders=pw.reducers.count())

    pw.io.fs.write(by_cat, out_csv, format="csv")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("orders_dir")
    ap.add_argument("categories_csv")
    ap.add_argument("out_csv")
    args = ap.parse_args()

    build(args.orders_dir, args.categories_csv, args.out_csv)
    pw.run(monitoring_level=pw.MonitoringLevel.ALL, with_http_server=True)


if __name__ == "__main__":
    main()
elif __name__ == "__pathway_check__":
    # `python -m pathway_tpu check` imports under this name: build the real
    # graph on placeholder inputs so the analyzer sees the full plan DAG
    # (paths are never opened — connectors only read at pw.run time)
    build("./orders", "./categories.csv", "./out.csv")
