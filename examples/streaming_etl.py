"""Streaming ETL template (reference: the WordCount / Kafka-ETL templates,
docs/2.developers/7.templates): tail a directory of JSONLines order events,
join against a dimension file, score each order with a traceable
(device-dispatched) batch UDF, aggregate revenue per category with a
sliding window, and stream results to CSV — with live dashboard,
Prometheus /metrics + /healthz, and supervised connectors (retry with
capped-jittered backoff; degrade instead of crash unless --strict).

The scoring UDF is ``batch=True, device=True``: whole engine batches
dispatch as one XLA call, and with ``PATHWAY_DEVICE_INFLIGHT >= 2`` (the
default) the scheduler overlaps each tick's device leg with the next
tick's host-side parsing/joining (README "Pipelined execution").

Run:
    python examples/streaming_etl.py ./orders ./categories.csv ./out.csv \
        [--max-retries 5] [--strict]
"""

from __future__ import annotations

import argparse

import jax.numpy as jnp
import numpy as np

import pathway_tpu as pw


@pw.udf(batch=True, device=True, deterministic=True, return_type=float)
def demand_score(qty: list[int], price: list[float]) -> list[float]:
    """Columnar demand score — one traceable XLA dispatch per engine
    batch (log1p(qty) * sqrt(price)); rides the pipelined device leg."""
    q = jnp.asarray(np.asarray(qty, np.float32))
    p = jnp.asarray(np.asarray(price, np.float32))
    s = jnp.log1p(q) * jnp.sqrt(p)
    return [float(v) for v in np.asarray(s)]


class Order(pw.Schema):
    item: str
    qty: int
    price: float
    ts: int


class Category(pw.Schema):
    item: str
    category: str


def build(orders_dir: str, categories_csv: str, out_csv: str,
          max_retries: int = 5) -> None:
    """Construct the ETL graph (no execution — `pw.run` happens in main)."""
    # a flaky order feed is retried with capped, jittered backoff before
    # the failure escalates (README "Fault tolerance")
    orders_policy = pw.ConnectorPolicy(
        max_retries=max_retries,
        retry_strategy=pw.ExponentialBackoffRetryStrategy(
            initial_delay_ms=500, backoff_factor=2.0, max_delay_ms=15_000,
            jitter=True),
        connect_timeout=60.0)
    # the stable persistent_id makes the feed resumable under
    # pw.persistence (crash/restart replays the committed watermark and
    # the reader seeks past it — tests/durability_canary.py)
    orders = pw.io.fs.read(orders_dir, format="json", schema=Order,
                           mode="streaming", persistent_id="orders",
                           connector_policy=orders_policy)
    cats = pw.io.fs.read(categories_csv, format="csv",
                         schema=Category, mode="static")

    enriched = orders.join(cats, orders.item == cats.item).select(
        orders.item, orders.qty, orders.price, orders.ts, cats.category,
        revenue=orders.qty * orders.price)
    enriched = enriched.select(
        *[enriched[c] for c in ("item", "qty", "price", "ts", "category",
                                "revenue")],
        score=demand_score(enriched.qty, enriched.price))
    by_cat = enriched.windowby(
        enriched.ts, window=pw.temporal.sliding(hop=60, duration=300),
        instance=enriched.category).reduce(
        category=pw.this._pw_instance,
        window_start=pw.this._pw_window_start,
        revenue=pw.reducers.sum(pw.this.revenue),
        n_orders=pw.reducers.count(),
        peak_demand=pw.reducers.max(pw.this.score))

    pw.io.fs.write(by_cat, out_csv, format="csv")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("orders_dir")
    ap.add_argument("categories_csv")
    ap.add_argument("out_csv")
    ap.add_argument("--max-retries", type=int, default=5,
                    help="order-feed restarts before escalation")
    ap.add_argument("--strict", action="store_true",
                    help="terminate (and re-raise) when a connector's "
                         "retries are exhausted instead of serving "
                         "degraded")
    args = ap.parse_args()

    build(args.orders_dir, args.categories_csv, args.out_csv,
          max_retries=args.max_retries)
    # non-strict mode keeps serving on a permanently-failed feed; the
    # degradation is visible on /healthz (503) and in /metrics
    pw.run(monitoring_level=pw.MonitoringLevel.ALL, with_http_server=True,
           terminate_on_error=args.strict,
           watchdog=pw.WatchdogConfig(tick_deadline_s=30.0))


if __name__ == "__main__":
    main()
elif __name__ == "__pathway_check__":
    # `python -m pathway_tpu check` imports under this name: build the real
    # graph on placeholder inputs so the analyzer sees the full plan DAG
    # (paths are never opened — connectors only read at pw.run time)
    build("./orders", "./categories.csv", "./out.csv")
