"""Streaming ETL template (reference: the WordCount / Kafka-ETL templates,
docs/2.developers/7.templates): tail a directory of JSONLines order events,
join against a dimension file, aggregate revenue per category with a
sliding window, and stream results to CSV — with live dashboard and
Prometheus /metrics.

Run:
    python examples/streaming_etl.py ./orders ./categories.csv ./out.csv
"""

from __future__ import annotations

import argparse

import pathway_tpu as pw


class Order(pw.Schema):
    item: str
    qty: int
    price: float
    ts: int


class Category(pw.Schema):
    item: str
    category: str


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("orders_dir")
    ap.add_argument("categories_csv")
    ap.add_argument("out_csv")
    args = ap.parse_args()

    orders = pw.io.fs.read(args.orders_dir, format="json", schema=Order,
                           mode="streaming")
    cats = pw.io.fs.read(args.categories_csv, format="csv",
                         schema=Category, mode="static")

    enriched = orders.join(cats, orders.item == cats.item).select(
        orders.item, orders.qty, orders.price, orders.ts, cats.category,
        revenue=orders.qty * orders.price)
    by_cat = enriched.windowby(
        enriched.ts, window=pw.temporal.sliding(hop=60, duration=300),
        instance=enriched.category).reduce(
        category=pw.this._pw_instance,
        window_start=pw.this._pw_window_start,
        revenue=pw.reducers.sum(pw.this.revenue),
        n_orders=pw.reducers.count())

    pw.io.fs.write(by_cat, args.out_csv, format="csv")
    pw.run(monitoring_level=pw.MonitoringLevel.ALL, with_http_server=True)


if __name__ == "__main__":
    main()
