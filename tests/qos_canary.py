"""QoS control-plane canary: the closed SLO loop, proven end to end
(same pattern as serving_canary.py / durability_canary.py). Two gates:

1. **byte-identity + deferral** (in-process) — a deterministic counts
   pipeline streamed under a deliberately tiny ingest budget
   (``PATHWAY_QOS_ALWAYS_BUDGET`` + clamped partition) must produce
   consolidated outputs IDENTICAL to the QoS-off run while the
   controller demonstrably deferred ingest across ticks: deferral moves
   timestamps, never content, and exactly-once is untouched.

2. **bench qos leg** (subprocess) — the real serving workload (KNN
   index under heavy live ingest + closed-loop rest queries) run
   QoS-off then QoS-on, gating:

   - >=1 observed ingest deferral and >=1 shed under the induced
     overload burst, with every shed counted in ``qos_shed_total``
     (never silent — the 503s carried ``Retry-After``, asserted inside
     the leg);
   - >=2 queries coalesced into shared kernel dispatches;
   - the controller's trade, both directions: QoS-on lowers query p50
     AND measurably defers ingest (lower ingest rate); QoS-off is the
     inverse — full ingest rate, blown-out latency;
   - ``BENCH_LASTGOOD.json`` checkpointed + JSON artifact written (the
     ROADMAP evidence rule).

   The ABSOLUTE bar — ``knn_p50_e2e_ms < 20`` under live ingest — arms
   via ``QOS_CANARY_REQUIRE_SLO=1`` (device-capable runners: the
   ROADMAP done-bar rides the driver's device artifact). On CPU-only
   runners the number is REPORTED loudly instead: this container's
   no-ingest serving floor measured ~30 ms (jax-on-CPU dispatch + 2
   cores), the same reason the PR-6 serving canary reports rather than
   thresholds — gating an unreachable bar would only teach CI to
   ignore red.

Exits 0 iff all armed gates hold. Run: ``python tests/qos_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

SLO_GATE_MS = float(os.environ.get("QOS_CANARY_P50_GATE_MS", "20"))
REQUIRE_SLO = os.environ.get("QOS_CANARY_REQUIRE_SLO", "") not in ("", "0")

# calibration for the bench child: heavy-but-sustainable ingest pressure
# (beyond-capacity overload measures nothing but the backlog) at the
# production defaults — pipelined device dispatch, default budget
# floor/deadline — sized down only for canary wall-clock. Measured on
# this container: p50 ~547ms -> ~45ms (12x) while ingest halves; the
# relative-trade gates have an order-of-magnitude margin.
_BENCH_ENV = {
    "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    "BENCH_SKIP": ",".join(sorted(
        {"etl", "autojit", "scaleout", "paging", "durability", "recovery",
         "replica", "embed", "framework", "knn", "serving"})),
    "BENCH_QOS_N": os.environ.get("BENCH_QOS_N", "8000"),
    "BENCH_QOS_QUERIES": os.environ.get("BENCH_QOS_QUERIES", "16"),
    "BENCH_QOS_WARMUP": os.environ.get("BENCH_QOS_WARMUP", "4"),
    "BENCH_QOS_BURST": os.environ.get("BENCH_QOS_BURST", "16"),
}


def gate_identity_and_deferral() -> str | None:
    """Deterministic pipeline, QoS-off vs QoS-on with a clamped ingest
    partition: consolidated outputs must be byte-identical while the
    controller demonstrably deferred rows to later ticks."""
    import pathway_tpu as pw
    from pathway_tpu.engine.qos import current_controller, install_controller
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.testing.faults import flaky_subject

    words = [f"w{i % 101}" for i in range(2000)]

    def run_counts(qos_on: bool) -> tuple[dict, dict]:
        G.clear()
        install_controller(None)
        env = {
            "PATHWAY_QOS": "1" if qos_on else "0",
            "PATHWAY_QOS_ALWAYS_BUDGET": "1" if qos_on else "",
            "PATHWAY_QOS_MIN_INGEST_ROWS": "32",
            "PATHWAY_QOS_MAX_INGEST_ROWS": "32",
        }
        old = {k: os.environ.get(k) for k in env}
        for k, v in env.items():
            if v:
                os.environ[k] = v
            else:
                os.environ.pop(k, None)
        try:
            t = pw.io.python.read(
                flaky_subject([{"word": w} for w in words], fail_after=0,
                              fail_attempts=0),
                schema=pw.schema_from_types(word=str),
                autocommit_duration_ms=5)
            counts = t.groupby(t.word).reduce(word=t.word,
                                              c=pw.reducers.count())
            state: dict = {}
            captured: list = []

            def on_change(key, row, time, is_addition):
                if not captured:
                    ctl = current_controller()
                    if ctl is not None:
                        captured.append(ctl)
                if is_addition:
                    state[row["word"]] = row["c"]
                elif state.get(row["word"]) == row["c"]:
                    del state[row["word"]]

            pw.io.subscribe(counts, on_change)
            pw.run()
            return state, (captured[0].summary() if captured else {})
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            G.clear()
            install_controller(None)

    base, _ = run_counts(qos_on=False)
    qos, stats = run_counts(qos_on=True)
    if sum(base.values()) != len(words):
        return f"baseline dropped rows: {sum(base.values())}/{len(words)}"
    if qos != base:
        missing = {k: v for k, v in base.items() if qos.get(k) != v}
        return (f"IDENTITY VIOLATION: QoS-on consolidated outputs differ "
                f"from QoS-off on {len(missing)} key(s): "
                f"{dict(list(missing.items())[:5])}")
    if stats.get("ingest_deferrals", 0) < 1:
        return (f"no ingest deferral observed under a 32-row/tick clamp "
                f"(stats: {stats})")
    if stats.get("shed_total", 0) != 0:
        return f"ingest-only run shed queries?! {stats}"
    print(f"identity gate OK: {len(base)} keys identical, "
          f"{stats['ingest_deferrals']} deferrals "
          f"({stats['deferred_rows_total']} rows rode later ticks)")
    return None


def gate_bench_before_after() -> str | None:
    root = pathlib.Path(__file__).resolve().parent.parent
    artifact = pathlib.Path(os.environ.get("QOS_CANARY_ARTIFACT",
                                           root / "qos_canary_artifact.json"))
    lastgood = root / pathlib.Path(
        os.environ.get("BENCH_LASTGOOD_PATH", "BENCH_LASTGOOD.json"))
    env = dict(os.environ)
    env.update(_BENCH_ENV)
    proc = subprocess.run(
        [sys.executable, str(root / "bench.py")], cwd=str(root),
        env=env, capture_output=True, text=True, timeout=1500)
    last = None
    for line in (proc.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                last = json.loads(line)
            except json.JSONDecodeError:
                continue
    if last is None:
        tail = (proc.stderr or "").strip().splitlines()[-5:]
        return f"bench emitted no JSON (rc={proc.returncode}): {tail}"
    if "qos_error" in last:
        return f"qos leg failed: {last['qos_error']}"
    artifact.write_text(json.dumps(last, indent=1))
    required = ("qos_off_knn_p50_e2e_ms", "qos_on_knn_p50_e2e_ms",
                "qos_off_ingest_rate_rps", "qos_on_ingest_rate_rps",
                "qos_shed_total", "qos_ingest_deferrals",
                "qos_coalesced_queries")
    for field in required:
        if field not in last:
            return f"bench JSON missing {field}: {sorted(last)}"
    # -- mechanisms: visible shedding, deferral, coalescing ---------------
    if last["qos_shed_total"] < 1:
        return (f"no shed under the induced overload burst "
                f"(qos_shed_total={last['qos_shed_total']})")
    if last["qos_ingest_deferrals"] < 1:
        return (f"no ingest deferral under budget pressure "
                f"(qos_ingest_deferrals={last['qos_ingest_deferrals']})")
    if last["qos_coalesced_queries"] < 2:
        return (f"no cross-request coalescing observed "
                f"(qos_coalesced_queries={last['qos_coalesced_queries']})")
    # -- the trade, both directions ---------------------------------------
    on_p50 = last["qos_on_knn_p50_e2e_ms"]
    off_p50 = last["qos_off_knn_p50_e2e_ms"]
    on_rate = last["qos_on_ingest_rate_rps"]
    off_rate = last["qos_off_ingest_rate_rps"]
    if not on_p50 < off_p50:
        return (f"QoS-on did not lower query p50: on={on_p50}ms vs "
                f"off={off_p50}ms")
    if not on_rate < off_rate:
        return (f"QoS-on did not defer ingest: on={on_rate} rows/s vs "
                f"off={off_rate} rows/s")
    # -- the absolute bar --------------------------------------------------
    if on_p50 < SLO_GATE_MS:
        slo_note = f"MEETS the {SLO_GATE_MS}ms target"
    elif REQUIRE_SLO:
        return (f"qos_on_knn_p50_e2e_ms={on_p50}ms misses the "
                f"{SLO_GATE_MS}ms bar (QOS_CANARY_REQUIRE_SLO armed)")
    else:
        slo_note = (f"reported, not gated: {on_p50}ms vs the "
                    f"{SLO_GATE_MS}ms device bar (CPU runner — no-ingest "
                    f"serving floor is above the bar here; arm with "
                    f"QOS_CANARY_REQUIRE_SLO=1 on capable runners)")
    # -- evidence rule -----------------------------------------------------
    if not lastgood.exists():
        return "BENCH_LASTGOOD.json was not written"
    good = json.loads(lastgood.read_text())["result"]
    if good.get("qos_on_knn_p50_e2e_ms") != on_p50:
        return f"lastgood diverged from bench JSON: {good}"
    print(f"bench qos gate OK: p50 {off_p50}ms -> {on_p50}ms "
          f"({last.get('qos_p50_speedup', '?')}x) while ingest "
          f"{off_rate} -> {on_rate} rows/s; shed={last['qos_shed_total']} "
          f"deferrals={last['qos_ingest_deferrals']} "
          f"coalesced={last['qos_coalesced_queries']}q/"
          f"{last['qos_coalesced_dispatches']}d; {slo_note}")
    return None


def main() -> int:
    for name, gate in (("identity+deferral", gate_identity_and_deferral),
                       ("bench-before-after", gate_bench_before_after)):
        err = gate()
        if err:
            print(f"QOS CANARY FAILED [{name}]: {err}", file=sys.stderr)
            return 1
        print(f"gate {name}: OK", flush=True)
    print("qos canary: all gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
