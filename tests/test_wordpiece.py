"""WordPiece tokenizer: pure-Python vs native C++ parity, and golden
parity against the HF BertTokenizer algorithm (constructed offline from a
local vocab file — no network). Replaces the reference's dependency on HF
`tokenizers` inside SentenceTransformerEmbedder
(python/pathway/xpacks/llm/embedders.py:268-326)."""

from __future__ import annotations

import numpy as np
import pytest

from pathway_tpu.models.tokenizer import (WordPieceTokenizer,
                                          make_synthetic_vocab)

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "##ing",
    "over", "lazy", "dog", "un", "##believ", "##able", "!", ",", ".",
    "##anana", "b", "1", "##2", "##3", "好", "世", "界",
    "a", "##b", "##c",
]

CASES = [
    "The quick brown fox jumped over the lazy dog!",
    "unbelievable, jumps jumping",
    "banana b123 bb",
    "hello 世界 好",  # hello is OOV → [UNK]; CJK chars split singly
    "",
    "   ",
    "a,b.c",
    "word-with-dashes and under_scores",
    "x" * 150,  # over max word bytes → [UNK]
    "MiXeD CaSe LOWERing",
    "tabs\tand\nnewlines  multiple   spaces",
    "trailing punctuation...",
    "ab abc ba cab",  # exercises longest-match-first backtracking
    "a b",       # narrow no-break space (French number grouping)
    "a\x1cb\x1db\x1eb\x1fb",  # ASCII separators Python isspace() accepts
    "a\x85b  c d　e",  # NEL + more unicode spaces
    "fox\u2066over\u2069 dog",  # bidi isolates dropped, words fuse
    "fox\u2028over\u2029dog",  # Zl/Zp split like str.split()
    "a\u200bb \u00adc",  # zero-width space + soft hyphen dropped,
]


def _tok(**kw):
    return WordPieceTokenizer(VOCAB, **kw)


def test_basic_encoding():
    tok = _tok(prefer_native=False)
    ids = tok.encode("The quick brown fox")
    assert ids[0] == tok.cls_id and ids[-1] == tok.sep_id
    inner = ids[1:-1]
    assert inner == [tok.vocab["the"], tok.vocab["quick"],
                     tok.vocab["brown"], tok.vocab["fox"]]
    # longest-match-first: "jumped" → jump + ##ed
    ids2 = tok.encode("jumped")[1:-1]
    assert ids2 == [tok.vocab["jump"], tok.vocab["##ed"]]
    # whole-word UNK when any piece fails
    assert tok.encode("zzz")[1:-1] == [tok.unk_id]
    # banana → b + ##anana
    assert tok.encode("banana")[1:-1] == [tok.vocab["b"],
                                          tok.vocab["##anana"]]


def test_python_native_parity():
    native = _tok(prefer_native=True)
    if native._native is None:
        pytest.skip("native toolchain unavailable")
    python = _tok(prefer_native=False)
    for case in CASES:
        nids, nmask = native.batch([case], pad_to=64)
        pids, pmask = python.batch([case], pad_to=64)
        assert nids.tolist() == pids.tolist(), case
        assert nmask.tolist() == pmask.tolist(), case
    # one batched call over all cases must equal per-case calls
    nids, _ = native.batch(CASES, pad_to=64)
    pids, _ = python.batch(CASES, pad_to=64)
    assert nids.tolist() == pids.tolist()


def test_hf_bert_tokenizer_golden_parity():
    """Both engines must reproduce HF BertTokenizer ids on a shared vocab
    (accent stripping off — a documented simplification)."""
    transformers = pytest.importorskip("transformers")
    import tempfile, os

    with tempfile.TemporaryDirectory() as d:
        vocab_path = os.path.join(d, "vocab.txt")
        with open(vocab_path, "w", encoding="utf-8") as f:
            f.write("\n".join(VOCAB) + "\n")
        hf = transformers.BertTokenizer(
            vocab_file=vocab_path, do_lower_case=True, strip_accents=False,
            tokenize_chinese_chars=True)
        ours = WordPieceTokenizer.from_vocab_file(vocab_path)
        python = WordPieceTokenizer.from_vocab_file(vocab_path,
                                                    prefer_native=False)
        for case in CASES:
            want = hf(case, add_special_tokens=True,
                      truncation=True, max_length=64)["input_ids"]
            got_n = ours.encode(case, max_len=64) if ours._native is None \
                else ours.batch([case], pad_to=64)[0][0]
            got_p = python.encode(case, max_len=64)
            if not isinstance(got_n, list):
                got_n = [int(x) for x in got_n if x != ours.pad_id
                         or want.count(ours.pad_id)]
                got_n = got_n[: len(want)]
            assert got_p == want, (case, got_p, want)
            assert got_n == want, (case, got_n, want)


def test_batch_padding_and_mask():
    tok = _tok(prefer_native=False)
    ids, mask = tok.batch(["the quick", "fox"], pad_to=8)
    assert ids.shape == (2, 8) and mask.shape == (2, 8)
    assert ids[0, 0] == tok.cls_id
    assert mask[0].sum() == 4 and mask[1].sum() == 3  # CLS + words + SEP
    assert (ids[~mask] == tok.pad_id).all()
    # truncation to pad_to keeps the trailing SEP
    long_ids, long_mask = tok.batch(["the quick brown fox " * 20], pad_to=8)
    assert long_mask.all() and long_ids[0, -1] == tok.sep_id


def test_vocab_file_roundtrip(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    tok = WordPieceTokenizer.from_vocab_file(str(p), prefer_native=False)
    assert tok.vocab_size == len(VOCAB)
    assert tok.cls_id == 2 and tok.pad_id == 0


def test_synthetic_vocab_covers_corpus():
    words = [f"word{i}" for i in range(500)]
    vocab = make_synthetic_vocab(words, vocab_size=4096)
    assert len(vocab) == 4096 and len(set(vocab)) == 4096
    tok = WordPieceTokenizer(vocab, prefer_native=False)
    ids = tok.encode("word1 word499")[1:-1]
    assert tok.unk_id not in ids
    # OOV words split into pieces rather than collapsing to UNK
    ids2 = tok.encode("zq9k")[1:-1]
    assert len(ids2) >= 1
