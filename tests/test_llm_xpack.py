"""LLM xpack tests — fake models injected like the reference test suite
(xpacks/llm/tests/test_vector_store.py:107-121)."""

import asyncio

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_rows, table_to_pandas
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.xpacks.llm import llms, prompts, rerankers, splitters
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseRAGQuestionAnswerer,
    answer_with_geometric_rag_strategy,
)
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


@pw.udf
def fake_embedder(text: str) -> np.ndarray:
    """Deterministic bag-of-words embedding (dimension 16). Uses md5, not
    hash(): str hashing is PYTHONHASHSEED-randomized and unlucky seeds
    collide enough to flip nearest-neighbour ranks (seed 6 did)."""
    import hashlib

    vec = np.zeros(16)
    for w in str(text).lower().split():
        h = int(hashlib.md5(w.encode()).hexdigest(), 16)
        vec[h % 16] += 1.0
    n = np.linalg.norm(vec)
    return vec / n if n else vec


class FakeChat(llms.BaseChat):
    """Echoes doc 1's text when there is context, else the no-info answer."""

    def __init__(self, min_docs: int = 1):
        super().__init__()
        self.min_docs = min_docs
        self.calls = []

    async def __wrapped__(self, messages, **kwargs):
        prompt = self._as_messages(messages)[-1]["content"]
        n_docs = prompt.count("[doc ")
        self.calls.append(n_docs)
        if n_docs >= self.min_docs:
            return f"answer from {n_docs} docs"
        return prompts.NO_INFO_ANSWER


def _docs_table():
    schema = sch.schema_from_types(data=str, _metadata=pw.Json)
    rows = [
        ("the quick brown fox jumps over the lazy dog",
         Json({"path": "/a.txt", "modified_at": 100})),
        ("TPU systolic arrays multiply matrices fast",
         Json({"path": "/b.txt", "modified_at": 200})),
        ("ring attention rotates blocks around the interconnect",
         Json({"path": "/c.txt", "modified_at": 300})),
    ]
    return table_from_rows(schema, rows)


def _result_rows(table):
    df = table_to_pandas(table, include_id=False)
    return df.to_dict("records")


def test_vector_store_retrieve_batch():
    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    schema = sch.schema_from_types(query=str, k=int,
                                   metadata_filter=type(None),
                                   filepath_globpattern=type(None))
    queries = table_from_rows(
        schema, [("systolic arrays multiply", 2, None, None)])
    res = store.retrieve_query(queries)
    rows = _result_rows(res.select(result=pw.this.result))
    pw.run()
    matches = rows[0]["result"].value
    assert len(matches) == 2
    assert "systolic" in matches[0]["text"]
    assert matches[0]["metadata"]["path"] == "/b.txt"


def test_vector_store_statistics_and_inputs():
    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    stats_q = table_from_rows(sch.schema_from_types(dummy=int), [(1,)])
    res = store.statistics_query(stats_q)
    rows = _result_rows(res)
    stats = rows[0]["result"].value
    assert stats["file_count"] == 3
    assert stats["last_modified"] == 300

    inputs_q = table_from_rows(
        sch.schema_from_types(metadata_filter=type(None),
                              filepath_globpattern=str),
        [(None, "/b*")])
    res2 = store.inputs_query(inputs_q)
    rows2 = _result_rows(res2)
    assert rows2[0]["result"].value == ["/b.txt"]


def test_vector_store_with_splitter():
    long_doc = ". ".join(f"sentence number {i} about topic{i % 3}"
                         for i in range(40)) + "."
    schema = sch.schema_from_types(data=str, _metadata=pw.Json)
    docs = table_from_rows(schema, [(long_doc, Json({"path": "/l.txt"}))])
    store = VectorStoreServer(
        docs, embedder=fake_embedder,
        splitter=splitters.TokenCountSplitter(min_tokens=10, max_tokens=40))
    chunks = store._graph["chunks"]
    df = table_to_pandas(chunks.select(text=pw.this.text))
    assert len(df) > 1  # split into multiple chunks
    for t in df["text"]:
        assert len(t.split()) <= 4 * 40


def test_token_count_splitter_bounds():
    sp = splitters.TokenCountSplitter(min_tokens=5, max_tokens=20)
    text = "word " * 200
    chunks = sp.chunk(text)
    assert all(5 <= len(c.split()) <= 20 for c, _ in chunks[:-1])
    assert sum(len(c.split()) for c, _ in chunks) == 200
    assert sp.chunk("") == []


def test_token_count_splitter_never_exceeds_max():
    """Regression: a short chunk must not absorb a long sentence past
    max_tokens (oversized chunks get truncated by the embedder)."""
    sp = splitters.TokenCountSplitter(min_tokens=50, max_tokens=100)
    text = " ".join(["a"] * 39) + ". " + " ".join(["b"] * 89) + "."
    chunks = sp.chunk(text)
    token_counts = [len(sp._tokenize(c)) for c, _ in chunks]
    assert all(n <= 100 for n in token_counts), token_counts
    assert sum(c.count("a") + c.count("b") for c, _ in chunks) == 128


def test_deck_retriever_builds():
    from pathway_tpu.xpacks.llm.question_answering import DeckRetriever

    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    deck = DeckRetriever(FakeChat(), store)
    # the answer route takes retrieval-shaped queries
    queries = table_from_rows(
        deck.AnswerQuerySchema,
        [("systolic arrays", 1, None, None)])
    res = deck.answer_query(queries)
    rows = _result_rows(res)
    assert "systolic" in rows[0]["result"].value[0]["text"]


def test_default_cache_applies_to_unconfigured_udfs():
    from pathway_tpu.internals import udfs

    calls = []

    @pw.udf
    async def expensive(x: int) -> int:
        calls.append(x)
        return x * 2

    cache = udfs.InMemoryCache()
    udfs.set_default_cache(cache)
    try:
        fn = expensive.prepared_async()
        assert asyncio.run(fn(3)) == 6
        assert asyncio.run(fn(3)) == 6
        assert calls == [3]  # second call served from cache
    finally:
        udfs.set_default_cache(None)


def test_prepared_async_applies_retry():
    from pathway_tpu.internals import udfs

    attempts = []

    class FlakyChat(llms.BaseChat):
        def __init__(self):
            super().__init__(retry_strategy=udfs.FixedDelayRetryStrategy(
                max_retries=2, delay_ms=1))

        async def __wrapped__(self, messages, **kwargs):
            attempts.append(1)
            if len(attempts) < 2:
                raise RuntimeError("transient")
            return "ok"

    chat = FlakyChat()
    out = asyncio.run(chat.prepared_async()(
        [{"role": "user", "content": "hi"}]))
    assert out == "ok"
    assert len(attempts) == 2


def test_geometric_rag_strategy_escalates():
    chat = FakeChat(min_docs=4)
    answers = asyncio.run(answer_with_geometric_rag_strategy(
        ["q"], [[f"doc{i}" for i in range(8)]], chat,
        n_starting_documents=1, factor=2, max_iterations=5))
    assert answers[0] == "answer from 4 docs"
    assert chat.calls == [1, 2, 4]


def test_geometric_rag_strategy_gives_up():
    chat = FakeChat(min_docs=100)
    answers = asyncio.run(answer_with_geometric_rag_strategy(
        ["q"], [["doc"]], chat, n_starting_documents=1, factor=2,
        max_iterations=3))
    assert answers[0] == prompts.NO_INFO_ANSWER


def test_base_rag_answer_query():
    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    rag = BaseRAGQuestionAnswerer(FakeChat(), store, search_topk=2)
    queries = table_from_rows(
        sch.schema_from_types(prompt=str, filters=type(None),
                              model=type(None), response_type=str),
        [("what do systolic arrays do", None, None, "long")])
    res = rag.answer_query(queries)
    rows = _result_rows(res)
    assert rows[0]["result"] == "answer from 2 docs"


def test_adaptive_rag_answer_query():
    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    chat = FakeChat(min_docs=2)
    rag = AdaptiveRAGQuestionAnswerer(
        chat, store, n_starting_documents=1, factor=2, max_iterations=3)
    queries = table_from_rows(
        sch.schema_from_types(prompt=str, filters=type(None),
                              model=type(None), response_type=str),
        [("quick brown fox", None, None, "long")])
    res = rag.answer_query(queries)
    rows = _result_rows(res)
    assert rows[0]["result"] == "answer from 2 docs"
    assert chat.calls == [1, 2]


def test_rerank_topk_filter_and_encoder_reranker():
    docs = [f"d{i}" for i in range(5)]
    scores = [0.1, 0.9, 0.5, 0.7, 0.3]
    fn = rerankers.rerank_topk_filter.func
    kept, kept_scores = fn(docs, scores, 3)
    assert kept == ["d1", "d3", "d2"]
    assert kept_scores == [0.9, 0.7, 0.5]

    vocab = ["quick", "brown", "fox", "systolic", "arrays"]

    def vocab_embedder(text):
        words = str(text).lower().split()
        return np.array([float(w in words) for w in vocab])

    rr = rerankers.EncoderReranker(vocab_embedder)
    out = rr.func(["quick brown fox", "systolic arrays"],
                  ["brown fox", "brown fox"])
    assert out[0] > out[1]


def test_llm_reranker_with_fake_chat():
    class ScoreChat(llms.BaseChat):
        async def __wrapped__(self, messages, **kwargs):
            prompt = self._as_messages(messages)[-1]["content"]
            return "5" if "relevant-doc" in prompt else "1"

    rr = rerankers.LLMReranker(ScoreChat())
    score = asyncio.run(rr.func("relevant-doc text", "query"))
    assert score == 5.0
    score2 = asyncio.run(rr.func("other", "query"))
    assert score2 == 1.0


def test_jax_encoder_embedder():
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    emb = JaxEncoderEmbedder(config=EncoderConfig.tiny())
    assert emb.get_embedding_dimension() == 64
    out = emb.embed_batch(["hello world", "foo bar baz"])
    assert out.shape == (2, 64)
    # deterministic + distinct
    out2 = emb.embed_batch(["hello world", "foo bar baz"])
    np.testing.assert_array_equal(out, out2)
    assert not np.allclose(out[0], out[1])


def test_jax_embedder_in_pipeline():
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    emb = JaxEncoderEmbedder(config=EncoderConfig.tiny())
    store = VectorStoreServer(_docs_table(), embedder=emb)
    queries = table_from_rows(
        sch.schema_from_types(query=str, k=int, metadata_filter=type(None),
                              filepath_globpattern=type(None)),
        [("TPU systolic arrays multiply matrices fast", 1, None, None)])
    res = store.retrieve_query(queries)
    rows = _result_rows(res)
    matches = rows[0]["result"].value
    assert len(matches) == 1
    assert "systolic" in matches[0]["text"]


def test_geometric_rag_from_index_escalates():
    """The direct path retrieves max docs ONCE and escalates locally
    (reference question_answering.py:153): the fake chat needs 2 docs, so
    calls go 1 -> 2 with a single retrieval behind them."""
    from pathway_tpu.xpacks.llm.question_answering import (
        answer_with_geometric_rag_strategy_from_index)

    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    chat = FakeChat(min_docs=2)
    queries = table_from_rows(
        sch.schema_from_types(prompt=str), [("quick brown fox",)])
    answer = answer_with_geometric_rag_strategy_from_index(
        queries.prompt, store.index, "text", chat,
        n_starting_documents=1, factor=2, max_iterations=3)
    rows = _result_rows(answer.table)
    assert rows[0]["answer"] == "answer from 2 docs"
    assert chat.calls == [1, 2]


def test_geometric_rag_from_index_returns_none_when_unanswerable():
    from pathway_tpu.xpacks.llm.question_answering import (
        answer_with_geometric_rag_strategy_from_index)

    store = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    chat = FakeChat(min_docs=100)  # never satisfied
    queries = table_from_rows(
        sch.schema_from_types(prompt=str), [("quick brown fox",)])
    answer = answer_with_geometric_rag_strategy_from_index(
        queries.prompt, store.index, "text", chat,
        n_starting_documents=2, factor=2, max_iterations=2)
    rows = _result_rows(answer.table)
    assert rows[0]["answer"] is None
    # escalation 2 -> 4, capped by the 3 retrievable docs
    assert chat.calls == [2, 3]


def test_fused_device_embedding_index_path():
    """A device-capable embedder (encode_batch_device) makes the engine
    index take raw text: no UDF embedding column, embeddings born on
    device (ops/knn.py DeviceEmbeddingKnnIndex). Retrieval, metadata
    filters, and incremental updates must behave exactly like the
    UDF-embedded path."""
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.ops.knn import DeviceEmbeddingKnnIndex
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index,
    )
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    emb = JaxEncoderEmbedder(config=EncoderConfig.tiny())
    docs = _docs_table()
    index = default_brute_force_knn_document_index(
        docs.data, docs, embedder=emb, dimensions=64,
        metadata_column=docs._metadata)
    assert index.inner_index.embeds_internally
    built = index.inner_index.factory().build()
    assert isinstance(built, DeviceEmbeddingKnnIndex)

    queries = table_from_rows(
        sch.schema_from_types(q=str), [("systolic arrays multiply",)])
    res = index.query_as_of_now(queries.q, number_of_matches=1,
                                collapse_rows=False)
    rows = _result_rows(res.select(data=res.data))
    assert len(rows) == 1 and "systolic" in rows[0]["data"]

    # same query against the classic UDF-embedded path must agree
    res2 = index.query_as_of_now(queries.q, number_of_matches=3,
                                 collapse_rows=False,
                                 metadata_filter="modified_at > `150`")
    rows2 = _result_rows(res2.select(data=res2.data))
    # the filter drops /a.txt (modified_at 100); both survivors return
    assert len(rows2) == 2
    assert not any("quick brown fox" in r["data"] for r in rows2)


def test_fused_index_handles_document_update_and_delete():
    """Retraction + re-add of a doc through the fused device-embedding
    index: a query after the update must see only the NEW text, and a
    deleted doc must stop matching."""
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index,
    )
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    emb = JaxEncoderEmbedder(config=EncoderConfig.tiny())
    schema = sch.schema_from_types(doc_id=int, data=str)
    docs = table_from_rows(
        schema,
        [(1, "systolic arrays multiply matrices", 0, 1),
         (2, "ring attention rotates blocks", 0, 1),
         (1, "systolic arrays multiply matrices", 2, -1),  # doc replaced
         (1, "pallas kernels tile vmem", 2, 1)],
        is_stream=True)
    docs = docs.with_id_from(docs.doc_id)
    index = default_brute_force_knn_document_index(
        docs.data, docs, embedder=emb, dimensions=64)
    queries = table_from_rows(
        sch.schema_from_types(q=str), [("systolic arrays", 4, 1)],
        is_stream=True)
    res = index.query_as_of_now(queries.q, number_of_matches=2,
                                collapse_rows=False)
    rows = _result_rows(res.select(data=res.data))
    texts = {r["data"] for r in rows}
    assert "systolic arrays multiply matrices" not in texts
    assert texts <= {"ring attention rotates blocks",
                     "pallas kernels tile vmem"} and texts


# ---------------------------------------------------------------------------
# SlidesVectorStoreServer: per-slide indexing + metadata-rich /v1/inputs
# ---------------------------------------------------------------------------

def test_slides_vector_store_indexes_per_slide():
    from tests.test_doc_extract import make_pptx

    from pathway_tpu.xpacks.llm.vector_store import SlidesVectorStoreServer

    deck = make_pptx([["systolic arrays multiply matrices"],
                      ["ring attention rotates blocks"],
                      ["lazy dog jumps"]])
    schema = sch.schema_from_types(data=bytes, _metadata=pw.Json)
    docs = table_from_rows(
        schema, [(deck, Json({"path": "/deck.pptx", "b64_image": "xxxx"}))])
    store = SlidesVectorStoreServer(docs, embedder=fake_embedder)
    chunks = store._graph["chunks"]
    df = table_to_pandas(chunks.select(text=pw.this.text,
                                       metadata=pw.this.metadata))
    assert len(df) == 3                      # one chunk PER SLIDE
    metas = sorted((m.value for m in df["metadata"]),
                   key=lambda d: d["page"])
    assert [m["page"] for m in metas] == [1, 2, 3]
    assert all(m["total_pages"] == 3 for m in metas)
    assert all(m["path"] == "/deck.pptx" for m in metas)

    schema_q = sch.schema_from_types(query=str, k=int,
                                     metadata_filter=type(None),
                                     filepath_globpattern=type(None))
    queries = table_from_rows(
        schema_q, [("ring attention blocks", 1, None, None)])
    res = store.retrieve_query(queries)
    rows = _result_rows(res.select(result=pw.this.result))
    pw.run()
    (match,) = rows[0]["result"].value
    assert "ring attention" in match["text"]
    assert match["metadata"]["page"] == 2


def test_slides_vector_store_inputs_returns_metadata_dicts():
    from tests.test_doc_extract import make_pptx

    from pathway_tpu.xpacks.llm.vector_store import SlidesVectorStoreServer

    schema = sch.schema_from_types(data=bytes, _metadata=pw.Json)
    docs = table_from_rows(schema, [
        (make_pptx([["alpha"]]),
         Json({"path": "/a.pptx", "b64_image": "A" * 64, "owner": "ann"})),
        (make_pptx([["beta"]]),
         Json({"path": "/b.pptx", "image_base64": "B" * 64})),
    ])
    store = SlidesVectorStoreServer(docs, embedder=fake_embedder)
    inputs_q = table_from_rows(
        sch.schema_from_types(metadata_filter=type(None),
                              filepath_globpattern=type(None)),
        [(None, None)])
    rows = _result_rows(store.inputs_query(inputs_q))
    listing = sorted(rows[0]["result"].value, key=lambda d: d["path"])
    assert [d["path"] for d in listing] == ["/a.pptx", "/b.pptx"]
    assert listing[0]["owner"] == "ann"      # full metadata, not paths
    # bulky image payloads are stripped from the listing
    assert "b64_image" not in listing[0]
    assert "image_base64" not in listing[1]

    glob_q = table_from_rows(
        sch.schema_from_types(metadata_filter=type(None),
                              filepath_globpattern=str),
        [(None, "/b*")])
    rows2 = _result_rows(store.inputs_query(glob_q))
    assert [d["path"] for d in rows2[0]["result"].value] == ["/b.pptx"]


def test_parse_slides_non_deck_fallback():
    from pathway_tpu.xpacks.llm.vector_store import parse_slides

    out = parse_slides(b"plain notes, not a deck")
    assert len(out) == 1
    text, meta = out[0]
    assert "plain notes" in text
    assert meta["page"] == 1 and meta["total_pages"] == 1
