"""Deliberately non-durable code — the CI canary proving the PWT3xx gate
bites.

``python -m pathway_tpu check --durability
tests/durability_negative_example.py`` must exit nonzero:

- ``RollingCountOperator`` mutates ``self.counts`` on the step path but
  defines no ``snapshot_state``/``restore_state`` pair — on recovery its
  state silently degrades to full-WAL replay (PWT301, warning);
- ``save_manifest`` writes a persistence-root-derived path with a plain
  write-mode ``open``, no tmp+fsync+rename — a crash mid-write leaves a
  torn manifest where a checkpoint should be (PWT304, error; this is
  what makes the exit code nonzero without ``--strict``).

The module is never imported by the suite (the checker parses, it does
not execute).
"""

import json


class RollingCountOperator:
    """Stateful operator with no capture/restore pair (PWT301)."""

    def __init__(self):
        self.counts = {}

    def step(self, key):
        self.counts[key] = self.counts.get(key, 0) + 1


def save_manifest(root, manifest):
    """Torn-write hazard on the persistence root (PWT304)."""
    with open(root / "manifest.json", "w") as f:
        f.write(json.dumps(manifest))
