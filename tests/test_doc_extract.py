"""Dependency-free document extraction (xpacks/llm/_doc_extract.py):
PDF content streams, DOCX/PPTX OOXML, HTML — the fallback engine behind
ParseUnstructured/ParseOpenParse (reference parses these via the
unstructured/openparse libraries, xpacks/llm/parsers.py)."""

from __future__ import annotations

import io
import zipfile
import zlib

from pathway_tpu.xpacks.llm._doc_extract import (
    detect_format,
    extract_docx,
    extract_elements,
    extract_html,
    extract_pdf,
    extract_pptx,
)
from pathway_tpu.xpacks.llm.parsers import ParseOpenParse, ParseUnstructured


def make_pdf(pages: list[list[str]], compress=True) -> bytes:
    """Tiny but structurally real PDF: one content stream per page."""
    out = io.BytesIO()
    out.write(b"%PDF-1.4\n")
    for lines in pages:
        ops = [b"BT", b"/F1 12 Tf"]
        for line in lines:
            esc = line.replace("\\", r"\\").replace("(", r"\(") \
                      .replace(")", r"\)")
            ops.append(f"({esc}) Tj".encode())
            ops.append(b"0 -14 Td")
        ops.append(b"ET")
        content = b"\n".join(ops)
        if compress:
            content = zlib.compress(content)
            hdr = b"<< /Length %d /Filter /FlateDecode >>" % len(content)
        else:
            hdr = b"<< /Length %d >>" % len(content)
        out.write(b"1 0 obj\n" + hdr + b"\nstream\n" + content +
                  b"\nendstream\nendobj\n")
    out.write(b"%%EOF\n")
    return out.getvalue()


def make_docx(paragraphs: list[str]) -> bytes:
    ns = "http://schemas.openxmlformats.org/wordprocessingml/2006/main"
    body = "".join(
        f'<w:p><w:r><w:t>{p}</w:t></w:r></w:p>' for p in paragraphs)
    doc = (f'<?xml version="1.0"?><w:document xmlns:w="{ns}">'
           f'<w:body>{body}</w:body></w:document>')
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("[Content_Types].xml", "<Types/>")
        z.writestr("word/document.xml", doc)
    return buf.getvalue()


def make_pptx(slides: list[list[str]]) -> bytes:
    ns = "http://schemas.openxmlformats.org/drawingml/2006/main"
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("[Content_Types].xml", "<Types/>")
        for i, texts in enumerate(slides, 1):
            runs = "".join(f"<a:t>{t}</a:t>" for t in texts)
            z.writestr(f"ppt/slides/slide{i}.xml",
                       f'<?xml version="1.0"?><p:sld '
                       f'xmlns:a="{ns}" xmlns:p="x">{runs}</p:sld>')
    return buf.getvalue()


def test_detect_format():
    assert detect_format(make_pdf([["x"]])) == "pdf"
    assert detect_format(make_docx(["x"])) == "docx"
    assert detect_format(make_pptx([["x"]])) == "pptx"
    assert detect_format(b"<html><body>hi</body></html>") == "html"
    assert detect_format(b"plain words") == "text"


def test_pdf_flate_and_plain():
    for compress in (True, False):
        raw = make_pdf([["Hello TPU world", "second line"],
                        ["page two here"]], compress=compress)
        pages = extract_pdf(raw)
        assert len(pages) == 2
        assert "Hello TPU world" in pages[0]
        assert "second line" in pages[0]
        assert "page two here" in pages[1]


def test_pdf_escapes_and_hex_and_tj_array():
    content = (b"BT (paren \\( inside\\)) Tj 0 -14 Td "
               b"<48656C6C6F> Tj T* "
               b"[(kerned ) -120 (array)] TJ ET")
    raw = (b"%PDF-1.4\n1 0 obj\n<< /Length " + str(len(content)).encode()
           + b" >>\nstream\n" + content + b"\nendstream\nendobj\n%%EOF")
    [page] = extract_pdf(raw)
    assert "paren ( inside)" in page
    assert "Hello" in page
    assert "kerned array" in page


def test_docx_pptx_html():
    assert extract_docx(make_docx(["alpha beta", "gamma"])) == \
        ["alpha beta", "gamma"]
    slides = extract_pptx(make_pptx([["title", "bullet"], ["closing"]]))
    assert slides == ["title\nbullet", "closing"]
    html = (b"<html><head><style>p{}</style><script>var x;</script></head>"
            b"<body><h1>Title</h1><p>one</p><p>two &amp; three</p></body>"
            b"</html>")
    lines = extract_html(html)
    assert lines == ["Title", "one", "two & three"]
    assert all("var x" not in line for line in lines)


def test_parse_unstructured_fallback_modes():
    pdf = make_pdf([["page one text"], ["page two text"]])
    single = ParseUnstructured(mode="single").__wrapped__(pdf)
    assert len(single) == 1 and "page one text" in single[0][0]
    paged = ParseUnstructured(mode="paged").__wrapped__(pdf)
    assert [m["page_number"] for _t, m in paged] == [1, 2]
    elements = ParseUnstructured(mode="elements").__wrapped__(
        make_docx(["first", "second"]))
    assert [t for t, _m in elements] == ["first", "second"]
    assert elements[0][1]["filetype"] == "docx"


def test_parse_openparse_fallback():
    pdf = make_pdf([["content here"]])
    nodes = ParseOpenParse().__wrapped__(pdf)
    assert nodes and "content here" in nodes[0][0]


def test_extract_elements_plain_text():
    [(text, meta)] = extract_elements("just text".encode())
    assert text == "just text" and meta["filetype"] == "text"


def make_table_pdf() -> bytes:
    """A page laying out a 3x3 grid with absolute Tm positions (the shape
    machine-generated table PDFs use) plus a loose paragraph line."""
    cells = [
        ("name", 72, 700), ("qty", 200, 700), ("price", 320, 700),
        ("bolt", 72, 684), ("4", 200, 684), ("0.10", 320, 684),
        ("nut", 72, 668), ("12", 200, 668), ("0.05", 320, 668),
    ]
    ops = [b"BT", b"/F1 10 Tf"]
    for text, x, y in cells:
        ops.append(f"1 0 0 1 {x} {y} Tm ({text}) Tj".encode())
    ops.append(b"1 0 0 1 72 600 Tm (Totals are indicative only.) Tj")
    ops.append(b"ET")
    content = b"\n".join(ops)
    hdr = b"<< /Length %d >>" % len(content)
    return (b"%PDF-1.4\n1 0 obj\n" + hdr + b"\nstream\n" + content
            + b"\nendstream\nendobj\n%%EOF\n")


def test_pdf_table_extraction_structured_rows():
    from pathway_tpu.xpacks.llm import _doc_extract as de

    tables = de.extract_pdf_tables(make_table_pdf())
    assert len(tables) == 1
    assert tables[0]["page"] == 1
    assert tables[0]["rows"] == [
        ["name", "qty", "price"],
        ["bolt", "4", "0.10"],
        ["nut", "12", "0.05"],
    ]
    # the loose paragraph line must NOT be swallowed into the table
    flat = [c for row in tables[0]["rows"] for c in row]
    assert "Totals are indicative only." not in flat


def test_pdf_table_flows_through_extract_elements():
    from pathway_tpu.xpacks.llm import _doc_extract as de

    elements = de.extract_elements(make_table_pdf())
    tables = [(t, m) for t, m in elements if m.get("category") == "Table"]
    assert len(tables) == 1
    text, meta = tables[0]
    assert meta["rows"][1] == ["bolt", "4", "0.10"]
    assert "name | qty | price" in text  # markdown rendering for RAG


def make_table_docx() -> bytes:
    ns = "http://schemas.openxmlformats.org/wordprocessingml/2006/main"
    tbl = (
        "<w:tbl>"
        "<w:tr><w:tc><w:p><w:r><w:t>h1</w:t></w:r></w:p></w:tc>"
        "<w:tc><w:p><w:r><w:t>h2</w:t></w:r></w:p></w:tc></w:tr>"
        "<w:tr><w:tc><w:p><w:r><w:t>a</w:t></w:r></w:p></w:tc>"
        "<w:tc><w:p><w:r><w:t>b</w:t></w:r></w:p></w:tc></w:tr>"
        "</w:tbl>")
    doc = (f'<?xml version="1.0"?><w:document xmlns:w="{ns}">'
           f'<w:body><w:p><w:r><w:t>intro</w:t></w:r></w:p>{tbl}'
           f'</w:body></w:document>')
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("[Content_Types].xml", "<Types/>")
        z.writestr("word/document.xml", doc)
    return buf.getvalue()


def test_docx_table_extraction():
    from pathway_tpu.xpacks.llm import _doc_extract as de

    assert de.extract_docx_tables(make_table_docx()) == [
        [["h1", "h2"], ["a", "b"]]]
    elements = de.extract_elements(make_table_docx())
    cats = [m.get("category") for _t, m in elements]
    assert "Table" in cats and "Paragraph" in cats


def test_table_cells_indexed_exactly_once():
    """Cell text must appear in the Table element only — not duplicated in
    the Page/Paragraph body (double-indexing skews retrieval)."""
    from pathway_tpu.xpacks.llm import _doc_extract as de

    for raw in (make_table_pdf(), make_table_docx()):
        elements = de.extract_elements(raw)
        body_text = "\n".join(
            t for t, m in elements if m.get("category") != "Table")
        for cell in ("bolt", "h1"):
            if any(cell in t for t, m in elements
                   if m.get("category") == "Table"):
                assert cell not in body_text, (cell, body_text)
