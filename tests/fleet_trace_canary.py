"""Fleet observability canary: the /fleet/* plane proven on a REAL
multi-process fleet (PR 14, engine/fleet_observability.py).

Builds the same fleet shape as tests/replica_canary.py — an in-process
QueryRouter fronting a primary + two read replicas, each a full
``pw.run`` OS process — but in observability mode: every member runs
its monitoring HTTP server on an ephemeral port (announced over the
control-channel heartbeat) with the flight recorder on, and the primary
registers with the router too. Under closed-loop load with one SIGKILL
failover, the gates are:

1. **fleet metrics** — the router's ``/fleet/metrics`` serves every
   registered process's families, re-labeled ``{process=,role=}``, with
   exactly one ``# TYPE`` line per family and a ``process="_fleet"``
   counter aggregate; ``/fleet/status`` carries roles, applied ticks,
   staleness and burn rates in one JSON.
2. **failover under load** — ≥ 1 failover observed, ZERO lost queries
   (the PR-12 guarantee, re-proven with tracing on).
3. **merged trace** — ``/fleet/trace`` is ONE clock-aligned Perfetto
   timeline: ≥ 2 processes carry events, at least one request id spans
   ≥ 2 processes, every (pid, tid) track validates under the PR-5 B/E
   nesting checker, and a failed-over request's flow arrow lands on a
   DIFFERENT process than the router (the rescuing replica's track).
4. **perf trajectory** — the canary's own measurements append to
   ``BENCH_HISTORY.jsonl``; ``bench.py --check-regression`` passes on
   the real trajectory and FLAGS a seeded synthetic regression.

Artifacts: the merged trace JSON (``FLEET_TRACE_ARTIFACT``) and the
history file (``BENCH_HISTORY_PATH``). Exits 0 iff all gates hold.
Run: ``python tests/fleet_trace_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

_SAMPLE_RE = re.compile(
    r'^(?P<family>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r'\s+(?P<value>\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _samples(text: str):
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        out.append((m.group("family"), labels, m.group("value")))
    return out


def _get(url: str, timeout: float = 20.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _check_nesting(events) -> None:
    """PR-5 checker, keyed per (pid, tid) — the merged file must stay
    Perfetto-valid after N processes' B/E spans interleave."""
    stacks: dict = {}
    for ev in events:
        key = (ev.get("pid"), ev.get("tid"))
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ev["ph"] == "E":
            stack = stacks.setdefault(key, [])
            assert stack, f"E without B on {key}: {ev['name']}"
            top = stack.pop()
            assert top == ev["name"], \
                f"mis-nested on {key}: E {ev['name']!r} closes {top!r}"
    for key, stack in stacks.items():
        assert not stack, f"unclosed spans on {key}: {stack}"


def _wait_fleet(router, names: set[str], timeout_s: float = 60.0) -> None:
    """Wait until every named process is registered WITH a monitoring
    port (the heartbeat announces it)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        eps = {e.replica_id: e for e in router.endpoints()}
        if names <= set(eps) and all(eps[n].monitoring_port
                                     for n in names):
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"fleet never fully announced monitoring ports: "
        f"{ {e.replica_id: e.monitoring_port for e in router.endpoints()} }")


def main() -> int:
    import bench

    hist = os.environ.setdefault(
        "BENCH_HISTORY_PATH",
        os.path.join(tempfile.gettempdir(),
                     f"fleet_canary_hist_{os.getpid()}.jsonl"))
    tmp = tempfile.mkdtemp(prefix="fleet_canary_")
    fleet = bench._ReplicaFleet(tmp, observability=True)
    try:
        router = fleet.start_router()
        fleet.start_primary()
        fleet.start_replica("r1")
        fleet.start_replica("r2")
        _wait_fleet(router, {"primary", "r1", "r2"})

        # ---- gate 1: /fleet/metrics + /fleet/status (full fleet) ------
        base = f"http://127.0.0.1:{router.port}"
        merged = _get(base + "/fleet/metrics").decode()
        lines = merged.splitlines()
        assert lines[-1] == "# EOF", "merged doc missing the EOF marker"
        fams = [l.split()[2] for l in lines if l.startswith("# TYPE")]
        assert len(fams) == len(set(fams)), (
            f"duplicate # TYPE declarations in the merged doc: "
            f"{[f for f in fams if fams.count(f) > 1][:4]}")
        samples = _samples(merged)
        procs = {labels.get("process") for _f, labels, _v in samples}
        assert {"router", "primary", "r1", "r2"} <= procs, (
            f"/fleet/metrics missing per-process families: {procs}")
        for name in ("primary", "r1", "r2"):
            assert any(f == "pathway_tpu_insertions"
                       and labels.get("process") == name
                       for f, labels, _v in samples), (
                f"no per-process engine family for {name}")
        assert "_fleet" in procs, "no process=\"_fleet\" aggregate"
        status = json.loads(_get(base + "/fleet/status"))
        assert status["role"] == "router" and "burn_rate" in status
        by_name = {m["replica"]: m for m in status["fleet"]}
        assert {"primary", "r1", "r2"} <= set(by_name)
        assert by_name["primary"]["role"] == "primary"
        for n in ("r1", "r2"):
            assert by_name[n]["role"] == "replica"
            assert by_name[n]["applied_tick"] > 0
            assert by_name[n]["staleness_ticks"] >= 0
        print(f"[gate1] /fleet/metrics serves {len(procs)} processes "
              f"({sorted(p for p in procs if p)}), one TYPE per family, "
              f"_fleet aggregates present; /fleet/status has "
              f"roles/ticks/staleness/burn in one JSON")

        # ---- real perf trajectory: several measured points ------------
        # four short steady-state windows (same fleet, same load shape)
        # each append a real fleet_p50_ms row, so the gate-4 regression
        # check evaluates a genuinely multi-point series instead of
        # passing vacuously on a too-young one (a fresh CI checkout has
        # no committed history — BENCH_HISTORY.jsonl is machine-local
        # evidence like BENCH_LASTGOOD.json)
        from pathway_tpu.engine.fleet_observability import \
            append_bench_history

        window_s = float(os.environ.get("FLEET_CANARY_WINDOW_S", 2.0))
        window_p50s = []
        for _ in range(4):
            win = fleet.run_load(window_s, clients=6, warmup_s=0.5)
            if win.get("p50_ms"):
                window_p50s.append(win["p50_ms"])
                append_bench_history(
                    "fleet_canary", {"fleet_p50_ms": win["p50_ms"]},
                    path=hist)
        assert len(window_p50s) >= 4, (
            f"steady-state windows produced too few p50s: {window_p50s}")

        # ---- gate 2: SIGKILL failover under load ----------------------
        load_s = float(os.environ.get("FLEET_CANARY_LOAD_S", 6.0))
        kill = fleet.run_load(load_s, clients=6, warmup_s=1.0,
                              kill_at_s=load_s / 3, kill_rid="r1")
        fleet.wait_deregistered("r1")
        assert kill["queries"] > 0, kill
        assert kill["lost"] == 0, (
            f"{kill['lost']} of {kill['queries']} queries lost across "
            "the SIGKILL — failover leaked load")
        assert router.failovers_total >= 1, (
            "no failover observed: the kill window never exercised the "
            "replay path")
        print(f"[gate2] {kill['queries']} queries across the SIGKILL, "
              f"0 lost, {router.failovers_total} failover(s)")

        # ---- gate 3: one clock-aligned merged trace -------------------
        trace = json.loads(_get(base + "/fleet/trace", timeout=30.0))
        events = trace["traceEvents"]
        fleet_meta = trace["pathway_fleet"]
        roles = {p["role"] for p in fleet_meta["processes"]}
        assert "router" in roles and {"replica", "primary"} & roles, roles
        pids_with_events = {e["pid"] for e in events
                            if e["ph"] in ("B", "E", "b", "e")}
        assert len(pids_with_events) >= 2, (
            f"merged trace carries events from "
            f"{len(pids_with_events)} process(es) only")
        cross = fleet_meta["cross_process_request_ids"]
        assert cross, "no request id spans >= 2 processes in the trace"
        # verify one id end to end straight from the events, not the
        # summary: the same request_id on a router_request span AND a
        # serving-process request span, different pids
        rid = cross[0]
        span_pids = {e["pid"] for e in events
                     if e["ph"] == "b"
                     and (e.get("args") or {}).get("request_id") == rid}
        assert len(span_pids) >= 2, (rid, span_pids)
        _check_nesting(events)
        # failover arrow: a router span that failed over must flow into
        # a DIFFERENT process — the rescuing replica's track
        failed_over = {e["args"]["request_id"] for e in events
                       if e.get("cat") == "router_request"
                       and e["ph"] == "b"
                       and e.get("args", {}).get("failovers", 0) >= 1
                       and e["args"].get("request_id")}
        flows = {}
        for e in events:
            if e.get("cat") == "fleet" and e["ph"] in ("s", "t", "f"):
                flows.setdefault(e["id"], {}).setdefault(
                    e["ph"], set()).add(e["pid"])
        arrows = 0
        for rid in failed_over & {i[len("xreq-"):] for i in flows}:
            flow = flows[f"xreq-{rid}"]
            src = flow.get("s", set())
            dst = flow.get("f", set()) | flow.get("t", set())
            if src and dst and not (src & dst):
                arrows += 1
        assert arrows >= 1, (
            f"no failover flow arrow lands on another process "
            f"(failed-over ids: {len(failed_over)}, flows: {len(flows)})")
        print(f"[gate3] merged trace: {len(events)} events across "
              f"{len(pids_with_events)} processes, {len(cross)} request "
              f"id(s) span processes, nesting valid, {arrows} failover "
              f"arrow(s) into the rescuing replica")
        artifact = os.environ.get("FLEET_TRACE_ARTIFACT")
        if artifact:
            from pathway_tpu.engine.flight_recorder import \
                atomic_write_json

            atomic_write_json(artifact, trace)

        # ---- gate 4: perf-trajectory watch ----------------------------
        # the post-failover load's numbers join the trajectory too (the
        # kill-window p95 is load-shape-specific, so it rides under its
        # own metric names, not the steady-state series)
        append_bench_history("fleet_canary", {
            "fleet_kill_queries": kill["queries"],
            "fleet_lost_queries": kill["lost"],
            "fleet_failovers": router.failovers_total,
        }, path=hist)
        env = dict(os.environ, BENCH_HISTORY_PATH=hist)
        bench_py = str(pathlib.Path(__file__).resolve().parent.parent
                       / "bench.py")
        clean = subprocess.run(
            [sys.executable, bench_py, "--check-regression"],
            capture_output=True, text=True, env=env, timeout=120)
        # NON-vacuous: fleet_p50_ms carries >= 4 real points (> the
        # min-prior floor), so the newest steady-state window was
        # genuinely judged against the trailing median of its siblings
        # — assert the series is old enough to be judged AND passed
        from pathway_tpu.engine.fleet_observability import \
            bench_history_rows

        p50_rows = [r for r in bench_history_rows(hist)
                    if r["metric"] == "fleet_p50_ms"]
        assert len(p50_rows) >= 4, p50_rows
        assert clean.returncode == 0, (
            f"real trajectory flagged as a regression:\n{clean.stdout}"
            f"\n{clean.stderr}")
        # seed a synthetic regression: healthy history, then a 60% drop
        for v in (100.0, 101.0, 99.0, 100.5):
            append_bench_history("canary", {"synthetic_docs_per_s": v},
                                 path=hist)
        append_bench_history("canary", {"synthetic_docs_per_s": 40.0},
                             path=hist)
        flagged = subprocess.run(
            [sys.executable, bench_py, "--check-regression"],
            capture_output=True, text=True, env=env, timeout=120)
        assert flagged.returncode == 1, (
            f"seeded synthetic regression NOT flagged:\n{flagged.stdout}")
        assert "synthetic_docs_per_s" in flagged.stderr, flagged.stderr
        print(f"[gate4] --check-regression: real trajectory clean, "
              f"seeded synthetic regression flagged "
              f"({flagged.stderr.strip().splitlines()[-1]})")
    finally:
        fleet.stop()

    print("fleet trace canary: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
