"""pw.sql — SQL subset compiled to Table ops
(reference: python/pathway/internals/sql.py; parser re-implemented in
internals/sql_parser.py since sqlglot is not vendored)."""

import pytest

import pathway_tpu as pw
from tests.utils import T, rows_of


def _tab():
    return T("""
    name  | dept | salary
    alice | eng  | 100
    bob   | eng  | 80
    carol | ops  | 60
    dave  | ops  | 40
    erin  | hr   | 90
    """)


def test_select_where():
    t = _tab()
    r = pw.sql("SELECT name, salary FROM tab WHERE salary > 70", tab=t)
    assert sorted(rows_of(r)) == [("alice", 100), ("bob", 80), ("erin", 90)]


def test_select_star_and_expressions():
    t = _tab()
    r = pw.sql("SELECT *, salary * 2 AS double FROM tab WHERE dept = 'hr'",
               tab=t)
    assert rows_of(r) == [("erin", "hr", 90, 180)]


def test_arithmetic_and_case():
    t = _tab()
    r = pw.sql(
        """
        SELECT name,
               CASE WHEN salary >= 90 THEN 'high'
                    WHEN salary >= 60 THEN 'mid'
                    ELSE 'low' END AS band
        FROM tab
        """,
        tab=t)
    assert sorted(rows_of(r)) == [
        ("alice", "high"), ("bob", "mid"), ("carol", "mid"),
        ("dave", "low"), ("erin", "high")]


def test_group_by_having():
    t = _tab()
    r = pw.sql(
        """
        SELECT dept, SUM(salary) AS total, COUNT(*) AS n
        FROM tab GROUP BY dept HAVING SUM(salary) > 80
        """,
        tab=t)
    assert sorted(rows_of(r)) == [("eng", 180, 2), ("hr", 90, 1), ("ops", 100, 2)]
    r2 = pw.sql("SELECT dept, AVG(salary) AS a FROM tab GROUP BY dept "
                "HAVING COUNT(*) > 1", tab=t)
    assert sorted(rows_of(r2)) == [("eng", 90.0), ("ops", 50.0)]


def test_global_aggregate():
    t = _tab()
    r = pw.sql("SELECT COUNT(*) AS n, MIN(salary) AS lo, MAX(salary) AS hi "
               "FROM tab", tab=t)
    assert rows_of(r) == [(5, 40, 100)]


def test_join_inner_and_left():
    emp = _tab()
    dept = T("""
    dept | site
    eng  | NYC
    ops  | SF
    """)
    r = pw.sql(
        "SELECT e.name, d.site FROM emp e JOIN dept d ON e.dept = d.dept "
        "WHERE e.salary > 70", emp=emp, dept=dept)
    assert sorted(rows_of(r)) == [("alice", "NYC"), ("bob", "NYC")]
    r2 = pw.sql(
        "SELECT e.name, d.site FROM emp e LEFT JOIN dept d ON e.dept = d.dept",
        emp=emp, dept=dept)
    assert sorted(rows_of(r2), key=repr) == sorted(
        [("alice", "NYC"), ("bob", "NYC"), ("carol", "SF"), ("dave", "SF"),
         ("erin", None)], key=repr)


def test_join_three_way_and_residual_condition():
    a = T("""
    k | x
    1 | 10
    2 | 20
    """)
    b = T("""
    k | y
    1 | 1
    2 | 2
    """)
    c = T("""
    k | z
    1 | 7
    2 | 9
    """)
    r = pw.sql(
        "SELECT a.x, b.y, c.z FROM a JOIN b ON a.k = b.k "
        "JOIN c ON b.k = c.k AND c.z > 8", a=a, b=b, c=c)
    assert rows_of(r) == [(20, 2, 9)]


def test_union_and_intersect():
    t1 = T("""
    v
    1
    2
    3
    """)
    t2 = T("""
    v
    2
    3
    4
    """)
    u = pw.sql("SELECT v FROM t1 UNION SELECT v FROM t2", t1=t1, t2=t2)
    assert sorted(rows_of(u)) == [(1,), (2,), (3,), (4,)]
    ua = pw.sql("SELECT v FROM t1 UNION ALL SELECT v FROM t2", t1=t1, t2=t2)
    assert sorted(rows_of(ua)) == [(1,), (2,), (2,), (3,), (3,), (4,)]
    i = pw.sql("SELECT v FROM t1 INTERSECT SELECT v FROM t2", t1=t1, t2=t2)
    assert sorted(rows_of(i)) == [(2,), (3,)]


def test_with_cte_and_subquery():
    t = _tab()
    r = pw.sql(
        """
        WITH rich AS (SELECT name, dept FROM tab WHERE salary >= 90)
        SELECT dept, COUNT(*) AS n FROM rich GROUP BY dept
        """,
        tab=t)
    assert sorted(rows_of(r)) == [("eng", 1), ("hr", 1)]
    r2 = pw.sql(
        "SELECT name FROM (SELECT name, salary FROM tab WHERE dept = 'eng') s "
        "WHERE s.salary > 90", tab=t)
    assert rows_of(r2) == [("alice",)]


def test_predicates_in_between_like_null():
    t = _tab()
    r = pw.sql("SELECT name FROM tab WHERE dept IN ('eng', 'hr')", tab=t)
    assert sorted(rows_of(r)) == [("alice",), ("bob",), ("erin",)]
    r2 = pw.sql("SELECT name FROM tab WHERE salary BETWEEN 60 AND 90", tab=t)
    assert sorted(rows_of(r2)) == [("bob",), ("carol",), ("erin",)]
    r3 = pw.sql("SELECT name FROM tab WHERE name LIKE '%ar%'", tab=t)
    assert sorted(rows_of(r3)) == [("carol",)]
    r4 = pw.sql("SELECT name FROM tab WHERE name NOT LIKE 'a%' "
                "AND salary NOT IN (40, 60)", tab=t)
    assert sorted(rows_of(r4)) == [("bob",), ("erin",)]


def test_functions_and_distinct():
    t = _tab()
    r = pw.sql("SELECT DISTINCT dept FROM tab", tab=t)
    assert sorted(rows_of(r)) == [("eng",), ("hr",), ("ops",)]
    r2 = pw.sql("SELECT UPPER(name) AS u FROM tab WHERE LENGTH(name) = 3",
                tab=t)
    assert rows_of(r2) == [("BOB",)]
    r3 = pw.sql("SELECT name, COALESCE(NULLIF(dept, 'hr'), 'people') AS d "
                "FROM tab WHERE salary = 90", tab=t)
    assert rows_of(r3) == [("erin", "people")]


def test_cross_join():
    a = T("""
    x
    1
    2
    """)
    b = T("""
    y
    10
    20
    """)
    r = pw.sql("SELECT a.x, b.y FROM a CROSS JOIN b", a=a, b=b)
    assert sorted(rows_of(r)) == [(1, 10), (1, 20), (2, 10), (2, 20)]


def test_parse_errors():
    t = _tab()
    with pytest.raises(ValueError, match="SQL parse error"):
        pw.sql("SELECT FROM tab", tab=t)
    with pytest.raises(KeyError, match="unknown table"):
        pw.sql("SELECT x FROM missing", tab=t)
    with pytest.raises(ValueError, match="unsupported SQL function"):
        pw.sql("SELECT FOO(name) FROM tab", tab=t)


def test_duplicate_alias_is_an_error():
    """Regression: duplicate SELECT output names used to be silently
    renamed to name_<i>, changing the result schema without warning."""
    t = _tab()
    with pytest.raises(ValueError, match="duplicate output column 'name'"):
        pw.sql("SELECT name, dept AS name FROM t", t=t)
    with pytest.raises(ValueError, match="duplicate output column"):
        pw.sql("SELECT sum(salary) AS s, count(*) AS s FROM t GROUP BY dept",
               t=t)
    # same column twice without aliases collides on the inferred name too
    with pytest.raises(ValueError, match="duplicate output column 'name'"):
        pw.sql("SELECT name, name FROM t", t=t)
    # star-expansion colliding with an explicit alias — both orders
    with pytest.raises(ValueError, match="duplicate output column 'name'"):
        pw.sql("SELECT dept AS name, * FROM t", t=t)
    with pytest.raises(ValueError, match="duplicate output column 'name'"):
        pw.sql("SELECT *, dept AS name FROM t", t=t)
