"""Network connectors against in-test fake servers: gdrive (Drive REST),
pubsub (REST publish), bigquery (insertAll), airbyte (protocol subprocess),
nats (wire protocol broker), mongodb (OP_MSG + BSON).

No external services or client packages: every test spins up a local
stand-in speaking the real protocol, which is exactly what the connectors
implement (reference test strategy: fakes injected where real services
would go, SURVEY §4)."""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clear_graph():
    G.clear()
    yield
    G.clear()


def _start_http(handler_cls):
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


# ---------------------------------------------------------------------------
# gdrive
# ---------------------------------------------------------------------------


class _FakeDrive(BaseHTTPRequestHandler):
    files: dict = {}

    def log_message(self, *args):
        pass

    def _json(self, payload, code=200):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.headers.get("Authorization") != "Bearer tok123":
            return self._json({"error": "unauthorized"}, 401)
        u = urlparse(self.path)
        q = parse_qs(u.query)
        if u.path == "/files":
            match = q["q"][0].split("'")[1]
            listing = [{k: v for k, v in meta.items() if k != "_content"}
                       for meta in self.files.values()
                       if match in meta.get("parents", [])]
            return self._json({"files": listing})
        fid = u.path.split("/files/")[1].split("/")[0]
        meta = self.files.get(fid)
        if meta is None:
            return self._json({"error": "notFound"}, 404)
        if q.get("alt") == ["media"]:
            body = meta["_content"]
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        return self._json({k: v for k, v in meta.items() if k != "_content"})


def test_gdrive_static_and_filtering():
    _FakeDrive.files = {
        "root": {"id": "root", "name": "dir",
                 "mimeType": "application/vnd.google-apps.folder"},
        "f1": {"id": "f1", "name": "a.txt", "mimeType": "text/plain",
               "parents": ["root"], "modifiedTime": "t1", "size": "5",
               "_content": b"hello"},
        "f2": {"id": "f2", "name": "b.pdf", "mimeType": "application/pdf",
               "parents": ["root"], "modifiedTime": "t1", "size": "3",
               "_content": b"pdf"},
        "sub": {"id": "sub", "name": "nested",
                "mimeType": "application/vnd.google-apps.folder",
                "parents": ["root"]},
        "f3": {"id": "f3", "name": "c.txt", "mimeType": "text/plain",
               "parents": ["sub"], "modifiedTime": "t1", "size": "6",
               "_content": b"nested"},
    }
    server, url = _start_http(_FakeDrive)
    try:
        t = pw.io.gdrive.read("root", mode="static", access_token="tok123",
                              endpoint=url, with_metadata=True)
        rows = pw.debug.table_to_pandas(t).to_dict("records")
        contents = sorted(r["data"] for r in rows)
        assert contents == [b"hello", b"nested", b"pdf"]
        # glob filtering
        G.clear()
        t2 = pw.io.gdrive.read("root", mode="static", access_token="tok123",
                               endpoint=url, file_name_pattern="*.txt")
        rows2 = pw.debug.table_to_pandas(t2).to_dict("records")
        assert sorted(r["data"] for r in rows2) == [b"hello", b"nested"]
    finally:
        server.shutdown()


def test_gdrive_streaming_update_and_delete(tmp_path):
    _FakeDrive.files = {
        "root": {"id": "root", "name": "dir",
                 "mimeType": "application/vnd.google-apps.folder"},
        "f1": {"id": "f1", "name": "a.txt", "mimeType": "text/plain",
               "parents": ["root"], "modifiedTime": "t1", "size": "2",
               "_content": b"v1"},
    }
    server, url = _start_http(_FakeDrive)
    try:
        t = pw.io.gdrive.read("root", mode="streaming",
                              access_token="tok123", endpoint=url,
                              refresh_interval=0,
                              autocommit_duration_ms=20)
        seen = []
        pw.io.subscribe(t, on_change=lambda key, row, time, is_addition:
                        seen.append((row["data"], is_addition)))

        def mutate():
            time.sleep(0.4)
            _FakeDrive.files["f1"] = dict(
                _FakeDrive.files["f1"], modifiedTime="t2", _content=b"v2")
            time.sleep(0.4)
            del _FakeDrive.files["f1"]

        threading.Thread(target=mutate, daemon=True).start()
        threading.Thread(target=lambda: pw.run(), daemon=True).start()
        want = {(b"v1", True), (b"v1", False), (b"v2", True), (b"v2", False)}
        deadline = time.time() + 12
        while time.time() < deadline and not want <= set(seen):
            time.sleep(0.1)
    finally:
        server.shutdown()
    assert want <= set(seen)


# ---------------------------------------------------------------------------
# pubsub
# ---------------------------------------------------------------------------


class _FakePubSub(BaseHTTPRequestHandler):
    published: list = []

    def log_message(self, *args):
        pass

    def do_POST(self):
        n = int(self.headers["Content-Length"])
        payload = json.loads(self.rfile.read(n))
        self.published.append((self.path, payload))
        body = json.dumps({"messageIds": [
            str(i) for i in range(len(payload["messages"]))]}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_pubsub_rest_write():
    _FakePubSub.published = []
    server, url = _start_http(_FakePubSub)
    try:
        t = pw.debug.table_from_markdown("""
        data
        alpha
        beta
        """)
        pw.io.pubsub.write(t, project_id="proj", topic_id="top",
                           endpoint=url)
        pw.run()
    finally:
        server.shutdown()
    [(path, payload)] = _FakePubSub.published
    assert path == "/projects/proj/topics/top:publish"
    import base64

    datas = sorted(base64.b64decode(m["data"]).decode()
                   for m in payload["messages"])
    assert datas == ["alpha", "beta"]
    attrs = payload["messages"][0]["attributes"]
    assert attrs["pathway_diff"] == "1"


def test_pubsub_duck_typed_publisher():
    calls = []

    class _Future:
        def result(self):
            return "id"

    class _Publisher:
        def topic_path(self, project, topic):
            return f"projects/{project}/topics/{topic}"

        def publish(self, topic_path, data, **attrs):
            calls.append((topic_path, data, attrs))
            return _Future()

    t = pw.debug.table_from_markdown("""
    data
    xyz
    """)
    pw.io.pubsub.write(t, _Publisher(), "proj", "top")
    pw.run()
    [(path, data, attrs)] = calls
    assert path == "projects/proj/topics/top"
    assert data == b"xyz"
    assert attrs["pathway_diff"] == "1"


# ---------------------------------------------------------------------------
# bigquery
# ---------------------------------------------------------------------------


class _FakeBigQuery(BaseHTTPRequestHandler):
    inserted: list = []

    def log_message(self, *args):
        pass

    def do_POST(self):
        n = int(self.headers["Content-Length"])
        payload = json.loads(self.rfile.read(n))
        self.inserted.append((self.path, payload))
        body = json.dumps({"kind": "bigquery#tableDataInsertAllResponse"}
                          ).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_bigquery_write():
    _FakeBigQuery.inserted = []
    server, url = _start_http(_FakeBigQuery)
    try:
        t = pw.debug.table_from_markdown("""
        name  | qty
        bolt  | 3
        screw | 7
        """)
        pw.io.bigquery.write(t, "warehouse", "parts", project_id="proj",
                             endpoint=url)
        pw.run()
    finally:
        server.shutdown()
    [(path, payload)] = _FakeBigQuery.inserted
    assert path == "/projects/proj/datasets/warehouse/tables/parts/insertAll"
    rows = sorted((r["json"]["name"], r["json"]["qty"], r["json"]["diff"])
                  for r in payload["rows"])
    assert rows == [("bolt", 3, 1), ("screw", 7, 1)]


# ---------------------------------------------------------------------------
# airbyte
# ---------------------------------------------------------------------------

_FAKE_CONNECTOR = r'''#!/usr/bin/env python3
import json, sys

def emit(m):
    print(json.dumps(m), flush=True)

args = sys.argv[1:]
cmd = args[0]
opts = dict(zip(args[1::2], args[2::2]))
if cmd == "discover":
    emit({"type": "CATALOG", "catalog": {"streams": [
        {"name": "events", "json_schema": {},
         "supported_sync_modes": ["full_refresh", "incremental"]}]}})
elif cmd == "read":
    state = {}
    if "--state" in opts:
        with open(opts["--state"]) as f:
            raw = json.load(f)
        if isinstance(raw, list) and raw:
            state = raw[0]["stream"]["stream_state"]
    start = state.get("cursor", 0)
    for i in range(start, start + 3):
        emit({"type": "RECORD", "record": {
            "stream": "events", "emitted_at": 0,
            "data": {"n": i}}})
    emit({"type": "STATE", "state": {
        "type": "STREAM",
        "stream": {"stream_descriptor": {"name": "events"},
                   "stream_state": {"cursor": start + 3}}}})
'''


def _write_fake_connector(tmp_path):
    script = tmp_path / "connector.py"
    script.write_text(_FAKE_CONNECTOR)
    config = tmp_path / "airbyte.yaml"
    import sys

    config.write_text(json.dumps({
        "source": {
            "executable": [sys.executable, str(script)],
            "config": {"seed": 1},
        }
    }))
    return config


def test_airbyte_static_read(tmp_path):
    config = _write_fake_connector(tmp_path)
    t = pw.io.airbyte.read(config, ["events"], mode="static")
    rows = pw.debug.table_to_pandas(t).to_dict("records")
    assert sorted(r["data"].value["n"] for r in rows) == [0, 1, 2]


def test_airbyte_incremental_state(tmp_path):
    """Two extract cycles: the STATE from cycle 1 must feed cycle 2, so
    records continue from the cursor instead of repeating."""
    from pathway_tpu.io.airbyte import AirbyteProtocolSource
    import sys

    script = tmp_path / "connector.py"
    script.write_text(_FAKE_CONNECTOR)
    src = AirbyteProtocolSource([sys.executable, str(script)],
                                {"seed": 1}, ["events"])
    records1, state1 = src.extract(None)
    assert [r["data"]["n"] for r in records1] == [0, 1, 2]
    records2, state2 = src.extract(state1)
    assert [r["data"]["n"] for r in records2] == [3, 4, 5]
    assert state2[0]["stream"]["stream_state"]["cursor"] == 6


def test_airbyte_unknown_stream_rejected(tmp_path):
    config = _write_fake_connector(tmp_path)
    with pytest.raises(ValueError, match="not found"):
        pw.io.airbyte.read(config, ["nope"], mode="static")


# ---------------------------------------------------------------------------
# nats
# ---------------------------------------------------------------------------


class _FakeNatsBroker:
    """Speaks enough of the NATS protocol to route PUB -> SUB."""

    def __init__(self):
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        self.subs: list[tuple] = []  # (conn, subject, sid)
        self.lock = threading.Lock()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            conn.sendall(b'INFO {"server_name":"fake"}\r\n')
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = b""
        while True:
            try:
                chunk = conn.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while b"\r\n" in buf:
                line, rest = buf.split(b"\r\n", 1)
                parts = line.split()
                if not parts:
                    buf = rest
                    continue
                verb = parts[0].upper()
                if verb == b"PUB":
                    nbytes = int(parts[-1])
                    if len(rest) < nbytes + 2:
                        break  # wait for full payload
                    payload, rest = rest[:nbytes], rest[nbytes + 2:]
                    self._route(parts[1].decode(), payload)
                elif verb == b"SUB":
                    with self.lock:
                        self.subs.append((conn, parts[1].decode(),
                                          parts[2].decode()))
                buf = rest
                continue
            else:
                continue

    def _route(self, subject, payload):
        with self.lock:
            for conn, sub, sid in self.subs:
                if sub == subject:
                    try:
                        conn.sendall(
                            f"MSG {subject} {sid} {len(payload)}\r\n"
                            .encode() + payload + b"\r\n")
                    except OSError:
                        pass

    def close(self):
        self.server.close()


def test_nats_reader_receives_published_messages():
    broker = _FakeNatsBroker()
    uri = f"nats://127.0.0.1:{broker.port}"
    try:
        class S(pw.Schema):
            word: str

        incoming = pw.io.nats.read(uri, "updates", schema=S, format="json",
                                   autocommit_duration_ms=30)
        got = []
        pw.io.subscribe(incoming, on_change=lambda key, row, time,
                        is_addition: got.append(row["word"]))
        threading.Thread(target=lambda: pw.run(), daemon=True).start()
        # NATS is fire-and-forget: wait for the reader's SUB to register
        # before publishing, else messages are (correctly) dropped
        deadline = time.time() + 5
        while time.time() < deadline and not broker.subs:
            time.sleep(0.05)
        assert broker.subs, "reader never subscribed"
        from pathway_tpu.io.nats import _NatsConn

        conn = _NatsConn(uri)
        conn.publish("updates", b'{"word": "ping"}')
        conn.publish("updates", b'{"word": "pong"}')
        deadline = time.time() + 10
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.05)
    finally:
        broker.close()
    assert sorted(got) == ["ping", "pong"]


def test_nats_writer_publishes_change_stream():
    broker = _FakeNatsBroker()
    uri = f"nats://127.0.0.1:{broker.port}"
    try:
        # raw protocol subscriber listening on the broker
        from pathway_tpu.io.nats import _NatsConn

        sub = _NatsConn(uri)
        sub.subscribe("updates")
        deadline = time.time() + 5
        while time.time() < deadline and not broker.subs:
            time.sleep(0.05)

        src = pw.debug.table_from_markdown("""
        word
        ping
        pong
        """)
        pw.io.nats.write(src, uri, "updates", format="json")
        pw.run()
        msgs = []
        sub.sock.settimeout(5)
        for _ in range(2):
            msgs.append(json.loads(sub.next_message()))
    finally:
        broker.close()
    assert sorted(m["word"] for m in msgs) == ["ping", "pong"]
    assert all(m["diff"] == 1 for m in msgs)


# ---------------------------------------------------------------------------
# mongodb
# ---------------------------------------------------------------------------


class _FakeMongo:
    def __init__(self):
        self.server = socket.create_server(("127.0.0.1", 0))
        self.port = self.server.getsockname()[1]
        self.commands: list[dict] = []
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        from pathway_tpu.io.mongodb import _bson

        try:
            conn, _ = self.server.accept()
        except OSError:
            return
        while True:
            try:
                header = self._read_exact(conn, 16)
            except (ConnectionError, OSError):
                return
            length, rid, _resp, opcode = struct.unpack("<iiii", header)
            payload = self._read_exact(conn, length - 16)
            doc = _bson.decode(payload, 5)
            self.commands.append(doc)
            reply = _bson.encode({"ok": 1.0, "n": len(
                doc.get("documents", []))})
            body = struct.pack("<I", 0) + b"\x00" + reply
            conn.sendall(struct.pack("<iiii", 16 + len(body), 1, rid, 2013)
                         + body)

    @staticmethod
    def _read_exact(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def close(self):
        self.server.close()


def test_mongodb_write():
    fake = _FakeMongo()
    try:
        t = pw.debug.table_from_markdown("""
        item | qty
        nut  | 5
        bolt | 9
        """)
        pw.io.mongodb.write(
            t, connection_string=f"mongodb://127.0.0.1:{fake.port}",
            database="warehouse", collection="parts")
        pw.run()
        time.sleep(0.1)
    finally:
        fake.close()
    [cmd] = fake.commands
    assert cmd["insert"] == "parts" and cmd["$db"] == "warehouse"
    docs = sorted((d["item"], d["qty"], d["diff"])
                  for d in cmd["documents"])
    assert docs == [("bolt", 9, 1), ("nut", 5, 1)]


def test_bson_roundtrip():
    import datetime

    from pathway_tpu.io.mongodb import _bson

    doc = {
        "s": "text", "i": 42, "big": 1 << 40, "f": 3.5, "b": True,
        "none": None, "blob": b"\x00\x01", "arr": [1, "two", None],
        "nested": {"k": "v"},
        "ts": datetime.datetime(2026, 7, 30, 12, 0,
                                tzinfo=datetime.timezone.utc),
    }
    out = _bson.decode(_bson.encode(doc))
    assert out["s"] == "text" and out["i"] == 42 and out["big"] == 1 << 40
    assert out["f"] == 3.5 and out["b"] is True and out["none"] is None
    assert out["blob"] == b"\x00\x01"
    assert out["arr"] == [1, "two", None]
    assert out["nested"] == {"k": "v"}
    assert out["ts"].year == 2026
