"""HTTP monitoring endpoint + error-trace attribution
(reference: src/engine/http_server.rs, internals/trace.py)."""

from __future__ import annotations

import json
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.http_server import MonitoringHttpServer
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


class _FakeNode:
    def __init__(self, id, name):
        self.id = id
        self.name = name
        self.op = object()


class _FakeRuntime:
    def __init__(self):
        class Sched:
            stats = {0: {"insertions": 7, "retractions": 2}}

        class Graph:
            nodes = [_FakeNode(0, "source:test")]

        class Runner:
            graph = Graph()

        self.scheduler = Sched()
        self.runner = Runner()
        self.sessions = [1, 2]


def test_http_status_and_metrics():
    server = MonitoringHttpServer(_FakeRuntime(), port=0)  # ephemeral port
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status = json.loads(urllib.request.urlopen(base + "/status").read())
        assert status["sources"] == 2
        assert status["operators"][0]["insertions"] == 7
        metrics = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'pathway_tpu_insertions{operator="source:test",id="0"} 7' in metrics
        assert metrics.rstrip().endswith("# EOF")
    finally:
        server.stop()


def test_engine_error_carries_user_trace():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        0
        """
    )
    bad = t.flatten(t.a)  # flattening an int column: TypeError in-operator
    with pytest.raises(TypeError) as exc_info:  # original type preserved
        pw.debug.compute_and_print(bad)
    notes = "\n".join(getattr(exc_info.value, "__notes__", []))
    assert "in operator" in notes
    assert "test_monitoring_http.py" in notes
    assert "flatten" in notes
