"""HTTP monitoring endpoint + error-trace attribution
(reference: src/engine/http_server.rs, internals/trace.py), plus the
exposition-format contract of every /metrics family: label escaping,
histogram bucket monotonicity + _sum/_count consistency, and a regex lint
over every emitted line."""

from __future__ import annotations

import json
import math
import re
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.http_server import MonitoringHttpServer
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def fresh_graph():
    G.clear()
    yield
    G.clear()


class _FakeNode:
    def __init__(self, id, name):
        self.id = id
        self.name = name
        self.op = object()
        self.trace = None


class _FakeRuntime:
    def __init__(self):
        class Sched:
            stats = {0: {"insertions": 7, "retractions": 2}}
            recorder = None

        class Graph:
            nodes = [_FakeNode(0, "source:test")]

        class Runner:
            graph = Graph()

        self.scheduler = Sched()
        self.runner = Runner()
        self.sessions = [1, 2]


_AWKWARD = 'source:"we\\ird"\nname'  # quote, backslash, newline

_STEP_SAMPLES_MS = (0.05, 0.3, 2.0, 7.0, 180.0, 3000.0, 50_000.0)


def _recording_runtime():
    """A fake runtime whose scheduler carries a flight recorder with one
    awkwardly-named operator and a known latency sample set."""
    from pathway_tpu.engine.flight_recorder import FlightRecorder

    rt = _FakeRuntime()
    rec = FlightRecorder()
    rec.enabled = True
    node = _FakeNode(0, _AWKWARD)
    for i, ms in enumerate(_STEP_SAMPLES_MS):
        rec.record(i, node, "host", float(i), ms, 10, 9)
    rt.scheduler.recorder = rec
    return rt


def test_http_status_and_metrics():
    server = MonitoringHttpServer(_FakeRuntime(), port=0)  # ephemeral port
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status = json.loads(urllib.request.urlopen(base + "/status").read())
        assert status["sources"] == 2
        assert status["operators"][0]["insertions"] == 7
        metrics = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'pathway_tpu_insertions{operator="source:test",id="0"} 7' in metrics
        assert metrics.rstrip().endswith("# EOF")
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# /metrics exposition format: escaping, histogram invariants, family lint
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^(?P<family>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*",?)+)\})?'
    r' (?P<value>-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|\+Inf|NaN))$')


def _metrics_lines(rt) -> list[str]:
    server = MonitoringHttpServer(rt, port=0)
    return server.metrics_payload().splitlines()


def _parse_samples(lines):
    """[(family, {label: value}, float)] for every sample line; asserts
    every non-comment line parses (the regex lint)."""
    out = []
    for line in lines:
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        raw = m.group("labels")
        if raw:
            for lm in re.finditer(r'([a-zA-Z_][a-zA-Z0-9_]*)='
                                  r'"((?:[^"\\\n]|\\.)*)"', raw):
                labels[lm.group(1)] = lm.group(2)
        out.append((m.group("family"), labels, float(m.group("value"))))
    return out


def test_metrics_regex_lint_every_family_typed():
    """Every emitted sample parses, and every family is announced with a
    # TYPE line (histogram samples resolve to their base family)."""
    lines = _metrics_lines(_recording_runtime())
    assert lines[-1] == "# EOF"
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    assert typed, "no TYPE declarations emitted"
    for family, _labels, _v in _parse_samples(lines):
        base = re.sub(r"_(bucket|sum|count)$", "", family)
        assert family in typed or base in typed, \
            f"sample family {family!r} has no # TYPE declaration"


def test_metrics_label_escaping_round_trips():
    """Quote / backslash / newline in an operator name must be escaped per
    the exposition format and decode back to the original name."""
    lines = _metrics_lines(_recording_runtime())
    ops = set()
    for family, labels, _v in _parse_samples(lines):
        if family.startswith("pathway_tpu_operator_step_duration_ms"):
            raw = labels["operator"]
            assert "\n" not in raw
            ops.add(raw.replace(r"\\", "\x00").replace(r"\"", '"')
                    .replace(r"\n", "\n").replace("\x00", "\\"))
    assert _AWKWARD in ops


def test_histogram_monotonic_and_sum_count_consistent():
    lines = _metrics_lines(_recording_runtime())
    buckets = []   # (le, cumulative_count) in emission order
    sum_ms = count = None
    for family, labels, v in _parse_samples(lines):
        if family == "pathway_tpu_operator_step_duration_ms_bucket":
            le = math.inf if labels["le"] == "+Inf" else float(labels["le"])
            buckets.append((le, v))
        elif family == "pathway_tpu_operator_step_duration_ms_sum":
            sum_ms = v
        elif family == "pathway_tpu_operator_step_duration_ms_count":
            count = v
    assert buckets and sum_ms is not None and count is not None
    # le values strictly increasing, ending at +Inf
    les = [b[0] for b in buckets]
    assert les == sorted(les) and len(set(les)) == len(les)
    assert les[-1] == math.inf
    # cumulative counts monotonically non-decreasing; +Inf == _count
    counts = [b[1] for b in buckets]
    assert counts == sorted(counts)
    assert counts[-1] == count == len(_STEP_SAMPLES_MS)
    assert sum_ms == pytest.approx(sum(_STEP_SAMPLES_MS), rel=1e-6)
    # spot-check one interior bucket: samples <= 2.5ms
    by_le = dict(buckets)
    assert by_le[2.5] == sum(1 for ms in _STEP_SAMPLES_MS if ms <= 2.5)


def test_metrics_row_counters_and_gauges_still_linted():
    """The pre-existing families (operator gauges, process memory) pass
    the same lint and the recorder's row counters total correctly."""
    samples = _parse_samples(_metrics_lines(_recording_runtime()))
    rows_in = [v for f, _l, v in samples
               if f == "pathway_tpu_operator_rows_in"]
    rows_out = [v for f, _l, v in samples
                if f == "pathway_tpu_operator_rows_out"]
    assert rows_in == [10 * len(_STEP_SAMPLES_MS)]
    assert rows_out == [9 * len(_STEP_SAMPLES_MS)]


def test_exchange_plane_metrics_exposed_per_row():
    """A runtime with a cluster exports pathway_tpu_exchange_* with
    per-transport (tcp/shm) labels, including the per-row encode/decode
    gauges (the r5 encdec-regression surface), all passing the same
    exposition lint."""
    from pathway_tpu.engine.multiproc import Cluster

    rt = _FakeRuntime()
    cl = Cluster(2, 0, 41000)
    cl.stats.update({"rounds": 2, "shm_bytes_out": 90000,
                     "shm_bytes_in": 38000})
    cl.stats_by_transport["tcp"].update(
        {"encode_s": 0.010, "decode_s": 0.004,
         "rows_out": 2000, "rows_in": 1000,
         "bytes_out": 64000, "bytes_in": 32000, "messages": 4})
    cl.stats_by_transport["shm"].update(
        {"encode_s": 0.001, "decode_s": 0.002,
         "rows_out": 500, "rows_in": 1000,
         "bytes_out": 52, "bytes_in": 52, "messages": 4})
    rt.cluster = cl
    samples = _parse_samples(_metrics_lines(rt))
    by_series = {(f, labels.get("transport")): v
                 for f, labels, v in samples}
    assert by_series["pathway_tpu_exchange_encode_us_per_row", "tcp"] == \
        pytest.approx(5.0)
    assert by_series["pathway_tpu_exchange_decode_us_per_row", "tcp"] == \
        pytest.approx(4.0)
    assert by_series["pathway_tpu_exchange_decode_us_per_row", "shm"] == \
        pytest.approx(2.0)
    assert by_series["pathway_tpu_exchange_rows_out", "tcp"] == 2000
    assert by_series["pathway_tpu_exchange_rows_out", "shm"] == 500
    assert by_series["pathway_tpu_exchange_bytes_in", "tcp"] == 32000
    assert by_series["pathway_tpu_exchange_shm_bytes", None] == 128000
    assert by_series["pathway_tpu_exchange_rounds", None] == 2


def test_exchange_payload_row_counting():
    """payload_rows (and the codec's own row accounting) count genuine
    entry lists only: wm/bcast side-channels, scalars, liveness flags and
    plain lists are excluded — encode_us_per_row divides by rows moved,
    nothing else (the old _payload_rows counted any list it saw)."""
    from pathway_tpu.engine import wire
    from pathway_tpu.internals.keys import hash_values

    ents = [(hash_values("r", i), (f"w{i}", i), 1) for i in range(7)]
    payload = {"rows": {0: {3: ents}}, "wm": None, "bcast": {1: ents[:2]},
               "any": True, "closed": False}
    assert wire.payload_rows(payload) == 7
    chunks, _total, n_enc = wire.encode_frame(("x", 1, 0), payload)
    _tag, decoded, n_dec = wire.decode_frame(b"".join(chunks))
    assert n_enc == n_dec == 7
    assert decoded == payload
    assert wire.payload_rows({"any": True, "wm": 3}) == 0
    # a plain (non-entry) list is payload structure, not rows
    assert wire.payload_rows({"xs": [1, 2, 3]}) == 0
    # watermark side-channels never count, even when list-shaped
    assert wire.payload_rows({"wm": ents, "bcast": {0: ents}}) == 0


def test_paged_store_metrics_exposed():
    """A live paged pool surfaces the page-occupancy families (and they
    pass the exposition lint) plus the /status paged_store section."""
    import numpy as np

    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex

    idx = BruteForceKnnIndex(8, paged=True, tenant="acme")
    idx.add_batch([Pointer(i) for i in range(10)],
                  np.zeros((10, 8), np.float32))
    lines = _metrics_lines(_FakeRuntime())
    samples = {f: (labels, v) for f, labels, v in _parse_samples(lines)}
    assert samples["pathway_tpu_paged_pages_total"][1] >= 1
    assert "pathway_tpu_paged_occupancy_ratio" in samples
    assert samples["pathway_tpu_paged_grow_events"][1] >= 0
    tenant_rows = [(labels, v) for f, labels, v in _parse_samples(lines)
                   if f == "pathway_tpu_paged_tenant_pages"]
    assert any(labels.get("tenant") == "acme" for labels, _ in tenant_rows)
    server = MonitoringHttpServer(_FakeRuntime(), port=0)
    st = server.status_payload()
    assert st["paged_store"]["pages_total"] >= 1
    del idx  # release the pool so later exposition tests see a clean set


def test_trace_endpoint_serves_span_buffer():
    rt = _recording_runtime()
    server = MonitoringHttpServer(rt, port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        payload = json.loads(urllib.request.urlopen(base + "/trace").read())
        assert payload["enabled"] is True
        assert len(payload["events"]) == len(_STEP_SAMPLES_MS)
        ev = payload["events"][-1]
        assert ev["operator"] == _AWKWARD
        assert ev["leg"] == "host"
        assert ev["rows_in"] == 10 and ev["rows_out"] == 9
        # /status names the operator that dominated the last tick
        status = json.loads(
            urllib.request.urlopen(base + "/status").read())
        assert status["last_tick_dominator"]["operator"] == _AWKWARD
    finally:
        server.stop()


def test_trace_endpoint_without_recorder_reports_disabled():
    server = MonitoringHttpServer(_FakeRuntime(), port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        payload = json.loads(urllib.request.urlopen(base + "/trace").read())
        assert payload == {"enabled": False, "events": [],
                           "device_legs": [], "inflight": None}
    finally:
        server.stop()


def test_log_buffer_lines_env(monkeypatch):
    from pathway_tpu.internals.monitoring import _LogBuffer

    monkeypatch.setenv("PATHWAY_LOG_BUFFER_LINES", "3")
    assert _LogBuffer().records.maxlen == 3
    monkeypatch.setenv("PATHWAY_LOG_BUFFER_LINES", "bogus")
    assert _LogBuffer().records.maxlen == 8  # fallback, never a crash
    monkeypatch.delenv("PATHWAY_LOG_BUFFER_LINES")
    assert _LogBuffer().records.maxlen == 8


def test_engine_error_carries_user_trace():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        0
        """
    )
    bad = t.flatten(t.a)  # flattening an int column: TypeError in-operator
    with pytest.raises(TypeError) as exc_info:  # original type preserved
        pw.debug.compute_and_print(bad)
    notes = "\n".join(getattr(exc_info.value, "__notes__", []))
    assert "in operator" in notes
    assert "test_monitoring_http.py" in notes
    assert "flatten" in notes


def test_persistence_watermark_metrics_exposed():
    """Commit-watermark durability families (PR 8): lag gauge, inflight
    at commit, commit counters, write retries, and the commit-wait
    histogram — all lint-clean with monotone cumulative buckets."""
    import pathway_tpu as pw
    from pathway_tpu.engine.http_server import MonitoringHttpServer
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.io._datasource import CallbackSource, Session

    rt = _FakeRuntime()
    backend = pw.persistence.Backend.mock()
    driver = PersistenceDriver(pw.persistence.Config.simple_config(backend))
    src = CallbackSource(lambda: iter(()), pw.schema_from_types(x=int))
    src.persistent_id = "m"
    rec = driver.attach_source(src, Session())
    rec.push("k", (1,), 1)
    driver.seal(4)
    driver.commit(6, watermark=4, inflight=3)
    rt.persistence = driver

    lines = _metrics_lines(rt)
    samples = {f: v for f, _l, v in _parse_samples(lines)}
    assert samples["pathway_tpu_commit_watermark"] == 4
    assert samples["pathway_tpu_commit_watermark_lag_ticks"] == 2
    assert samples["pathway_tpu_device_inflight_at_commit"] == 3
    assert samples["pathway_tpu_persistence_commits"] == 1
    assert samples["pathway_tpu_persistence_entries_committed"] == 1
    assert "pathway_tpu_persistence_write_retries" in samples
    assert samples["pathway_tpu_commit_wait_ms_count"] == 1
    # histogram: cumulative bucket counts are monotone and end at count
    buckets = [(l, v) for f, l, v in _parse_samples(lines)
               if f == "pathway_tpu_commit_wait_ms_bucket"]
    values = [v for _l, v in buckets]
    assert values == sorted(values)
    assert buckets[-1][0]["le"] == "+Inf"
    assert values[-1] == samples["pathway_tpu_commit_wait_ms_count"]
    # every family is TYPE-declared (same lint as the rest of the suite)
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    for fam in ("pathway_tpu_commit_watermark_lag_ticks",
                "pathway_tpu_commit_wait_ms",
                "pathway_tpu_device_inflight_at_commit",
                "pathway_tpu_persistence_write_retries"):
        assert fam in typed
    # /status carries the same snapshot
    status = MonitoringHttpServer(rt, port=0).status_payload()
    assert status["persistence"]["watermark"] == 4
    assert status["persistence"]["lag_ticks"] == 2


def test_snapshot_tier_metrics_exposed():
    """Snapshot/compaction families (PR 10): age, bytes, generation,
    totals, compactions and the replayable-entry gauge — plus the
    /status.persistence naming of last snapshot tick + generation."""
    import pathway_tpu as pw
    from pathway_tpu.engine.http_server import MonitoringHttpServer
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.io._datasource import CallbackSource, Session

    rt = _FakeRuntime()
    backend = pw.persistence.Backend.mock()
    driver = PersistenceDriver(pw.persistence.Config.simple_config(backend))
    src = CallbackSource(lambda: iter(()), pw.schema_from_types(x=int))
    src.persistent_id = "m"
    rec = driver.attach_source(src, Session())
    rec.push("k", (1,), 1)
    driver.seal(2)
    driver.commit(2, watermark=2)
    assert driver.write_snapshot(2, {"nodes": {}}) is True
    rec.push("k2", (2,), 1)
    driver.seal(5)
    driver.commit(5, watermark=5)
    rt.persistence = driver

    lines = _metrics_lines(rt)
    samples = {f: v for f, _l, v in _parse_samples(lines)}
    assert samples["pathway_tpu_snapshot_age_ticks"] == 3  # tick 5 vs 2
    assert samples["pathway_tpu_snapshot_generation"] == 1
    assert samples["pathway_tpu_snapshots_total"] == 1
    assert samples["pathway_tpu_snapshot_bytes"] > 0
    assert samples["pathway_tpu_compactions_total"] == 1
    # compaction dropped the covered entry; one suffix entry remains
    assert samples["pathway_tpu_wal_replayable_entries"] == 1
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    for fam in ("pathway_tpu_snapshot_age_ticks",
                "pathway_tpu_snapshot_bytes",
                "pathway_tpu_wal_replayable_entries",
                "pathway_tpu_compactions_total"):
        assert fam in typed
    status = MonitoringHttpServer(rt, port=0).status_payload()
    assert status["persistence"]["snapshot_tick"] == 2
    assert status["persistence"]["snapshot_generation"] == 1
    assert status["persistence"]["wal_replayable_entries"] == 1


# ---------------------------------------------------------------------------
# replica fleet exposition (PR 12): role fields + staleness families on the
# replica's own endpoint, and the router's /metrics — all through the same
# regex lint + TYPE-declaration contract as every other family
# ---------------------------------------------------------------------------

class _FakeTailer:
    """Duck-types engine/replica.ReplicaTailer's monitoring surface, with
    an awkward replica id to exercise label escaping."""

    replica_id = 'rep"lica\\one'
    applied_tick = 41
    primary_watermark = 44
    generation = 3

    def staleness_ticks(self):
        return 3

    def stats(self):
        return {
            "replica_id": self.replica_id,
            "applied_tick": self.applied_tick,
            "primary_watermark": self.primary_watermark,
            "staleness_ticks": self.staleness_ticks(),
            "generation": self.generation,
            "hydrate_wall_s": 0.125,
            "catchup_wall_s": 0.5,
            "records_applied": 7,
            "entries_applied": 70,
            "tailed_sources": ["vecs"],
        }


def test_replica_families_exposition_and_status_role():
    rt = _FakeRuntime()
    rt.role = "replica"
    rt.replica = _FakeTailer()
    lines = _metrics_lines(rt)
    by_family = {}
    for f, labels, v in _parse_samples(lines):
        by_family.setdefault(f, []).append((labels, v))
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    for fam, want in (("pathway_tpu_replica_staleness_ticks", 3),
                      ("pathway_tpu_replica_applied_tick", 41),
                      ("pathway_tpu_replica_primary_watermark", 44),
                      ("pathway_tpu_replica_generation", 3),
                      ("pathway_tpu_replica_entries_applied", 70)):
        assert fam in typed, fam
        (labels, v), = by_family[fam]
        # the escaped label round-trips back to the raw replica id
        raw = labels["replica"].replace(r"\\", "\\").replace(r"\"", '"')
        assert raw == _FakeTailer.replica_id
        assert v == want, (fam, v)
    server = MonitoringHttpServer(rt, port=0)
    status = server.status_payload()
    assert status["role"] == "replica"
    assert status["applied_tick"] == 41
    assert status["staleness_ticks"] == 3
    assert status["replica"]["generation"] == 3
    healthy, hz = server.healthz_payload()
    assert hz["role"] == "replica"
    assert hz["applied_tick"] == 41 and hz["staleness_ticks"] == 3


def test_primary_role_default_on_status_and_healthz():
    server = MonitoringHttpServer(_FakeRuntime(), port=0)
    assert server.status_payload()["role"] == "primary"
    _healthy, hz = server.healthz_payload()
    assert hz["role"] == "primary" and hz["staleness_ticks"] == 0


def test_failover_families_exposition_and_status():
    """Failover families (PR 18): the fencing epoch gauge + fenced-write
    counter ride the persistence block; the promotion counter and
    failover wall-clock appear once this process has promoted — all
    through the exposition lint, and mirrored on /status."""
    import pathway_tpu as pw
    from pathway_tpu.engine.http_server import MonitoringHttpServer
    from pathway_tpu.engine.persistence import (FencedPrimaryError,
                                                PersistenceDriver)

    backend = pw.persistence.Backend.mock()
    cfg = pw.persistence.Config.simple_config(backend)
    promoted = PersistenceDriver(cfg)
    zombie = PersistenceDriver(cfg)
    assert promoted.claim_epoch("rescuer", min_epoch=3) == 3
    with pytest.raises(FencedPrimaryError):
        zombie.commit(1)

    # the promoted runtime: epoch gauge + promotion counter + wall-clock
    rt = _FakeRuntime()
    rt.persistence = promoted
    rt.role = "primary"
    rt.promotions = 1
    rt.promotion_tick = 9
    rt.failover_promotion_s = 1.25
    lines = _metrics_lines(rt)
    samples = {f: v for f, _l, v in _parse_samples(lines)}
    assert samples["pathway_tpu_fleet_epoch"] == 3
    assert samples["pathway_tpu_fenced_writes_total"] == 0
    assert samples["pathway_tpu_promotions_total"] == 1
    assert samples["pathway_tpu_failover_seconds"] == 1.25
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    for fam in ("pathway_tpu_fleet_epoch", "pathway_tpu_fenced_writes_total",
                "pathway_tpu_promotions_total",
                "pathway_tpu_failover_seconds"):
        assert fam in typed, fam
    status = MonitoringHttpServer(rt, port=0).status_payload()
    assert status["promotions"] == 1
    assert status["promotion_tick"] == 9
    assert status["failover_promotion_s"] == 1.25

    # the fenced zombie: its counter is the split-brain smoking gun
    zrt = _FakeRuntime()
    zrt.persistence = zombie
    zsamples = {f: v for f, _l, v
                in _parse_samples(_metrics_lines(zrt))}
    assert zsamples["pathway_tpu_fenced_writes_total"] == 1
    assert "pathway_tpu_promotions_total" not in zsamples  # never promoted


def test_router_metrics_through_exposition_lint():
    """The router's /metrics body obeys the same exposition contract:
    every sample parses, every family is TYPE-declared, per-replica
    labels escape correctly."""
    import socket as _socket

    from pathway_tpu.engine.router import QueryRouter, ReplicaEndpoint

    router = QueryRouter(slo_ms=10.0)
    a, _b = _socket.socketpair()
    ep = ReplicaEndpoint('we"ird\\replica', "replica", "127.0.0.1", 1, a)
    ep.staleness_ticks = 5
    ep.applied_tick = 12
    for ms in (1.0, 2.0, 3.0, 40.0, 5.0, 6.0):
        ep.observe(ms)
    ep.requests = 6
    router._endpoints[ep.replica_id] = ep
    for ms in (5.0, 50.0):
        router._window.append(ms)
    lines = router.metrics_payload().splitlines()
    assert lines[-1] == "# EOF"
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    seen = {}
    for f, labels, v in _parse_samples(lines):
        assert f in typed, f"router family {f!r} has no # TYPE line"
        seen.setdefault(f, []).append((labels, v))
    for fam in ("pathway_tpu_router_replicas",
                "pathway_tpu_router_requests_total",
                "pathway_tpu_router_failovers",
                "pathway_tpu_router_requests",
                "pathway_tpu_router_replica_p50_ms",
                "pathway_tpu_router_replica_p95_ms",
                "pathway_tpu_replica_staleness_ticks",
                "pathway_tpu_slo_burn_rate"):
        assert fam in seen, fam
    (labels, v), = seen["pathway_tpu_replica_staleness_ticks"]
    raw = labels["replica"].replace(r"\\", "\\").replace(r"\"", '"')
    assert raw == ep.replica_id and v == 5
    # p50 <= p95 (the exposed pair is ordered like the tracker's)
    p50 = seen["pathway_tpu_router_replica_p50_ms"][0][1]
    p95 = seen["pathway_tpu_router_replica_p95_ms"][0][1]
    assert p50 <= p95


# ---------------------------------------------------------------------------
# fleet metrics aggregation (engine/fleet_observability.py, PR 14): the
# /fleet/metrics merge must keep the SAME exposition contract the
# per-process endpoints are gated on — one TYPE line per family however
# many processes ship it, every sample re-labeled {process=,role=} with
# exposition-format escaping, and histogram aggregates that stay monotone
# ---------------------------------------------------------------------------

_ADVERSARIAL_PROCESS = 'pro"cess\\one\nx'
_ADVERSARIAL_REPLICA = 'rep"lica\\two'


def _fleet_doc(process: str, counters: dict[str, float],
               hist: tuple[tuple[float, int], ...] | None = None,
               role: str = "replica") -> tuple[dict, str]:
    lines = []
    for fam, v in counters.items():
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {v}")
    lines.append("# TYPE pathway_tpu_q_ms summary")
    lines.append('pathway_tpu_q_ms{quantile="0.5"} 4.0')
    lines.append("# TYPE pathway_tpu_up gauge")
    lines.append(
        f'pathway_tpu_up{{replica="{_ADVERSARIAL_REPLICA.replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"}} 1')
    if hist is not None:
        lines.append("# TYPE pathway_tpu_wait_ms histogram")
        total = 0
        for le, c in hist:
            total = c
            le_s = "+Inf" if le == float("inf") else format(le, "g")
            lines.append(
                f'pathway_tpu_wait_ms_bucket{{le="{le_s}"}} {c}')
        lines.append(f"pathway_tpu_wait_ms_sum {float(total)}")
        lines.append(f"pathway_tpu_wait_ms_count {total}")
    lines.append("# EOF")
    return ({"process": process, "role": role}, "\n".join(lines) + "\n")


def test_fleet_metrics_label_escaping_round_trips():
    """Adversarial process AND replica ids survive the merge: the
    injected process label escapes per the exposition format and decodes
    back to the raw id, and pre-existing labels are untouched."""
    from pathway_tpu.engine.fleet_observability import merge_metrics

    merged = merge_metrics([
        _fleet_doc(_ADVERSARIAL_PROCESS, {"pathway_tpu_reqs": 3}),
        _fleet_doc("plain", {"pathway_tpu_reqs": 4}),
    ])
    samples = _parse_samples(merged.splitlines())
    procs = set()
    for f, labels, _v in samples:
        if f == "pathway_tpu_reqs" and "process" in labels:
            procs.add(labels["process"].replace(r"\\", "\x00")
                      .replace(r"\"", '"').replace(r"\n", "\n")
                      .replace("\x00", "\\"))
    assert _ADVERSARIAL_PROCESS in procs and "plain" in procs
    replicas = {labels["replica"].replace(r"\\", "\x00")
                .replace(r"\"", '"').replace("\x00", "\\")
                for f, labels, _v in samples
                if f == "pathway_tpu_up" and "replica" in labels}
    assert replicas == {_ADVERSARIAL_REPLICA}


def test_fleet_metrics_type_declared_once_per_family():
    """N processes shipping the same family must yield exactly ONE
    # TYPE declaration (Prometheus rejects redeclaration), with every
    per-process sample under it and every line lint-clean."""
    from pathway_tpu.engine.fleet_observability import merge_metrics

    docs = [_fleet_doc(f"p{i}", {"pathway_tpu_reqs": i})
            for i in range(4)]
    merged = merge_metrics(docs)
    lines = merged.splitlines()
    assert lines[-1] == "# EOF"
    type_lines = [l for l in lines if l.startswith("# TYPE")]
    families = [l.split()[2] for l in type_lines]
    assert len(families) == len(set(families)), families
    assert families.count("pathway_tpu_reqs") == 1
    samples = _parse_samples(lines)  # regex lint over every line
    reqs = [(labels.get("process"), v) for f, labels, v in samples
            if f == "pathway_tpu_reqs"]
    # 4 per-process samples + the _fleet sum
    assert len(reqs) == 5
    assert ("_fleet", 0 + 1 + 2 + 3) in reqs
    # every sample family is TYPE-declared (PR-5 contract)
    typed = set(families)
    for f, _labels, _v in samples:
        base = re.sub(r"_(bucket|sum|count)$", "", f)
        assert f in typed or base in typed, f


def test_fleet_metrics_histogram_merge_monotone():
    """Histogram families merge by summing cumulative buckets — the
    merged _fleet series must stay monotone with +Inf == _count, and the
    per-process pass-throughs keep their own invariants."""
    import math

    from pathway_tpu.engine.fleet_observability import merge_metrics

    h1 = ((1.0, 2), (5.0, 4), (float("inf"), 7))
    h2 = ((1.0, 1), (5.0, 5), (float("inf"), 6))
    merged = merge_metrics([
        _fleet_doc("p1", {}, hist=h1),
        _fleet_doc("p2", {}, hist=h2),
    ])
    samples = _parse_samples(merged.splitlines())
    fleet_buckets = []
    fleet_count = None
    for f, labels, v in samples:
        if labels.get("process") != "_fleet":
            continue
        if f == "pathway_tpu_wait_ms_bucket":
            le = math.inf if labels["le"] == "+Inf" else float(labels["le"])
            fleet_buckets.append((le, v))
        elif f == "pathway_tpu_wait_ms_count":
            fleet_count = v
    assert fleet_buckets, "no merged _fleet histogram emitted"
    fleet_buckets.sort(key=lambda b: b[0])
    counts = [c for _le, c in fleet_buckets]
    assert counts == sorted(counts), "merged buckets lost monotonicity"
    assert fleet_buckets[-1][0] == math.inf
    assert fleet_buckets[-1][1] == fleet_count == 7 + 6
    assert counts == [2 + 1, 4 + 5, 7 + 6]
    # summaries (quantiles) are pass-through only: no fake fleet p50
    assert not any(f == "pathway_tpu_q_ms"
                   and labels.get("process") == "_fleet"
                   for f, labels, _v in samples)
    # gauges pass through per-process only as well
    assert not any(f == "pathway_tpu_up"
                   and labels.get("process") == "_fleet"
                   for f, labels, _v in samples)


def test_fleet_metrics_family_named_like_histogram_suffix():
    """A counter literally NAMED *_count (or *_sum/_bucket) must keep
    its own TYPE line and _fleet aggregate — the histogram sub-sample
    resolution only applies to UNDECLARED suffixed samples."""
    from pathway_tpu.engine.fleet_observability import merge_metrics

    doc = ("# TYPE pathway_tpu_foo_count counter\n"
           "pathway_tpu_foo_count 5\n# EOF\n")
    merged = merge_metrics([({"process": "p1", "role": "replica"}, doc),
                            ({"process": "p2", "role": "replica"}, doc)])
    lines = merged.splitlines()
    assert lines.count("# TYPE pathway_tpu_foo_count counter") == 1
    samples = _parse_samples(lines)
    vals = {labels.get("process"): v for f, labels, v in samples
            if f == "pathway_tpu_foo_count"}
    assert vals == {"p1": 5, "p2": 5, "_fleet": 10}


def test_trace_endpoint_chrome_format_carries_fleet_meta():
    """/trace?format=chrome serves the mergeable payload: traceEvents +
    pathway_meta (pid, role, process, clock anchor)."""
    rt = _recording_runtime()
    server = MonitoringHttpServer(rt, port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        payload = json.loads(urllib.request.urlopen(
            base + "/trace?format=chrome").read())
        assert isinstance(payload["traceEvents"], list)
        meta = payload["pathway_meta"]
        assert meta["pid"] > 0 and meta["role"] and meta["process"]
        assert meta["epoch_wall_us"] > 0
        # the plain /trace contract is unchanged
        plain = json.loads(urllib.request.urlopen(
            base + "/trace").read())
        assert plain["enabled"] is True and "events" in plain
    finally:
        server.stop()


def test_router_fleet_metrics_endpoint_merges_live_scrape():
    """The router's /fleet/metrics scrapes a REAL monitoring endpoint
    (announced via heartbeat monitoring_port) and serves the merged
    document with the router's own families alongside."""
    import socket as _socket

    from pathway_tpu.engine.router import QueryRouter, ReplicaEndpoint

    server = MonitoringHttpServer(_recording_runtime(), port=0)
    server.start()
    router = QueryRouter(port=0, control_port=0)
    router.start()
    try:
        a, _b = _socket.socketpair()
        ep = ReplicaEndpoint("r1", "replica", "127.0.0.1", 1, a)
        ep.monitoring_port = server.port
        router._endpoints["r1"] = ep
        merged = urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/fleet/metrics",
            timeout=10).read().decode()
        lines = merged.splitlines()
        assert lines[-1] == "# EOF"
        samples = _parse_samples(lines)
        procs = {labels.get("process") for _f, labels, _v in samples}
        assert {"router", "r1"} <= procs
        # a per-process family from the scraped endpoint rode through,
        # re-labeled
        assert any(f == "pathway_tpu_insertions"
                   and labels.get("process") == "r1"
                   and labels.get("role") == "replica"
                   for f, labels, _v in samples)
        # one TYPE line per family in the merged doc
        fams = [l.split()[2] for l in lines if l.startswith("# TYPE")]
        assert len(fams) == len(set(fams))
    finally:
        router.stop()
        server.stop()


# ---------------------------------------------------------------------------
# auto-jit tier exposition (internals/autojit.py): counter families under
# the same regex lint + TYPE-declaration contract, /status tier state
# ---------------------------------------------------------------------------

def test_autojit_families_exposed_and_status_tier_state(monkeypatch):
    from pathway_tpu.internals import autojit

    monkeypatch.setenv("PATHWAY_AUTO_JIT", "1")
    autojit.reset_stats()
    autojit._bump("programs")
    autojit._bump("compiles", 3)
    autojit._bump("demotions")
    autojit._bump("device_dispatches", 7)
    try:
        lines = _metrics_lines(_FakeRuntime())
        typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
        seen = {f: v for f, _labels, v in _parse_samples(lines)}
        for fam, want in (("pathway_tpu_autojit_enabled", 1),
                          ("pathway_tpu_autojit_programs", 1),
                          ("pathway_tpu_autojit_compiles", 3),
                          ("pathway_tpu_autojit_demotions", 1),
                          ("pathway_tpu_autojit_device_dispatches", 7),
                          ("pathway_tpu_autojit_vector_dispatches", 0),
                          ("pathway_tpu_autojit_fallback_batches", 0)):
            assert fam in typed, fam
            assert seen[fam] == want, (fam, seen[fam])
        # /status names the tier state (enabled flag + backend mix)
        status = MonitoringHttpServer(_FakeRuntime(), port=0).status_payload()
        assert status["autojit"]["enabled"] is True
        assert status["autojit"]["programs"] == 1
        assert "live_programs" in status["autojit"]
        # the escape hatch is visible on both surfaces
        monkeypatch.setenv("PATHWAY_AUTO_JIT", "0")
        lines = _metrics_lines(_FakeRuntime())
        seen = {f: v for f, _labels, v in _parse_samples(lines)}
        assert seen["pathway_tpu_autojit_enabled"] == 0
        status = MonitoringHttpServer(_FakeRuntime(), port=0).status_payload()
        assert status["autojit"]["enabled"] is False
    finally:
        autojit.reset_stats()


# ---------------------------------------------------------------------------
# unified 503 contract (engine/qos.py): every 503 — webserver shed,
# router unroutable / fleet-dead, proxied shed — echoes
# X-Pathway-Request-Id AND carries Retry-After
# ---------------------------------------------------------------------------

def _drain_http_error(ei):
    err = ei.value
    err.read()
    return err


def test_router_unroutable_503_carries_id_and_retry_after():
    import urllib.error

    from pathway_tpu.engine.router import QueryRouter

    router = QueryRouter()
    router.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/q", data=b"{}",
            method="POST",
            headers={"X-Pathway-Request-Id": "client-rid-42"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        err = _drain_http_error(ei)
        assert err.code == 503
        # the id the client holds rides the 503 back (fleet grep-ability)
        assert err.headers["X-Pathway-Request-Id"] == "client-rid-42"
        assert int(err.headers["Retry-After"]) >= 1
        assert router.unroutable_total == 1
    finally:
        router.stop()


def test_router_propagates_upstream_retry_after_on_shed_503():
    """A backend's QoS gate shed the query: the router's proxy must keep
    the upstream Retry-After (previously only body+content-type crossed
    the proxy) and still echo the request id."""
    import socket
    import threading
    import urllib.error
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from pathway_tpu.engine.router import QueryRouter, ReplicaEndpoint

    class _SheddingBackend(BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length") or 0))
            body = b"query shed: admission queue full"
            self.send_response(503)
            self.send_header("Retry-After", "7")
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    backend = ThreadingHTTPServer(("127.0.0.1", 0), _SheddingBackend)
    bthread = threading.Thread(target=backend.serve_forever, daemon=True)
    bthread.start()
    router = QueryRouter()
    router.start()
    try:
        a, b = socket.socketpair()
        ep = ReplicaEndpoint("shedder", "replica", "127.0.0.1",
                             backend.server_address[1], a)
        router._endpoints["shedder"] = ep
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/q", data=b"{}",
            method="POST",
            headers={"X-Pathway-Request-Id": "rid-shed-1"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        err = _drain_http_error(ei)
        assert err.code == 503
        assert err.headers["Retry-After"] == "7"       # propagated
        assert err.headers["X-Pathway-Request-Id"] == "rid-shed-1"
        b.close()
    finally:
        router.stop()
        backend.shutdown()
        backend.server_close()


def test_webserver_shed_503_carries_id_and_retry_after():
    """The webserver's own shed path (QueryShedError out of a handler)
    emits the same 503 pair — id echo + Retry-After."""
    import urllib.error

    from pathway_tpu.engine.qos import QueryShedError
    from pathway_tpu.io.http import PathwayWebserver

    ws = PathwayWebserver(host="127.0.0.1", port=0)

    async def shedding_handler(payload):
        raise QueryShedError("admission queue full (test)", 3)

    ws.register("/shed", ("POST",), shedding_handler, None)
    ws.start()
    req = urllib.request.Request(
        f"http://127.0.0.1:{ws.port}/shed", data=b"{}", method="POST",
        headers={"X-Pathway-Request-Id": "rid-web-9"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    err = _drain_http_error(ei)
    assert err.code == 503
    assert err.headers["X-Pathway-Request-Id"] == "rid-web-9"
    assert err.headers["Retry-After"] == "3"


# ---------------------------------------------------------------------------
# semantic result cache exposition (engine/result_cache.py)
# ---------------------------------------------------------------------------

def test_result_cache_metrics_exposed():
    """A live result cache surfaces the pathway_tpu_cache_* families
    (passing the exposition lint ridden by _parse_samples) plus the
    /status result_cache section."""
    import numpy as np

    from pathway_tpu.ops.knn import BruteForceKnnIndex

    idx = BruteForceKnnIndex(4, reserved_space=16)
    assert idx.result_cache is not None
    idx.add_batch([i for i in range(4)], np.eye(4, dtype=np.float32))
    q = np.ones(4, np.float32)
    idx.search([(0, q, 2, None)])
    fp = b"\x00" * 16
    idx.result_cache.fill(fp, ((1, 0.5),), frozenset({0}), 0.5, q)
    assert idx.result_cache.lookup(fp) is not None      # one hit
    idx.result_cache.lookup(b"\x01" * 16)               # one miss
    lines = _metrics_lines(_FakeRuntime())
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    samples = {f: (labels, v) for f, labels, v in _parse_samples(lines)}
    for fam in ("pathway_tpu_cache_hits", "pathway_tpu_cache_misses",
                "pathway_tpu_cache_invalidations",
                "pathway_tpu_cache_entries", "pathway_tpu_cache_hit_ratio",
                "pathway_tpu_cache_evictions",
                "pathway_tpu_cache_index_version",
                "pathway_tpu_cache_invalidations_per_tick"):
        assert fam in samples, fam
        assert fam in typed, f"{fam} has no # TYPE line"
    assert samples["pathway_tpu_cache_hits"][1] >= 1
    assert samples["pathway_tpu_cache_misses"][1] >= 1
    assert 0.0 <= samples["pathway_tpu_cache_hit_ratio"][1] <= 1.0
    server = MonitoringHttpServer(_FakeRuntime(), port=0)
    st = server.status_payload()
    assert st["result_cache"]["entries"] >= 1
    assert st["result_cache"]["hits"] >= 1
    del idx  # release the live cache so later exposition tests are clean


def test_router_cache_metrics_and_status():
    """The router's fleet-cache families ride its /metrics body under
    the same exposition contract, and /status carries result_cache with
    the configured routes + watermark liveness."""
    import socket as _socket

    from pathway_tpu.engine.router import QueryRouter, ReplicaEndpoint
    from pathway_tpu.engine.result_cache import RouterResultCache

    router = QueryRouter(cache_routes=("/query",))
    a, _b = _socket.socketpair()
    ep = ReplicaEndpoint("r0", "replica", "127.0.0.1", 1, a)
    ep.index_version = 7
    router._endpoints[ep.replica_id] = ep
    wm = router._fleet_watermark()
    assert wm == frozenset({("r0", 7)})
    key = RouterResultCache.key("POST", "/query", b"{}")
    router.response_cache.fill(key, wm, 200, b"ok", "application/json")
    assert router.response_cache.lookup(key, wm) is not None
    lines = router.metrics_payload().splitlines()
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    seen = {}
    for f, labels, v in _parse_samples(lines):
        assert f in typed, f"router family {f!r} has no # TYPE line"
        seen.setdefault(f, []).append((labels, v))
    for fam in ("pathway_tpu_router_cache_hits",
                "pathway_tpu_router_cache_misses",
                "pathway_tpu_router_cache_invalidations",
                "pathway_tpu_router_cache_entries",
                "pathway_tpu_router_cache_hit_ratio",
                "pathway_tpu_replica_index_version"):
        assert fam in seen, fam
    assert seen["pathway_tpu_router_cache_hits"][0][1] >= 1
    assert seen["pathway_tpu_router_cache_entries"][0][1] == 1
    (labels, v), = seen["pathway_tpu_replica_index_version"]
    assert labels["replica"] == "r0" and v == 7
    st = router.status_payload()
    assert st["result_cache"]["routes"] == ["/query"]
    assert st["result_cache"]["watermark_live"] is True
    assert st["result_cache"]["entries"] == 1


# ---------------------------------------------------------------------------
# continuous profiling plane (engine/profiler.py): exposition + endpoints
# ---------------------------------------------------------------------------

@pytest.fixture
def _installed_profiler():
    """A live profiler with known device dispatches and folded stacks
    (sampler not started — the endpoints read state, not the thread)."""
    from pathway_tpu.engine.profiler import (Profiler, install_profiler,
                                             knn_search_cost)

    prof = Profiler(sample_interval_ms=1e6)
    f, b = knn_search_cost(4, 1024, 64)
    prof.record_dispatch("knn_search", f, b, 2.0)
    prof.record_dispatch("encoder_forward", 1e9, 1e6, 5.0)
    with prof._lock:
        prof._stacks[("worker", ("run (graph.py:10)", "step (knn.py:20)"))] = 3
        prof._stacks[("device-bridge", ("work (bridge.py:5)",
                                        "[device:knn_q]"))] = 2
        prof.samples_total = 5
        prof.device_attributed_samples = 2
    install_profiler(prof)
    yield prof
    install_profiler(None)


_PROFILER_FAMILIES = (
    "pathway_tpu_mfu_rolling", "pathway_tpu_hbm_bw_util",
    "pathway_tpu_kernel_device_ms", "pathway_tpu_kernel_dispatches",
    "pathway_tpu_kernel_mfu", "pathway_tpu_kernel_arithmetic_intensity",
    "pathway_tpu_profiler_samples",
    "pathway_tpu_profiler_device_attributed_samples",
    "pathway_tpu_profiler_overhead_ratio",
    "pathway_tpu_profiler_distinct_stacks",
)


def test_profiler_families_exposition_and_status(_installed_profiler):
    lines = _metrics_lines(_recording_runtime())
    samples = _parse_samples(lines)  # regex lint over every line
    fam = {f for f, _l, _v in samples}
    typed = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    for name in _PROFILER_FAMILIES:
        assert name in fam, f"{name} not exported"
        assert name in typed, f"{name} has no # TYPE declaration"
    kernels = {labels["family"]: v for f, labels, v in samples
               if f == "pathway_tpu_kernel_device_ms"}
    assert kernels == {"knn_search": 2.0, "encoder_forward": 5.0}
    counts = {f: v for f, labels, v in samples if not labels}
    assert counts["pathway_tpu_profiler_samples"] == 5.0
    assert counts["pathway_tpu_profiler_device_attributed_samples"] == 2.0
    assert counts["pathway_tpu_mfu_rolling"] > 0.0
    # /status.profiler: roofline verdict per family
    server = MonitoringHttpServer(_recording_runtime(), port=0)
    status = server.status_payload()
    rooflines = {fam: st["roofline"]["bound_by"]
                 for fam, st in status["profiler"]["families"].items()}
    assert rooflines["knn_search"] == "bandwidth"
    assert status["profiler"]["host"]["samples_total"] == 5


def test_metrics_without_profiler_omit_the_families():
    lines = _metrics_lines(_recording_runtime())
    fam = {f for f, _l, _v in _parse_samples(lines)}
    assert not fam & set(_PROFILER_FAMILIES)


def _tenant_runtime():
    """A recording runtime whose tracker completed per-tenant queries:
    acme fast (inside the 50ms SLO), bigco slow (burning budget)."""
    import time as _time

    from pathway_tpu.engine.request_tracker import RequestTracker

    rt = _recording_runtime()
    tr = RequestTracker(slo_ms=50.0)
    for tenant, ms, n in (("acme", 10.0, 8), ("bigco", 120.0, 8)):
        for i in range(n):
            base = _time.perf_counter()
            span = tr.start(f"{tenant}{i}", "/q", t_ingress=base)
            span.key = (tenant, i)
            tr._by_key[span.key] = span
            span.t_enqueued = base
            tr.attribute_tenant([span.key], tenant)
            span.t_resolved = base + ms / 1e3
            tr.finish(span)
    rt.scheduler.recorder.requests = tr
    return rt


def test_tenant_serving_families_exposition():
    rt = _tenant_runtime()
    lines = _metrics_lines(rt)
    samples = _parse_samples(lines)  # regex lint over every line
    # tenant-labeled quantiles ride under the EXISTING summary family —
    # exactly one TYPE declaration for it
    type_lines = [l.split()[2] for l in lines if l.startswith("# TYPE")]
    assert type_lines.count("pathway_tpu_query_e2e_latency_ms") == 1
    q = {(labels["tenant"], labels["quantile"]): v
         for f, labels, v in samples
         if f == "pathway_tpu_query_e2e_latency_ms" and "tenant" in labels}
    assert set(q) == {("acme", "0.5"), ("acme", "0.95"),
                      ("bigco", "0.5"), ("bigco", "0.95")}
    assert q[("acme", "0.5")] <= q[("acme", "0.95")]
    assert q[("bigco", "0.5")] > q[("acme", "0.95")]
    counts = {labels["tenant"]: v for f, labels, v in samples
              if f == "pathway_tpu_query_e2e_latency_ms_count"
              and "tenant" in labels}
    assert counts == {"acme": 8.0, "bigco": 8.0}
    burn = {labels["tenant"]: v for f, labels, v in samples
            if f == "pathway_tpu_tenant_slo_burn_rate"}
    assert burn["acme"] == 0.0
    assert burn["bigco"] > 1.0
    assert "pathway_tpu_tenant_slo_burn_rate" in type_lines


def test_profile_host_endpoint_serves_collapsed_stacks(_installed_profiler):
    server = MonitoringHttpServer(_recording_runtime(), port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        resp = urllib.request.urlopen(base + "/profile/host")
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
        lines = text.strip().splitlines()
        line_re = re.compile(r"^[^; ][^;]*(;[^;]+)* \d+$")
        for ln in lines:
            assert line_re.match(ln), f"bad collapsed line: {ln!r}"
        assert "worker;run (graph.py:10);step (knn.py:20) 3" in lines
        # the in-flight tag survives as the synthetic leaf frame
        assert any(ln.endswith("[device:knn_q] 2") for ln in lines)
        # ?seconds=N serves only the window's delta (no new samples
        # arrive while the sampler is idle -> empty window)
        resp = urllib.request.urlopen(base + "/profile/host?seconds=0.05")
        assert resp.read().decode() == ""
    finally:
        server.stop()


def test_profile_endpoints_503_without_profiler():
    import urllib.error

    server = MonitoringHttpServer(_recording_runtime(), port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        for path in ("/profile/host", "/profile/device/start",
                     "/profile/device/stop"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + path)
            err = _drain_http_error(ei)
            assert err.code == 503
    finally:
        server.stop()


def test_profile_device_capture_contract(_installed_profiler, monkeypatch,
                                         tmp_path):
    """start -> artifact dir in JSON; double-start 409; stop returns the
    same dir; idle stop 409. jax.profiler is stubbed: the test pins OUR
    endpoint contract, not XLA's tracer."""
    import urllib.error

    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    server = MonitoringHttpServer(_recording_runtime(), port=0)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        target = str(tmp_path / "cap")
        from urllib.parse import quote

        out = json.loads(urllib.request.urlopen(
            base + f"/profile/device/start?dir={quote(target, safe='')}"
        ).read())
        assert out == {"capturing": True, "dir": target}
        import os

        assert os.path.isdir(target)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/profile/device/start")
        assert _drain_http_error(ei).code == 409  # one capture at a time
        out = json.loads(urllib.request.urlopen(
            base + "/profile/device/stop").read())
        assert out == {"capturing": False, "dir": target}
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/profile/device/stop")
        assert _drain_http_error(ei).code == 409  # nothing running
        assert _installed_profiler.captures_total == 1
    finally:
        server.stop()


def test_fleet_merge_relabels_profiler_gauges_per_process():
    """PR-14 /fleet/metrics: the profiler gauges ride the merged
    exposition with {process=,role=} labels and ONE TYPE declaration —
    per-role MFU is readable straight off the fleet scrape."""
    from pathway_tpu.engine.fleet_observability import merge_metrics

    def doc(process, role, mfu, knn_ms):
        lines = [
            "# TYPE pathway_tpu_mfu_rolling gauge",
            f"pathway_tpu_mfu_rolling {mfu}",
            "# TYPE pathway_tpu_kernel_device_ms counter",
            f'pathway_tpu_kernel_device_ms{{family="knn_search"}} {knn_ms}',
            "# EOF",
        ]
        return ({"process": process, "role": role},
                "\n".join(lines) + "\n")

    merged = merge_metrics([doc("primary-0", "primary", 0.31, 12.0),
                            doc("replica-1", "replica", 0.07, 48.0)])
    lines = merged.splitlines()
    samples = _parse_samples(lines)  # regex lint over every line
    type_lines = [l.split()[2] for l in lines if l.startswith("# TYPE")]
    assert type_lines.count("pathway_tpu_mfu_rolling") == 1
    assert type_lines.count("pathway_tpu_kernel_device_ms") == 1
    mfu = {(labels.get("process"), labels.get("role")): v
           for f, labels, v in samples if f == "pathway_tpu_mfu_rolling"
           if "process" in labels}
    assert mfu[("primary-0", "primary")] == 0.31
    assert mfu[("replica-1", "replica")] == 0.07
    knn = {labels.get("process"): (v, labels.get("family"))
           for f, labels, v in samples
           if f == "pathway_tpu_kernel_device_ms" and "process" in labels}
    assert knn["primary-0"] == (12.0, "knn_search")
    assert knn["replica-1"] == (48.0, "knn_search")
