"""Scale-out canary: the exchange plane must carry an honest multi-worker
speedup, on both transports, without changing a single output byte.

Two gates (same pattern as paging_canary.py — the gate is trusted because
a seeded property is proven end to end):

1. **bench scaleout leg** (bench.bench_scaleout): the WordCount+join ETL
   pipeline at 1 process vs 4 SPMD processes over BOTH transports (shm
   slab ring and raw tcp). Always gated: byte-identity of the merged
   consolidated outputs per transport, both transports actually used,
   and the coalesced exchange round count. Conditionally gated:
   ``etl_scaleout_efficiency`` ≥ 0.7 — ONLY when the runner exposes
   ≥ 4 cores (the cores-vs-workers honesty rule, bench_etl: a 4-process
   figure on fewer cores measures timesharing, not scaling; the leg then
   reports the number and flags ``scaleout_oversubscribed`` instead).
   The leg's JSON is written as a CI artifact AND checkpointed into
   ``BENCH_LASTGOOD.json`` per the evidence rule.

2. **codec absolute budget**: best-of-5 encode+decode of the r05 payload
   shape through the columnar wire format must stay ≤ 3.0 µs/row (vs
   6.495 at the r05 incident) — the same bound
   tests/test_exchange_perf.py pins, re-proven here against the bench's
   own measurement path so the artifact and the gate cannot drift apart.

Exits 0 iff all hold. Run: ``python tests/scaleout_canary.py``.
Knobs: BENCH_SCALEOUT_ROWS, SCALEOUT_MIN_EFFICIENCY (default 0.7),
SCALEOUT_BENCH_ARTIFACT (JSON path), BENCH_LASTGOOD_PATH.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

MIN_EFFICIENCY = float(os.environ.get("SCALEOUT_MIN_EFFICIENCY", 0.7))
ABS_BUDGET_US = 3.0


def gate_bench_leg() -> dict:
    import bench

    out = bench.bench_scaleout()
    bench._write_lastgood(out)  # evidence rule: checkpoint immediately
    artifact = os.environ.get("SCALEOUT_BENCH_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(out, f, indent=1)
    for transport in ("shm", "tcp"):
        assert out[f"scaleout_identical_{transport}"] is True, (
            f"{transport}: 4-process consolidated outputs diverged from "
            f"the 1-process run — the exchange plane changed results")
        assert out[f"scaleout_transport_used_{transport}"] == [transport], (
            f"forced transport {transport} was not the one used: "
            f"{out[f'scaleout_transport_used_{transport}']}")
        assert out[f"scaleout_exchange_rounds_{transport}"] > 0, out
    assert out["scaleout_shm_slab_bytes"] > 0, (
        "shm run moved no slab bytes — payloads fell back to sockets")
    cores = out["scaleout_n_cores"]
    eff = out.get("etl_scaleout_efficiency")
    assert eff is not None, "no transport produced an identical run"
    if cores >= out["scaleout_workers"]:
        assert eff >= MIN_EFFICIENCY, (
            f"etl_scaleout_efficiency {eff} < {MIN_EFFICIENCY} on a "
            f"{cores}-core host: scale-out is not honest yet "
            f"(1p {out['scaleout_rows_per_s_1p']} rows/s vs best 4p "
            f"{max(out['scaleout_rows_per_s_4p_shm'], out['scaleout_rows_per_s_4p_tcp'])})")
        print(f"[gate1] efficiency {eff} >= {MIN_EFFICIENCY} at "
              f"{out['scaleout_workers']} workers on {cores} cores "
              f"(best transport: {out['scaleout_best_transport']})")
    else:
        print(f"[gate1] identity holds on both transports; efficiency "
              f"{eff} reported NOT gated ({cores} cores < "
              f"{out['scaleout_workers']} workers — timesharing, the "
              f"honesty rule)")
    return out


def gate_codec_budget() -> None:
    import gc
    import time

    from pathway_tpu.engine import wire
    from pathway_tpu.internals.keys import hash_values

    n = 20_000
    ents = [(hash_values("row", i), (f"w{i % 5000}", int(i % 9 + 1)), 1)
            for i in range(n)]
    payload = {"rows": {0: {0: ents}}, "wm": None, "bcast": None}
    best = float("inf")
    # freeze the long-lived heap so a gen-2 GC pass over unrelated
    # objects cannot land inside a trial (the r05 noise class); the
    # codec's own allocations still pay their GC cost
    gc.collect()
    gc.freeze()
    for _ in range(5):
        t0 = time.perf_counter()
        blob = b"".join(wire.encode_frame(("x", 1, 0), payload)[0])
        wire.decode_frame(blob)
        best = min(best, (time.perf_counter() - t0) / n * 1e6)
    gc.unfreeze()
    assert best <= ABS_BUDGET_US, (
        f"columnar enc+dec best-of-5 {best:.3f} µs/row > {ABS_BUDGET_US} "
        f"(r05 was 6.495): absolute regression")
    print(f"[gate2] columnar enc+dec best-of-5 {best:.3f} µs/row "
          f"<= {ABS_BUDGET_US}")


def main() -> int:
    gate_bench_leg()
    gate_codec_budget()
    print("scaleout canary: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
