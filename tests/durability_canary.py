"""Durability gate: watermark commits must survive a seeded crash storm.

Drives ``examples/streaming_etl.py``'s real graph under persistence with
``PATHWAY_DEVICE_INFLIGHT=4`` through a seeded crash/restart loop: each
round trickles more order files in, arms a RANDOM watermark-boundary
fault point (``bridge.leg.exec`` / ``bridge.leg.resolved`` /
``persistence.commit`` / ``persistence.append.torn`` /
``persistence.fsync``) at a random hit index, and lets the run crash (or
go quiescent when the point never fires). After the storm, a clean run
over the same persistence root must produce a consolidated CSV
**identical** to a synchronous (``PATHWAY_DEVICE_INFLIGHT=1``,
no-persistence) reference over the full input — exactly-once at every
seeded crash point.

The final run must also prove the tentpole property: with persistence ON
the bridge reaches depth > 1 (the old barrier-before-commit pinned it at
effective depth 1) and trailing watermark commits happened mid-stream.

Exits 0 iff both hold. Run: ``python tests/durability_canary.py``
(``DURABILITY_SEED`` reruns a specific storm).
"""

from __future__ import annotations

import csv
import json
import os
import pathlib
import random
import sys
import tempfile
import threading
import time

N_ROUNDS = 3
FILES_PER_ROUND = 3
ROWS_PER_FILE = 4
POINTS = ("bridge.leg.exec", "bridge.leg.resolved", "persistence.commit",
          "persistence.append.torn", "persistence.fsync")


def _write_round(orders: pathlib.Path, rnd: int) -> None:
    for f in range(FILES_PER_ROUND):
        base = rnd * FILES_PER_ROUND + f
        rows = [{"item": f"i{(base + i) % 4}", "qty": 1 + (base + i) % 3,
                 "price": 2.5 * (1 + (base + i) % 5),
                 "ts": 60 * (base * ROWS_PER_FILE + i)}
                for i in range(ROWS_PER_FILE)]
        (orders / f"{base:03d}.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows) + "\n")


def _write_cats(root: pathlib.Path) -> str:
    cats = root / "categories.csv"
    cats.write_text("item,category\n" + "\n".join(
        f"i{i},cat{i % 2}" for i in range(4)) + "\n")
    return str(cats)


def _consolidate_csv(path: str) -> list:
    if not os.path.exists(path):
        return []
    acc: dict[tuple, int] = {}
    with open(path) as f:
        reader = csv.reader(f)
        header = next(reader, None)
        if header is None:
            return []
        t_pos, d_pos = header.index("time"), header.index("diff")
        for r in reader:
            key = tuple(v for i, v in enumerate(r)
                        if i not in (t_pos, d_pos))
            acc[key] = acc.get(key, 0) + int(r[d_pos])
    return sorted(k for k, n in acc.items() for _ in range(n) if n > 0)


def _run(orders_dir: str, cats_csv: str, out_csv: str, *, inflight: int,
         pdir: str | None, max_s: float = 25.0):
    """One run attempt: build the real graph, run on a thread, wait for a
    crash or sink quiescence, stop. Returns (error, bridge_stats,
    persistence_stats)."""
    os.environ["PATHWAY_DEVICE_INFLIGHT"] = str(inflight)
    import pathway_tpu as pw
    from examples.streaming_etl import build
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    build(orders_dir, cats_csv, out_csv)
    cfg = None
    if pdir is not None:
        cfg = pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(pdir))
    err: list[BaseException] = []

    def _target():
        try:
            pw.run(persistence_config=cfg, terminate_on_error=True)
        except BaseException as e:  # noqa: BLE001 — the injected crash
            err.append(e)

    t = threading.Thread(target=_target, daemon=True)
    t.start()
    deadline = time.monotonic() + max_s
    rt = None
    while time.monotonic() < deadline and rt is None and t.is_alive():
        live = list(_streaming._ACTIVE_RUNTIMES)
        rt = live[0] if live else None
        time.sleep(0.05)
    last_size = -1
    while time.monotonic() < deadline and t.is_alive():
        size = os.path.getsize(out_csv) if os.path.exists(out_csv) else 0
        if size > 0 and size == last_size:
            break  # sink quiescent: the finite feed is fully ingested
        last_size = size
        time.sleep(0.3)
    _streaming.stop_all()
    t.join(20.0)
    assert not t.is_alive(), "runtime did not stop"
    bridge = rt.scheduler.bridge_stats() if rt is not None else None
    pstats = rt.persistence.stats() \
        if rt is not None and rt.persistence is not None else None
    G.clear()
    return (err[0] if err else None), bridge, pstats


def main() -> int:
    seed = int(os.environ.get("DURABILITY_SEED", "8"))
    rng = random.Random(seed)
    from pathway_tpu.testing import faults

    # injected write failures must crash, not be retried away
    os.environ["PATHWAY_PERSISTENCE_WRITE_RETRIES"] = "0"
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders = root / "orders"
        orders.mkdir()
        cats_csv = _write_cats(root)
        pdir = str(root / "pstate")

        crashes = 0
        for rnd in range(N_ROUNDS):
            _write_round(orders, rnd)
            point = rng.choice(POINTS)
            k = rng.randint(2, 12)
            faults.arm_point(point, faults.FailOnHit(k))
            try:
                err, _bridge, _p = _run(
                    str(orders), cats_csv, str(root / f"out_{rnd}.csv"),
                    inflight=4, pdir=pdir)
            finally:
                faults.reset()
            if err is not None:
                if not isinstance(err, faults.InjectedFault):
                    print(f"FAIL: round {rnd} died of an UNINJECTED error: "
                          f"{type(err).__name__}: {err}", file=sys.stderr)
                    return 1
                crashes += 1
                print(f"round {rnd}: crashed at {point!r} hit {k} "
                      f"(as injected)")
            else:
                print(f"round {rnd}: {point!r} hit {k} never fired "
                      f"(quiescent run)")

        # final clean recovery run over the full input + durable prefix.
        # One more round of files lands first, so the recovery run always
        # has fresh rows to commit (a storm that already made everything
        # durable would otherwise leave the trailing-commit gate moot).
        _write_round(orders, N_ROUNDS)
        final_csv = str(root / "out_final.csv")
        err, bridge, pstats = _run(str(orders), cats_csv, final_csv,
                                   inflight=4, pdir=pdir)
        if err is not None:
            print(f"FAIL: clean recovery run raised {type(err).__name__}: "
                  f"{err}", file=sys.stderr)
            return 1
        got = _consolidate_csv(final_csv)

        # synchronous no-persistence reference over the same full input
        err, sync_bridge, _ = _run(str(orders), cats_csv,
                                   str(root / "out_sync.csv"),
                                   inflight=1, pdir=None)
        if err is not None:
            print(f"FAIL: sync reference raised {type(err).__name__}: "
                  f"{err}", file=sys.stderr)
            return 1
        want = _consolidate_csv(str(root / "out_sync.csv"))
        if sync_bridge is not None:
            print(f"FAIL: inflight=1 still built a bridge: {sync_bridge}",
                  file=sys.stderr)
            return 1
        if not want or got != want:
            print(f"FAIL: recovered CSV != synchronous CSV "
                  f"({len(got)} vs {len(want)} rows, seed {seed}, "
                  f"{crashes} crashes)", file=sys.stderr)
            for row in got[:5]:
                print(f"  got : {row}", file=sys.stderr)
            for row in want[:5]:
                print(f"  want: {row}", file=sys.stderr)
            return 1

        # tentpole property: persistence no longer collapses the bridge
        if not bridge or bridge["max_depth"] < 2:
            print(f"FAIL: bridge never exceeded depth 1 under persistence "
                  f"(watermark commits are still barriering): {bridge}",
                  file=sys.stderr)
            return 1
        if not pstats or pstats["commits_with_data"] < 1:
            print(f"FAIL: no trailing watermark commit happened: {pstats}",
                  file=sys.stderr)
            return 1
        print(f"OK: seed {seed}, {crashes}/{N_ROUNDS} rounds crashed; "
              f"recovered CSV identical to sync run ({len(got)} rows); "
              f"bridge max depth {bridge['max_depth']} with persistence "
              f"on; watermark t={pstats['watermark']} over "
              f"{pstats['commits_with_data']} durable commits")
        return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
