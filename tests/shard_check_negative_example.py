"""Deliberately misconfigured sharded-KNN pipeline — the CI canary proving
the PWT1xx gate bites.

``python -m pathway_tpu check --tpu-mesh 8x1 tests/shard_check_negative_example.py``
must exit nonzero: the slab reservation (1001 rows) does not tile the
8-way data axis (PWT102). Without ``--tpu-mesh`` the slab stays unsharded
and the script is clean — the misconfiguration is topology-relative.
"""

import numpy as np

import pathway_tpu as pw
import pathway_tpu.internals.schema as sch
from pathway_tpu.stdlib.indexing import default_brute_force_knn_document_index

docs = pw.io.fs.read("./docs", format="json", mode="streaming",
                     schema=sch.schema_from_types(doc=str))
data = docs.select(vec=pw.apply_with_type(
    lambda d: np.zeros(16, dtype=np.float32), np.ndarray, docs.doc))
# seeded misconfiguration: 1001 is not divisible by the 8-way data axis
index = default_brute_force_knn_document_index(
    data.vec, data, dimensions=16, reserved_space=1001, mesh="auto")
hits = index.query_as_of_now(data.vec, number_of_matches=1)
pw.io.subscribe(hits, lambda *a, **k: None)
