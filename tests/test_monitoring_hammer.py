"""Monitoring-hammer regression test: N threads pounding ``/metrics`` +
``/status`` while ingest grows the paged store and the device bridge
pipelines at inflight=4.

Pins the PR-7 race class — ``DevicePagePool.stats()`` iterating allocator
dicts while the ingest path mutates them (RuntimeError: dictionary changed
size during iteration) — so it cannot recur: the monitoring threads read
the same live pool registry ``/metrics`` reads in production, through the
same HTTP server, while the main thread churns adds/removes through the
index lock and the bridge worker retires legs concurrently.
"""

from __future__ import annotations

import threading
import urllib.request

import numpy as np

from pathway_tpu.engine.device_bridge import DeviceBridge
from pathway_tpu.engine.http_server import MonitoringHttpServer
from pathway_tpu.internals.keys import Pointer

N_HAMMER_THREADS = 6
N_INGEST_BATCHES = 60
BATCH_ROWS = 96
DIM = 16


class _Node:
    def __init__(self, id, name):
        self.id = id
        self.name = name
        self.op = object()
        self.trace = None


class _Runtime:
    """The minimal runtime surface MonitoringHttpServer reads, wired to a
    REAL flight recorder and a REAL device bridge (the fake parts are only
    the graph shell)."""

    def __init__(self, bridge):
        from pathway_tpu.engine.flight_recorder import FlightRecorder

        class Sched:
            stats = {0: {"insertions": 0, "retractions": 0}}
            recorder = FlightRecorder()
            _bridge = bridge

            def bridge_stats(self):
                return bridge.stats()

        class Graph:
            nodes = [_Node(0, "ingest")]

        class Runner:
            graph = Graph()

        self.scheduler = Sched()
        self.runner = Runner()
        self.sessions = []


def test_monitoring_hammer_under_paged_ingest_and_pipelining():
    from pathway_tpu.ops.knn import BruteForceKnnIndex

    rng = np.random.default_rng(7)
    # paged explicitly: the race class under test lives in the page
    # allocator's dict iteration, regardless of the matrix's default
    index = BruteForceKnnIndex(dimensions=DIM, reserved_space=256,
                               paged=True, page_rows=128)
    bridge = DeviceBridge(max_inflight=4, name="hammer-bridge")
    server = MonitoringHttpServer(_Runtime(bridge), port=0)
    server.start()
    stop = threading.Event()
    failures: list[BaseException] = []
    statuses: list[int] = []

    def hammer():
        base = f"http://127.0.0.1:{server.port}"
        while not stop.is_set():
            for path in ("/status", "/metrics", "/healthz"):
                try:
                    with urllib.request.urlopen(base + path,
                                                timeout=10) as resp:
                        statuses.append(resp.status)
                        resp.read()
                except Exception as e:  # noqa: BLE001 — collected, asserted
                    failures.append(e)
                    return

    threads = [threading.Thread(target=hammer, daemon=True,
                                name=f"hammer-{i}")
               for i in range(N_HAMMER_THREADS)]
    for t in threads:
        t.start()

    try:
        # ingest on the "commit loop" (this thread), device legs on the
        # bridge worker at inflight=4 — the two mutate the index/pool
        # while the hammer threads iterate its stats
        for batch in range(N_INGEST_BATCHES):
            keys = [Pointer(batch * BATCH_ROWS + i)
                    for i in range(BATCH_ROWS)]
            vecs = rng.standard_normal((BATCH_ROWS, DIM)).astype(
                np.float32)
            index.add_batch(keys, vecs)
            if batch % 3 == 2:
                # churn: free a third of the previous batch so pages
                # cycle through the free list, not just grow
                for k in keys[::3]:
                    index.remove(k)
            bridge.submit(batch + 1,
                          lambda n=len(keys): index.page_stats())
        bridge.barrier()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        bridge.close()
        server.stop()

    assert not failures, f"monitoring endpoint crashed under load: " \
                         f"{failures[:3]}"
    assert statuses, "hammer threads never completed a request"
    assert set(statuses) <= {200, 503}  # healthz may report degraded
    # the scenario actually exercised what it claims: growth happened and
    # the bridge pipelined
    st = index.page_stats()
    assert st["grow_events"] >= 1
    bs = bridge.stats()
    assert bs["legs_resolved"] == N_INGEST_BATCHES
    assert bs["max_depth"] >= 2
