"""Reducers & groupby (reference: engine Reducer set, src/engine/reduce.rs:22)."""

import numpy as np

import pathway_tpu as pw
from tests.utils import T, assert_table_equality_wo_index, rows_of


def _t():
    return T("""
    g | x | y
    a | 3 | 1.5
    a | 1 | 2.5
    b | 2 | 0.5
    """)


def test_basic_reducers():
    t = _t()
    r = t.groupby(t.g).reduce(
        t.g,
        s=pw.reducers.sum(t.x),
        n=pw.reducers.count(),
        mn=pw.reducers.min(t.x),
        mx=pw.reducers.max(t.x),
        av=pw.reducers.avg(t.y),
    )
    assert sorted(rows_of(r)) == [("a", 4, 2, 1, 3, 2.0), ("b", 2, 1, 2, 2, 0.5)]


def test_argmin_argmax():
    t = _t()
    r = t.groupby(t.g).reduce(
        t.g,
        lo=pw.reducers.argmin(t.x),
        hi=pw.reducers.argmax(t.x),
    )
    fetched_lo = t.ix(r.lo, context=r)
    fetched_hi = t.ix(r.hi, context=r)
    vals = r.select(r.g, lo_x=fetched_lo.x, hi_x=fetched_hi.x)
    assert sorted(rows_of(vals)) == [("a", 1, 3), ("b", 2, 2)]


def test_tuple_reducers():
    t = _t()
    r = t.groupby(t.g).reduce(
        t.g,
        st=pw.reducers.sorted_tuple(t.x),
    )
    assert sorted(rows_of(r)) == [("a", (1, 3)), ("b", (2,))]


def test_unique_any():
    t = T("""
    g | c
    a | 7
    a | 7
    b | 9
    """)
    r = t.groupby(t.g).reduce(t.g, u=pw.reducers.unique(t.c),
                              an=pw.reducers.any(t.c))
    assert sorted(rows_of(r)) == [("a", 7, 7), ("b", 9, 9)]


def test_ndarray_reducer():
    t = _t()
    r = t.groupby(t.g).reduce(t.g, arr=pw.reducers.ndarray(t.x))
    rows = dict(rows_of(r))
    assert sorted(rows["a"].tolist()) == [1, 3]


def test_earliest_latest():
    t = T("""
    g | x | _time
    a | 1 | 2
    a | 2 | 4
    a | 3 | 6
    """)
    r = t.groupby(t.g).reduce(
        t.g, e=pw.reducers.earliest(t.x), l=pw.reducers.latest(t.x))
    assert rows_of(r) == [("a", 1, 3)]


def test_stateful_single():
    t = T("""
    g | x
    a | 1
    a | 2
    b | 5
    """)

    def acc(state, x):
        return (state or 0) + x

    r = t.groupby(t.g).reduce(t.g, s=pw.reducers.stateful_single(acc, t.x))
    assert sorted(rows_of(r)) == [("a", 3), ("b", 5)]


def test_compound_reduce_expression():
    t = _t()
    r = t.groupby(t.g).reduce(
        t.g, z=pw.reducers.sum(t.x) * 10 + pw.reducers.count())
    assert sorted(rows_of(r)) == [("a", 42), ("b", 21)]


def test_incremental_retraction():
    t = T("""
    g | x | _time | _diff
    a | 1 | 2     | 1
    a | 2 | 4     | 1
    a | 1 | 6     | -1
    """)
    r = t.groupby(t.g).reduce(t.g, s=pw.reducers.sum(t.x),
                              mn=pw.reducers.min(t.x))
    assert rows_of(r) == [("a", 2, 2)]


def test_groupby_instance():
    t = T("""
    g | i | x
    a | 0 | 1
    a | 1 | 2
    b | 0 | 5
    """)
    r = t.groupby(t.g, instance=t.i).reduce(t.g, s=pw.reducers.sum(t.x))
    assert sorted(rows_of(r)) == [("a", 1), ("a", 2), ("b", 5)]


def test_global_reduce_empty_groups_vanish():
    t = T("""
    g | x | _time | _diff
    a | 1 | 2     | 1
    a | 1 | 4     | -1
    """)
    r = t.groupby(t.g).reduce(t.g, n=pw.reducers.count())
    assert rows_of(r) == []


def test_same_tick_net_zero_pair_invisible_to_order_sensitive_reducers():
    """A same-batch insert+delete of the same row must cancel BEFORE
    operators see it: earliest/latest would otherwise permanently record
    the deleted value (their canonical sort processes retractions first,
    so the uncancelled insert lands with no matching retraction), sinks
    would emit phantom events, and float sums would drift."""
    t = T("""
    g | v | _time | _diff
    a | 1 | 2     | 1
    a | 9 | 4     | 1
    a | 9 | 4     | -1
    """)
    r = t.groupby(t.g).reduce(
        t.g, last=pw.reducers.latest(t.v), s=pw.reducers.sum(t.v))
    assert sorted(rows_of(r)) == [("a", 1, 1)]

    # and the sink never observes the phantom value
    t2 = T("""
    g | v | _time | _diff
    a | 1 | 2     | 1
    a | 9 | 4     | 1
    a | 9 | 4     | -1
    """)
    from pathway_tpu.internals.runner import run_tables

    [cap] = run_tables(t2)
    assert all(row[1] != 9 for _k, row, _t, _d in cap.events)


def test_columnar_minmax_reducers_exact_under_retraction():
    """min/max ride the columnar operator as multiset side-state and stay
    exact through retractions, matching the row path."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.engine.operators import ColumnarGroupByOperator
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    G.clear()
    rows = [
        ("a", 5, 0, 1), ("a", 2, 0, 1), ("a", 9, 2, 1),
        ("b", 7, 2, 1), ("a", 2, 4, -1), ("a", 9, 4, -1),
    ]
    t = table_from_rows(
        sch.schema_from_types(k=str, v=int), rows, is_stream=True)
    g = t.groupby(t.k).reduce(
        t.k, lo=pw.reducers.min(t.v), hi=pw.reducers.max(t.v),
        s=pw.reducers.sum(t.v))
    runner = GraphRunner()
    cap = runner.capture(g)
    assert any(isinstance(n.op, ColumnarGroupByOperator)
               for n in runner.graph.nodes)
    runner.run_batch(n_workers=1)
    snap = sorted(cap.snapshot().values())
    # after retracting 2 and 9, group a holds only 5
    assert snap == [("a", 5, 5, 5), ("b", 7, 7, 7)]
    G.clear()


def test_columnar_minmax_ignores_net_negative_counts():
    """A retraction arriving ahead of its insertion must not surface its
    value in min/max (row-path _MultisetState parity)."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    G.clear()
    rows = [("a", 5, 0, 1), ("a", 7, 0, 1), ("a", 2, 0, -1)]
    t = table_from_rows(
        sch.schema_from_types(k=str, v=int), rows, is_stream=True)
    g = t.groupby(t.k).reduce(t.k, lo=pw.reducers.min(t.v))
    runner = GraphRunner()
    cap = runner.capture(g)
    runner.run_batch(n_workers=1)
    assert sorted(cap.snapshot().values()) == [("a", 5)]
    G.clear()


def test_columnar_argminmax_matches_row_path():
    """argmin/argmax ride the columnar operator; results (incl. key
    payloads, tiebreaks, retractions) must equal the row path."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.engine.operators import (ColumnarGroupByOperator,
                                              GroupByOperator)
    from pathway_tpu.internals import runner as _runner
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    rows = [
        ("a", 5, "x", 0, 1), ("a", 9, "y", 0, 1), ("a", 9, "z", 2, 1),
        ("b", 1, "q", 2, 1), ("a", 9, "z", 4, -1),
    ]

    def run(force_row_path):
        G.clear()
        t = table_from_rows(
            sch.schema_from_types(k=str, v=int, tag=str), rows,
            is_stream=True)
        from pathway_tpu.internals import expression as ex

        g = t.groupby(t.k).reduce(
            t.k,
            best_tag=ex.ReducerExpression("argmax", t.v, t.tag),
            lo_key=pw.reducers.argmin(t.v),
        )
        runner = GraphRunner()
        cap = runner.capture(g)
        kinds = {type(n.op) for n in runner.graph.nodes}
        if force_row_path:
            assert GroupByOperator in kinds
        else:
            assert ColumnarGroupByOperator in kinds
        runner.run_batch(n_workers=1)
        out = sorted(cap.snapshot().values())
        G.clear()
        return out

    columnar = run(False)
    orig = _runner._columnar_groupby_spec
    _runner._columnar_groupby_spec = lambda *a, **k: None
    try:
        row = run(True)
    finally:
        _runner._columnar_groupby_spec = orig
    assert columnar == row
    # argmax of a: after retracting (9, z), tie between remaining 9=y
    assert columnar[0][1] == "y"


def test_array_sum_device_path_bitwise_matches_numpy(monkeypatch):
    """Big float32 ndarray columns reduce through the XLA segment-sum
    (operators._device_array_sums); the device result must be BITWISE
    equal to the per-row numpy path — the scan kernel accumulates each
    group's rows sequentially in the same canonical order, so no float
    tolerance is needed (and the n_workers byte-identity contract
    holds)."""
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.engine import operators as eng_ops
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    rng = np.random.default_rng(7)
    vecs = rng.standard_normal((300, 6)).astype(np.float32)
    # one group of pure -0.0 rows: the device seed must reproduce each
    # state's numpy start exactly (npsum keeps -0.0, sum's int-0 start
    # flips it to +0.0) — np.array_equal can't see the sign, so signbits
    # are compared below
    vecs[::7] = -0.0
    rows = [(f"g{i % 7}", vecs[i], (i % 3) * 2, 1) for i in range(300)]

    def run(device_min, n_workers=1):
        monkeypatch.setattr(eng_ops, "_ARRAY_SUM_DEVICE_MIN", device_min)
        # sharded workers see ~300/(3 ticks × n_workers) entries per tick;
        # drop the row gate so the 4-worker leg really drives the device
        # path instead of vacuously passing through the numpy loop
        monkeypatch.setattr(eng_ops, "_ARRAY_SUM_MIN_ROWS", 1)
        G.clear()
        t = table_from_rows(
            sch.schema_from_types(g=str, v=np.ndarray), rows,
            is_stream=True)
        # npsum (array_sum) AND plain sum() both ride the device path
        r = t.groupby(t.g).reduce(t.g, s=pw.reducers.npsum(t.v),
                                  s2=pw.reducers.sum(t.v))
        runner = GraphRunner()
        cap = runner.capture(r)
        runner.run_batch(n_workers=n_workers)
        out = {row[0]: (row[1], row[2]) for row in cap.snapshot().values()}
        G.clear()
        return out

    numpy_out = run(0)             # device path disabled
    device_out = run(1)            # every tick routes through XLA
    device_sharded = run(1, n_workers=4)
    assert set(numpy_out) == set(device_out) == set(device_sharded)

    def bitwise_equal(a, b):
        return a.dtype == b.dtype and a.tobytes() == b.tobytes()

    for g in numpy_out:
        for col in (0, 1):  # npsum and plain sum
            assert numpy_out[g][col].dtype == np.float32
            assert bitwise_equal(numpy_out[g][col], device_out[g][col]), \
                (g, col, numpy_out[g][col], device_out[g][col])
            assert bitwise_equal(numpy_out[g][col],
                                 device_sharded[g][col]), (g, col)
