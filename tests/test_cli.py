"""CLI: spawn / replay / record (reference: python/pathway/cli.py:53-280)."""

from __future__ import annotations

import csv
import os
import subprocess
import sys
import textwrap

import pytest

_ENV = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")


def _run_cli(*args, env=None, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu", *args],
        env=env or _ENV, capture_output=True, text=True, timeout=timeout)


def test_help_and_version():
    res = _run_cli("--help")
    assert res.returncode == 0
    assert "spawn" in res.stdout and "replay" in res.stdout
    res = _run_cli("--version")
    assert "pathway-tpu" in res.stdout


_PROGRAM = textwrap.dedent("""
    import os
    import pathway_tpu as pw

    out = os.environ["TEST_OUT"] + os.environ.get("PATHWAY_PROCESS_ID", "?")
    t = pw.io.fs.read(os.environ["TEST_IN"], format="plaintext", mode="batch",
                      autocommit_duration_ms=20, persistent_id="src")
    counts = t.groupby(t.data).reduce(word=t.data, c=pw.reducers.count())
    pw.io.fs.write(counts, out, format="csv")
    pw.run()
""")


def _counts(path) -> dict[str, int]:
    state: dict[str, int] = {}
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            if int(row["diff"]) > 0:
                state[row["word"]] = int(row["c"])
            elif state.get(row["word"]) == int(row["c"]):
                del state[row["word"]]
    return state


def test_spawn_multi_process_env(tmp_path):
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "a.txt").write_text("x\ny\nx\n")
    prog = tmp_path / "prog.py"
    prog.write_text(_PROGRAM)
    env = dict(_ENV, TEST_IN=str(tmp_path / "in"),
               TEST_OUT=str(tmp_path / "out"))
    res = _run_cli("spawn", "-n", "2", sys.executable, str(prog), env=env)
    assert res.returncode == 0, res.stderr
    # -n forks a true process cluster (TCP exchange, engine/multiproc.py):
    # each process owns a worker block and writes ITS shard of the result;
    # the union of the shards equals the single-process answer and the
    # shards are disjoint (state actually partitioned across processes)
    assert "2 processes (2 total workers)" in res.stderr
    c0 = _counts(tmp_path / "out0")
    c1 = _counts(tmp_path / "out1")
    assert not (set(c0) & set(c1))
    assert {**c0, **c1} == {"x": 2, "y": 1}


def test_record_then_replay(tmp_path):
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "a.txt").write_text("p\nq\n")
    prog = tmp_path / "prog.py"
    prog.write_text(_PROGRAM)
    record = str(tmp_path / "rec")
    env = dict(_ENV, TEST_IN=str(tmp_path / "in"),
               TEST_OUT=str(tmp_path / "out"))

    res = _run_cli("spawn", "--record", "--record-path", record,
                   sys.executable, str(prog), env=env)
    assert res.returncode == 0, res.stderr
    assert _counts(tmp_path / "out0") == {"p": 1, "q": 1}
    assert os.path.isdir(os.path.join(record, "streams"))

    # replay against an EMPTY input dir: rows must come from the recording
    for f in (tmp_path / "in").iterdir():
        f.unlink()
    env2 = dict(env, TEST_OUT=str(tmp_path / "replay_out"))
    res = _run_cli("replay", "--record-path", record, "--mode", "batch",
                   sys.executable, str(prog), env=env2)
    assert res.returncode == 0, res.stderr
    assert _counts(tmp_path / "replay_out0") == {"p": 1, "q": 1}
