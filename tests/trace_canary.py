"""Trace canary: the flight recorder's two load-bearing promises, proven
end to end (same pattern as pipelining_canary.py / watchdog_canary.py).

1. **Trace gate** — drive ``examples/streaming_etl.py``'s real graph with
   ``PATHWAY_TRACE_PATH`` and assert the written file is valid Chrome
   trace JSON: metadata-named host/device tracks, > 0 device-leg operator
   spans, every B properly closed by a matching E (a mis-nested file
   renders as garbage in Perfetto), user-frame attribution present.

2. **Overhead guard** — with tracing disabled, the recorder hook must add
   < 2% per-tick wall time versus no recorder at all (the disabled path
   is one branch per operator step). Measured on the same join + sliding
   window + groupby shape the streaming example runs, over many ticks,
   min-of-K to de-noise; the device UDF is left out and the bridge pinned
   synchronous so the comparison measures the scheduler hook, not XLA
   compile or thread-scheduling variance.

Exits 0 iff both hold. Run: ``python tests/trace_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile
import time


def _check_nesting(events) -> str | None:
    stacks: dict = {}
    for ev in events:
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev["name"])
        elif ev["ph"] == "E":
            stack = stacks.setdefault(ev["tid"], [])
            if not stack:
                return f"E without B on tid {ev['tid']}: {ev['name']}"
            top = stack.pop()
            if top != ev["name"]:
                return f"mis-nested: E {ev['name']!r} closes B {top!r}"
    for tid, stack in stacks.items():
        if stack:
            return f"unclosed spans on tid {tid}: {stack}"
    return None


def check_trace_file() -> str | None:
    """Run the streaming example's graph with a trace path; return an
    error string or None."""
    from tests.pipelining_canary import _write_feed

    os.environ["PATHWAY_DEVICE_INFLIGHT"] = "2"
    import pathway_tpu as pw
    from examples.streaming_etl import build
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders_dir, cats_csv = _write_feed(root)
        out_csv = str(root / "out.csv")
        trace_path = str(root / "trace.json")
        build(orders_dir, cats_csv, out_csv)
        import threading

        def _run():
            pw.run(trace_path=trace_path)

        th = threading.Thread(target=_run, daemon=True)
        th.start()
        deadline = time.monotonic() + 30.0
        rt = None
        while time.monotonic() < deadline and rt is None:
            live = list(_streaming._ACTIVE_RUNTIMES)
            rt = live[0] if live else None
            time.sleep(0.05)
        if rt is None:
            return "runtime never started"
        # wait until device legs visibly resolved and the sink settled
        last_size = -1
        while time.monotonic() < deadline:
            stats = rt.scheduler.bridge_stats()
            size = os.path.getsize(out_csv) if os.path.exists(out_csv) else 0
            if stats and stats["legs_resolved"] > 0 and size > 0 \
                    and size == last_size:
                break
            last_size = size
            time.sleep(0.25)
        _streaming.stop_all()
        th.join(15.0)
        G.clear()
        if not os.path.exists(trace_path):
            return f"no trace written at {trace_path}"
        artifact = os.environ.get("PATHWAY_TRACE_ARTIFACT")
        if artifact:  # CI keeps the Perfetto-loadable file for inspection
            import shutil

            shutil.copyfile(trace_path, artifact)
        try:
            data = json.loads(pathlib.Path(trace_path).read_text())
        except json.JSONDecodeError as e:
            return f"trace is not valid JSON: {e}"
        events = data.get("traceEvents")
        if not isinstance(events, list) or not events:
            return "trace has no traceEvents"
        tracks = {e["args"]["name"] for e in events if e["ph"] == "M"}
        if not {"host leg", "device leg"} <= tracks:
            return f"missing track metadata: {tracks}"
        err = _check_nesting(events)
        if err:
            return err
        dev_ops = [e for e in events if e["ph"] == "B"
                   and e.get("cat") == "device"
                   and not e["name"].startswith("tick ")]
        if not dev_ops:
            return "no device-leg operator spans in the trace"
        framed = [e for e in events if e["ph"] == "B"
                  and "user_frame" in e.get("args", {})]
        if not any("streaming_etl.py" in e["args"]["user_frame"]
                   for e in framed):
            return "no span carries the example's user-frame attribution"
        print(f"trace gate OK: {len(events)} events, "
              f"{len(dev_ops)} device-leg spans, nesting valid")
        return None


def _etl_like_graph(n_rows: int, n_ticks: int):
    """The streaming example's shape as a batch feed: join against a
    dimension table + sliding-window aggregate, spread over many ticks."""
    import numpy as np

    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    G.clear()

    class Order(pw.Schema):
        item: str
        qty: int
        price: float
        ts: int

    class Category(pw.Schema):
        item: str
        category: str

    rng = np.random.default_rng(0)
    items = rng.integers(0, 16, size=n_rows)
    orders = table_from_rows(
        Order, [(f"i{items[i]}", 1 + int(i) % 3, 2.5, 60 * i,
                 (i * n_ticks) // n_rows * 2, 1) for i in range(n_rows)],
        is_stream=True)
    cats = table_from_rows(
        Category, [(f"i{i}", f"cat{i % 3}") for i in range(16)])
    enriched = orders.join(cats, orders.item == cats.item).select(
        orders.qty, orders.ts, cats.category,
        revenue=orders.qty * orders.price)
    by_cat = enriched.windowby(
        enriched.ts, window=pw.temporal.sliding(hop=60, duration=300),
        instance=enriched.category).reduce(
        category=pw.this._pw_instance,
        revenue=pw.reducers.sum(pw.this.revenue),
        n=pw.reducers.count())
    runner = GraphRunner()
    runner.capture(by_cat)
    return runner


def check_overhead(attempts: int = 3) -> str | None:
    """tracing disabled must add < 2% per-tick wall time.

    A wall-clock ratio on a shared CI runner can blip past the budget on
    correlated noise (frequency scaling, a noisy neighbor spanning all
    trials of one mode); a genuine regression fails every attempt, so the
    gate passes on the first attempt under budget and only reports the
    failure after ``attempts`` independent measurements all exceed it."""
    last = None
    for i in range(attempts):
        last = _measure_overhead()
        if last is None:
            return None
        print(f"overhead attempt {i + 1}/{attempts} over budget: {last}")
    return last


def _measure_overhead() -> str | None:
    from pathway_tpu.engine.flight_recorder import FlightRecorder
    from pathway_tpu.internals.parse_graph import G

    os.environ["PATHWAY_DEVICE_INFLIGHT"] = "1"  # no bridge-thread noise
    os.environ.pop("PATHWAY_TRACE_PATH", None)
    os.environ.pop("PATHWAY_FLIGHT_RECORDER", None)
    n_rows, n_ticks, trials = 4000, 120, 5

    def run_once(with_disabled_recorder: bool) -> float:
        runner = _etl_like_graph(n_rows, n_ticks)
        recorder = None
        if with_disabled_recorder:
            recorder = FlightRecorder()
            assert not recorder.enabled
        t0 = time.perf_counter()
        runner.run_batch(n_workers=1, recorder=recorder)
        dt = time.perf_counter() - t0
        G.clear()
        return dt

    run_once(False)  # warm caches/imports off the record
    run_once(True)
    # interleaved trials: thermal / allocator drift over the run must hit
    # both modes equally, or the guard measures the machine, not the hook
    base_ts, dis_ts = [], []
    for _ in range(trials):
        base_ts.append(run_once(False))
        dis_ts.append(run_once(True))
    base, disabled = min(base_ts), min(dis_ts)
    ratio = disabled / base
    print(f"overhead guard: baseline {base * 1e3:.1f}ms, "
          f"disabled-recorder {disabled * 1e3:.1f}ms over {n_ticks} ticks "
          f"(ratio {ratio:.4f})")
    if ratio > 1.02:
        return (f"tracing-disabled per-tick overhead {ratio:.4f}x "
                f"exceeds the 2% budget")
    return None


def main() -> int:
    for name, check in (("trace", check_trace_file),
                        ("overhead", check_overhead)):
        err = check()
        if err:
            print(f"FAIL [{name}]: {err}", file=sys.stderr)
            return 1
    print("OK: trace gate + overhead guard both hold")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
