"""Recovery gate: snapshot+suffix restarts must be byte-identical AND
bounded (engine/persistence.py operator-state snapshots + WAL compaction).

Drives ``examples/streaming_etl.py``'s real graph under persistence with
``PATHWAY_SNAPSHOT_EVERY_TICKS`` set and ``PATHWAY_DEVICE_INFLIGHT=4``
through a seeded kill/restart loop: each round trickles more order files
in, arms a RANDOM fault point — the PR-8 watermark boundaries PLUS the
PR-10 snapshot/compaction boundaries (``persistence.snapshot.write``,
``persistence.compact.truncate``, ``persistence.append.corrupt``) — and
lets the run crash (or go quiescent when the point never fires).

After the storm, a clean run over the same persistence root must:

1. produce a consolidated CSV **identical** to a synchronous
   (``PATHWAY_DEVICE_INFLIGHT=1``, no persistence) reference over the
   full input — exactly-once through snapshots, compaction, corruption
   and fallback;
2. have restored from an operator-state snapshot (generation >= 1);
3. show ``wal_replayable_entries`` MUCH smaller than the total ingested
   history — the compaction bound that makes restart time O(data), not
   O(stream age).

Exits 0 iff all hold. Run: ``python tests/recovery_canary.py``
(``RECOVERY_SEED`` reruns a specific storm).
"""

from __future__ import annotations

import csv
import json
import os
import pathlib
import random
import sys
import tempfile
import threading
import time

N_ROUNDS = 3
FILES_PER_ROUND = 3
ROWS_PER_FILE = 4
POINTS = ("bridge.leg.exec", "bridge.leg.resolved", "persistence.commit",
          "persistence.fsync", "persistence.snapshot.write",
          "persistence.compact.truncate")


def _write_round(orders: pathlib.Path, rnd: int) -> None:
    for f in range(FILES_PER_ROUND):
        base = rnd * FILES_PER_ROUND + f
        rows = [{"item": f"i{(base + i) % 4}", "qty": 1 + (base + i) % 3,
                 "price": 2.5 * (1 + (base + i) % 5),
                 "ts": 60 * (base * ROWS_PER_FILE + i)}
                for i in range(ROWS_PER_FILE)]
        (orders / f"{base:03d}.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows) + "\n")


def _write_cats(root: pathlib.Path) -> str:
    cats = root / "categories.csv"
    cats.write_text("item,category\n" + "\n".join(
        f"i{i},cat{i % 2}" for i in range(4)) + "\n")
    return str(cats)


def _consolidate_csv(path: str) -> list:
    if not os.path.exists(path):
        return []
    acc: dict[tuple, int] = {}
    with open(path) as f:
        reader = csv.reader(f)
        header = next(reader, None)
        if header is None:
            return []
        t_pos, d_pos = header.index("time"), header.index("diff")
        for r in reader:
            key = tuple(v for i, v in enumerate(r)
                        if i not in (t_pos, d_pos))
            acc[key] = acc.get(key, 0) + int(r[d_pos])
    return sorted(k for k, n in acc.items() for _ in range(n) if n > 0)


def _run(orders_dir: str, cats_csv: str, out_csv: str, *, inflight: int,
         pdir: str | None, max_s: float = 25.0):
    """One run attempt: build the real graph, run on a thread, wait for a
    crash or sink quiescence, stop. Returns (error, persistence_stats)."""
    os.environ["PATHWAY_DEVICE_INFLIGHT"] = str(inflight)
    import pathway_tpu as pw
    from examples.streaming_etl import build
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals.parse_graph import G

    G.clear()
    build(orders_dir, cats_csv, out_csv)
    cfg = None
    if pdir is not None:
        cfg = pw.persistence.Config.simple_config(
            pw.persistence.Backend.filesystem(pdir))
    err: list[BaseException] = []

    def _target():
        try:
            pw.run(persistence_config=cfg, terminate_on_error=True)
        except BaseException as e:  # noqa: BLE001 — the injected crash
            err.append(e)

    t = threading.Thread(target=_target, daemon=True)
    t.start()
    deadline = time.monotonic() + max_s
    rt = None
    while time.monotonic() < deadline and rt is None and t.is_alive():
        live = list(_streaming._ACTIVE_RUNTIMES)
        rt = live[0] if live else None
        time.sleep(0.05)
    last_size = -1
    while time.monotonic() < deadline and t.is_alive():
        size = os.path.getsize(out_csv) if os.path.exists(out_csv) else 0
        if size > 0 and size == last_size:
            break  # sink quiescent: the finite feed is fully ingested
        last_size = size
        time.sleep(0.3)
    _streaming.stop_all()
    t.join(20.0)
    assert not t.is_alive(), "runtime did not stop"
    pstats = rt.persistence.stats() \
        if rt is not None and rt.persistence is not None else None
    G.clear()
    return (err[0] if err else None), pstats


def main() -> int:
    seed = int(os.environ.get("RECOVERY_SEED", "5"))
    rng = random.Random(seed)
    from pathway_tpu.testing import faults

    # injected write failures must crash, not be retried away; snapshot
    # cadence keeps several generations landing inside a short storm
    os.environ["PATHWAY_PERSISTENCE_WRITE_RETRIES"] = "0"
    os.environ["PATHWAY_SNAPSHOT_EVERY_TICKS"] = "3"
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders = root / "orders"
        orders.mkdir()
        cats_csv = _write_cats(root)
        pdir = str(root / "pstate")

        crashes = 0
        for rnd in range(N_ROUNDS):
            _write_round(orders, rnd)
            point = rng.choice(POINTS)
            k = rng.randint(1, 8)
            faults.arm_point(point, faults.FailOnHit(k))
            try:
                err, _p = _run(
                    str(orders), cats_csv, str(root / f"out_{rnd}.csv"),
                    inflight=4, pdir=pdir)
            finally:
                faults.reset()
            if err is not None:
                if not isinstance(err, faults.InjectedFault):
                    print(f"FAIL: round {rnd} died of an UNINJECTED error: "
                          f"{type(err).__name__}: {err}", file=sys.stderr)
                    return 1
                crashes += 1
                print(f"round {rnd}: crashed at {point!r} hit {k} "
                      f"(as injected)")
            else:
                print(f"round {rnd}: {point!r} hit {k} never fired "
                      f"(quiescent run)")

        # one more round of files so the recovery run commits fresh rows
        _write_round(orders, N_ROUNDS)
        final_csv = str(root / "out_final.csv")
        err, pstats = _run(str(orders), cats_csv, final_csv,
                           inflight=4, pdir=pdir)
        if err is not None:
            print(f"FAIL: clean recovery run raised {type(err).__name__}: "
                  f"{err}", file=sys.stderr)
            return 1
        got = _consolidate_csv(final_csv)

        # synchronous no-persistence reference over the same full input
        err, _ = _run(str(orders), cats_csv, str(root / "out_sync.csv"),
                      inflight=1, pdir=None)
        if err is not None:
            print(f"FAIL: sync reference raised {type(err).__name__}: "
                  f"{err}", file=sys.stderr)
            return 1
        want = _consolidate_csv(str(root / "out_sync.csv"))
        if not want or got != want:
            print(f"FAIL: recovered CSV != synchronous CSV "
                  f"({len(got)} vs {len(want)} rows, seed {seed}, "
                  f"{crashes} crashes)", file=sys.stderr)
            for row in got[:5]:
                print(f"  got : {row}", file=sys.stderr)
            for row in want[:5]:
                print(f"  want: {row}", file=sys.stderr)
            return 1

        # tentpole properties: a snapshot generation exists, and the WAL
        # the NEXT restart would replay is much smaller than history
        total_rows = (N_ROUNDS + 1) * FILES_PER_ROUND * ROWS_PER_FILE
        if not pstats or pstats["snapshot_generation"] < 1:
            print(f"FAIL: no operator-state snapshot was ever written: "
                  f"{pstats}", file=sys.stderr)
            return 1
        if pstats["wal_replayable_entries"] > total_rows // 2:
            print(f"FAIL: WAL not compacted — "
                  f"{pstats['wal_replayable_entries']} replayable entries "
                  f"vs {total_rows} total history", file=sys.stderr)
            return 1
        print(f"OK: seed {seed}, {crashes}/{N_ROUNDS} rounds crashed; "
              f"recovered CSV identical to sync run ({len(got)} rows); "
              f"snapshot generation {pstats['snapshot_generation']} at "
              f"t={pstats['snapshot_tick']}; WAL replayable entries "
              f"{pstats['wal_replayable_entries']} of {total_rows} "
              f"ingested ({pstats['compactions_total']} compactions)")
        return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
