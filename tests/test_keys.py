"""Key derivation invariants (internals/keys.py).

The encoding is the cross-process sharding contract (blake2b-128 over a
canonical value encoding, reference: src/engine/value.rs HashInto) — the
fast exact-type dispatch, the slow isinstance chain, and the uncached
variant must all produce identical bytes for any value they share.
"""

import numpy as np

from pathway_tpu.internals.keys import (Pointer, _encode_value,
                                        _encode_value_slow, hash_values,
                                        hash_values_uncached)


CASES = [
    None, True, False, 0, 1, -5, 2**62, 2**70, -(2**70),
    1.5, 3.0, -0.0, float("nan"), float("inf"), float("-inf"),
    "", "abc", "naïve", b"", b"xy",
    (), (1, "a", (2.0, None)), Pointer(123), Pointer((1 << 127) + 5),
    np.int64(7), np.int32(-3), np.float32(2.5), np.float64(4.0),
    np.arange(6).reshape(2, 3), np.zeros(0, np.float32),
]


def test_fast_and_slow_encoders_agree():
    for v in CASES:
        fast: list = []
        slow: list = []
        _encode_value(v, fast)
        _encode_value_slow(v, slow)
        assert b"".join(fast) == b"".join(slow), v


def test_uncached_matches_cached():
    for v in CASES:
        assert hash_values_uncached("row", 3, v) == hash_values("row", 3, v)


def test_int_float_equal_values_share_keys():
    # reference HashInto: 3 and 3.0 hash identically; bools do NOT
    assert hash_values(3) == hash_values(3.0)
    assert hash_values(np.int64(3)) == hash_values(3)
    assert hash_values(True) != hash_values(1)
    assert hash_values(False) != hash_values(0)


def test_tuple_encoding_is_not_concatenation():
    # (("a",), "b") must differ from (("a", "b"),): lengths are framed
    assert hash_values(("a",), "b") != hash_values(("a", "b"))
