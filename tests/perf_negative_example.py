"""Deliberately device-undisciplined module — CI's inverted lint gate.

Never imported: tests/test_perf_check.py and the ``device-lint`` CI job
run ``check --perf`` over this file and require it to FAIL, proving the
PWT4xx analyzer still catches the seeded anti-patterns:

- PWT401: ``score_batch`` dispatches a jitted kernel with a raw
  ``len(rows)`` leading dim — a fresh XLA compile per batch length.
- PWT402: ``search`` casts/materializes device values per batch
  (``float()``, ``.tolist()``) — a device→host stall every query.
- PWT403: ``drain`` dispatches the kernel per row in a Python loop
  while a batched kernel exists in this module.
- PWT404: ``ingest`` feeds a numpy operand to a jitted kernel with no
  device residency — an implicit host→device transfer every tick.
- PWT405: ``make_score_table`` lets float64 reach kernel code.
- PWT406: ``apply_update`` reads a buffer after donating it.
- PWT407: ``search_jit`` is a jitted serving entry point absent from
  pw.warmup's bucket registry (checked with an explicit empty registry).
- PWT408: ``drain_tick`` does blocking host I/O on the device leg.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def kernel(x):
    return x * 2


@partial(jax.jit, donate_argnums=(0,))
def fused(buf, upd):
    return buf + upd


def kernel_batch(xs):
    return kernel(jnp.stack(xs))


def search(q):                              # PWT402 (x2)
    dev = jnp.asarray(q)
    r = kernel(dev)
    return float(r.sum()), r.tolist()


search_jit = jax.jit(search)                # PWT407


def score_batch(rows):                      # PWT401
    out = np.empty((len(rows), 4), np.float32)
    return kernel(out)


def drain(rows):                            # PWT403
    out = []
    for r in rows:
        out.append(kernel(r))
    return out


def ingest(rows):                           # PWT404
    padded = np.zeros((32, 4), np.float32)
    return kernel(padded)


def make_score_table(n):                    # PWT405
    return jnp.zeros((n, 4), dtype=np.float64)


def apply_update(buf, upd):                 # PWT406
    out = fused(buf, upd)
    return buf.sum()


def drain_tick(x):                          # PWT408
    print("tick", x)
    return kernel(x)
