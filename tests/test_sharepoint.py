"""SharePoint xpack connector against an in-test REST API double
(reference: xpacks/connectors/sharepoint — entitlement-gated office365
client there; the REST protocol itself here)."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote, urlparse

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.xpacks.connectors import sharepoint


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


class _FakeSharePoint(BaseHTTPRequestHandler):
    # folder url -> {"files": {name: (bytes, mtime)}, "folders": [urls]}
    tree: dict = {}

    def log_message(self, *args):
        pass

    def _json(self, payload, code=200):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.headers.get("Authorization") != "Bearer sp-tok":
            return self._json({"error": "unauthorized"}, 401)
        path = unquote(urlparse(self.path).path)
        if "GetFolderByServerRelativeUrl('" in path:
            folder = path.split("('", 1)[1].split("')", 1)[0]
            node = self.tree.get(folder)
            if node is None:
                return self._json({"error": "notFound"}, 404)
            if path.endswith("/Files"):
                results = [
                    {"Name": n, "ServerRelativeUrl": f"{folder}/{n}",
                     "Length": str(len(data)), "TimeCreated": "t0",
                     "TimeLastModified": mtime}
                    for n, (data, mtime) in node["files"].items()]
                return self._json({"d": {"results": results}})
            if path.endswith("/Folders"):
                results = [{"Name": f.rsplit("/", 1)[-1],
                            "ServerRelativeUrl": f}
                           for f in node["folders"]]
                return self._json({"d": {"results": results}})
        if "GetFileByServerRelativeUrl('" in path and path.endswith("$value"):
            furl = path.split("('", 1)[1].split("')", 1)[0]
            folder, _, name = furl.rpartition("/")
            node = self.tree.get(folder)
            if node is None or name not in node["files"]:
                return self._json({"error": "notFound"}, 404)
            data = node["files"][name][0]
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        self._json({"error": "bad request"}, 400)


@pytest.fixture()
def fake_sp():
    _FakeSharePoint.tree = {
        "/sites/MySite/Docs": {
            "files": {"a.txt": (b"alpha", "m1"),
                      "big.bin": (b"x" * 100, "m1")},
            "folders": ["/sites/MySite/Docs/Sub"],
        },
        "/sites/MySite/Docs/Sub": {
            "files": {"b.txt": (b"beta", "m1")},
            "folders": [],
        },
    }
    server = ThreadingHTTPServer(("127.0.0.1", 0), _FakeSharePoint)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}/sites/MySite"
    server.shutdown()


def test_sharepoint_static_recursive_and_size_limit(fake_sp):
    t = sharepoint.read(fake_sp, root_path="/sites/MySite/Docs",
                        mode="static", access_token="sp-tok",
                        with_metadata=True, object_size_limit=50)
    rows = pw.debug.table_to_pandas(t).to_dict("records")
    assert sorted(r["data"] for r in rows) == [b"alpha", b"beta"]
    metas = {r["_metadata"].value["name"] for r in rows}
    assert metas == {"a.txt", "b.txt"}  # big.bin filtered by size


def test_sharepoint_streaming_update(fake_sp):
    t = sharepoint.read(fake_sp, root_path="/sites/MySite/Docs",
                        mode="streaming", access_token="sp-tok",
                        refresh_interval=0, autocommit_duration_ms=20)
    seen = []
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition:
                    seen.append((row["data"], is_addition)))

    def mutate():
        time.sleep(0.4)
        _FakeSharePoint.tree["/sites/MySite/Docs"]["files"]["a.txt"] = \
            (b"alpha-v2", "m2")

    threading.Thread(target=mutate, daemon=True).start()
    threading.Thread(target=lambda: pw.run(), daemon=True).start()
    want = {(b"alpha", True), (b"alpha", False), (b"alpha-v2", True)}
    deadline = time.time() + 12
    while time.time() < deadline and not want <= set(seen):
        time.sleep(0.1)
    assert want <= set(seen)


def test_sharepoint_cert_flow_gated():
    with pytest.raises((ImportError, ValueError, OSError),
                       match="msal|access_token|nonexistent"):
        sharepoint.read("https://x.sharepoint.com/sites/S",
                        tenant="t", client_id="c",
                        cert_path="/nonexistent.pem", thumbprint="tp",
                        root_path="/sites/S/Docs", mode="static")
