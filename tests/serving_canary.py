"""Serving-latency canary: the request-scoped SLO path, proven end to end
(same pattern as pipelining_canary.py / trace_canary.py). Three gates:

1. **streaming_etl + rest_connector** — mount a scoring route (the
   example's own ``demand_score`` device UDF) next to
   ``examples/streaming_etl.py``'s real graph, keep the order feed
   ingesting WHILE queries run, and assert every completed request span
   carries a full, positive stage decomposition that sums to its e2e
   total, with the new metric families live on ``/metrics`` and the
   serving snapshot on ``/status``.

2. **sanitized serving** — warm a paged text index under
   ``PATHWAY_DEVICE_SANITIZER=1`` (engine/device_sanitizer.py), then
   serve queries in steady state and gate ZERO post-warmup compiles and
   zero implicit host→device transfers (any violation raises).

3. **bench serving leg** — run ``bench.py`` with only the ``serving``
   leg enabled (CPU-sized slab) and assert ``knn_p50_e2e_ms`` and every
   ``serving_stage_*_p50_ms`` field is present and positive in the bench
   JSON, and that ``BENCH_LASTGOOD.json`` captured the same numbers
   (values are REPORTED, not thresholded — CPU runners don't meet the
   20 ms target).

Exits 0 iff both hold. Run: ``python tests/serving_canary.py``.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

STAGE_FIELDS = ("ingress_wait", "admission_wait", "queue", "host",
                "device", "response_write")


def gate_streaming_etl() -> str | None:
    os.environ["PATHWAY_DEVICE_INFLIGHT"] = "2"
    os.environ["PATHWAY_MONITORING_HTTP_PORT"] = "0"  # ephemeral
    from tests.pipelining_canary import _write_feed

    import pathway_tpu as pw
    from examples.streaming_etl import build, demand_score
    from pathway_tpu.engine import streaming as _streaming
    from pathway_tpu.internals import schema as sch
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.io.http import PathwayWebserver, rest_connector

    G.clear()
    with tempfile.TemporaryDirectory() as td:
        root = pathlib.Path(td)
        orders_dir, cats_csv = _write_feed(root)
        build(orders_dir, cats_csv, str(root / "out.csv"))
        ws = PathwayWebserver(host="127.0.0.1", port=0)
        qschema = sch.schema_from_types(qty=int, price=float)
        queries, writer = rest_connector(
            webserver=ws, route="/score", schema=qschema,
            methods=("POST",), delete_completed_queries=True,
            autocommit_duration_ms=10)
        writer(queries.select(
            score=demand_score(queries.qty, queries.price)))

        errors: list[BaseException] = []

        def _run():
            try:
                # with_http_server auto-enables the flight recorder (and
                # with it the request tracker) — the canary rides the
                # production wiring, no explicit env needed
                pw.run(with_http_server=True)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        th = threading.Thread(target=_run, daemon=True)
        th.start()
        stop_feed = threading.Event()

        def _keep_ingesting():
            # live ingest: new order files land while queries are served
            i = 0
            while not stop_feed.is_set():
                rows = [{"item": f"i{j % 4}", "qty": 1 + j % 3,
                         "price": 2.5, "ts": 6000 + 60 * (i * 8 + j)}
                        for j in range(8)]
                (pathlib.Path(orders_dir) / f"more_{i}.jsonl").write_text(
                    "\n".join(json.dumps(r) for r in rows) + "\n")
                i += 1
                stop_feed.wait(0.2)

        feeder = threading.Thread(target=_keep_ingesting, daemon=True)
        try:
            deadline = time.monotonic() + 60.0
            rt = None
            while time.monotonic() < deadline and rt is None:
                live = list(_streaming._ACTIVE_RUNTIMES)
                if live and ws._started.is_set() and ws.port:
                    rt = live[0]
                if errors:
                    return f"pipeline failed at startup: {errors[0]!r}"
                time.sleep(0.05)
            if rt is None:
                return "runtime never started"
            feeder.start()
            rids = set()
            for i in range(6):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{ws.port}/score",
                    data=json.dumps({"qty": 2 + i, "price": 3.5}).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                    rids.add(resp.headers.get("X-Pathway-Request-Id"))
            if len(rids) != 6 or None in rids:
                return f"request ids not unique/present: {rids}"
            tracker = rt.recorder.requests if rt.recorder else None
            if tracker is None:
                return "request tracker not armed under with_http_server"
            spans = tracker.trace_spans()
            if len(spans) < 6:
                return f"expected >= 6 completed spans, got {len(spans)}"
            for rec in spans[-6:]:
                stages = rec["stages"]
                if set(stages) != set(STAGE_FIELDS):
                    return f"stage set mismatch: {sorted(stages)}"
                if any(v < 0.0 for v in stages.values()):
                    return f"negative stage in {rec}"
                if abs(sum(stages.values()) - rec["e2e_ms"]) > 0.05:
                    return (f"stages do not sum to e2e: {stages} vs "
                            f"{rec['e2e_ms']}")
                # queue (commit-tick wait) and response write must have
                # genuinely elapsed; compute lives in host+device
                if stages["queue"] <= 0.0 or \
                        stages["response_write"] <= 0.0 or \
                        stages["host"] + stages["device"] <= 0.0:
                    return f"implausible decomposition: {stages}"
            mport = rt.http_server.port
            metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=10
            ).read().decode()
            for fam in ("pathway_tpu_query_e2e_latency_ms",
                        "pathway_tpu_query_stage_ms",
                        "pathway_tpu_slo_burn_rate",
                        "pathway_tpu_query_slo_violations"):
                if fam not in metrics:
                    return f"/metrics missing family {fam}"
            status = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/status", timeout=10).read())
            if status.get("serving", {}).get("requests", 0) < 6:
                return f"/status.serving incomplete: {status.get('serving')}"
            if "slow_queries" not in status:
                return "/status.slow_queries missing"
            print(f"etl serving gate OK: {len(spans)} spans, e2e p50 "
                  f"{status['serving']['e2e_ms']['p50']:.1f}ms, stages "
                  f"{status['serving'].get('stages')}")
            return None
        finally:
            stop_feed.set()
            _streaming.stop_all()
            th.join(15.0)
            G.clear()


def gate_sanitized_serving() -> str | None:
    """Device-discipline gate (PWT4xx's runtime twin): the warmed text
    serving path — packed encode + paged multi-extent search — completes
    under ``PATHWAY_DEVICE_SANITIZER=1`` with ZERO post-warmup compiles
    and zero implicit host→device transfers. Any violation raises, so
    this gate fails loudly the day a dispatch shape drifts off the
    warmed ladder."""
    os.environ["PATHWAY_DEVICE_SANITIZER"] = "1"
    try:
        import jax

        import pathway_tpu as pw
        from pathway_tpu.engine import device_sanitizer as ds
        from pathway_tpu.internals.keys import Pointer
        from pathway_tpu.models.encoder import EncoderConfig, init_params
        from pathway_tpu.ops.knn import (BruteForceKnnIndex,
                                         DeviceEmbeddingKnnIndex,
                                         KnnMetric)
        from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

        cfg = EncoderConfig.tiny(max_len=64)
        emb = JaxEncoderEmbedder(
            config=cfg, params=init_params(jax.random.PRNGKey(0), cfg),
            max_len=64, max_batch_size=1)
        idx = DeviceEmbeddingKnnIndex(
            emb, BruteForceKnnIndex(cfg.hidden, metric=KnnMetric.COS,
                                    paged=True, page_rows=128))
        # population is pre-steady-state work: compiles here are warmup
        texts = [f"document number {i} with content {i * 7}"
                 for i in range(300)]  # 3 extents at page_rows=128
        idx.add_batch([Pointer(i) for i in range(300)], texts)
        idx.drain()
        pw.warmup(emb, index=idx, ks=(3,), cache=False)
        if not ds.in_steady_state():
            return "pw.warmup did not declare steady state"
        if ds.warmup_compiles() == 0:
            return "no compiles landed in the warmup window"
        # steady-state serving: every query must reuse warmed executables
        # (a violation raises DeviceDisciplineViolation out of this loop)
        for i in range(8):
            res = idx.search(
                [(Pointer(10 ** 6 + i), texts[17 + i], 3, None)])
            if Pointer(17 + i) not in [k for k, _ in res[0]]:
                return f"query {i} returned {res[0]}"
        if ds.post_warmup_compiles() != 0:
            return (f"{ds.post_warmup_compiles()} post-warmup compile(s): "
                    f"{ds.violations()}")
        if ds.violations():
            return f"violations recorded: {ds.violations()}"
        print(f"sanitized serving gate OK: {ds.warmup_compiles()} warmup "
              "compiles, 0 post-warmup, 8 queries served under the "
              "transfer guard")
        return None
    finally:
        os.environ.pop("PATHWAY_DEVICE_SANITIZER", None)


def gate_bench_serving() -> str | None:
    repo = pathlib.Path(__file__).resolve().parent.parent
    with tempfile.TemporaryDirectory() as td:
        lastgood = pathlib.Path(td) / "BENCH_LASTGOOD.json"
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            BENCH_SKIP="etl,embed,framework,knn",
            BENCH_SERVING_N="2000", BENCH_SERVING_QUERIES="12",
            BENCH_SERVING_WARMUP="4", BENCH_PROBE_TRIES="1",
            BENCH_LASTGOOD_PATH=str(lastgood))
        # the bench child re-warms mid-run with engine-driven (unpinned)
        # batch shapes — its compile/transfer-count COLUMNS watch that
        # leg; the sanitizer's raise-on-compile contract is gated by
        # gate_sanitized_serving above, on the pinned-shape path
        env.pop("PATHWAY_DEVICE_SANITIZER", None)
        proc = subprocess.run(
            [sys.executable, str(repo / "bench.py")], env=env, cwd=repo,
            capture_output=True, text=True, timeout=540)
        last = None
        for ln in reversed((proc.stdout or "").splitlines()):
            if ln.strip().startswith("{"):
                try:
                    last = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue
        if last is None:
            tail = (proc.stderr or "").strip().splitlines()[-5:]
            return f"bench emitted no JSON (rc={proc.returncode}): {tail}"
        required = ["knn_p50_e2e_ms", "knn_p95_e2e_ms", "knn_p99_e2e_ms",
                    "serving_n_queries"] + \
                   [f"serving_stage_{s}_p50_ms" for s in STAGE_FIELDS]
        for field in required:
            if field not in last:
                return f"bench JSON missing {field}: {sorted(last)}"
            # admission_wait is legitimately ~0 when QoS is off (the
            # stamp sits flush against the enqueue); every other stage
            # must have genuinely elapsed
            if field == "serving_stage_admission_wait_p50_ms":
                if last[field] < 0:
                    return f"bench JSON field {field} negative: {last[field]}"
            elif not last[field] > 0:
                return f"bench JSON field {field} not positive: {last[field]}"
        if not lastgood.exists():
            return "BENCH_LASTGOOD.json was not written"
        good = json.loads(lastgood.read_text())["result"]
        if good.get("knn_p50_e2e_ms") != last["knn_p50_e2e_ms"]:
            return f"lastgood diverged from bench JSON: {good}"
        print("bench serving gate OK: knn_p50_e2e_ms="
              f"{last['knn_p50_e2e_ms']}ms (reported, not thresholded); "
              "stages " + ", ".join(
                  f"{s}={last[f'serving_stage_{s}_p50_ms']}ms"
                  for s in STAGE_FIELDS))
        return None


def main() -> int:
    for name, gate in (("streaming-etl", gate_streaming_etl),
                       ("sanitized-serving", gate_sanitized_serving),
                       ("bench-serving", gate_bench_serving)):
        err = gate()
        if err:
            print(f"FAIL [{name}]: {err}", file=sys.stderr)
            return 1
    print("OK: serving-latency canary holds")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
