"""Write-path high availability: epoch fencing, replica promotion and
router election (engine/persistence.py, engine/streaming.py,
engine/router.py).

Covers the PR's pinned contracts:

* **epoch fencing** — the root carries a monotone fencing epoch in an
  fsynced manifest; a writer whose epoch the root moved past raises
  ``FencedPrimaryError`` BY NAME (naming both epochs) before any byte
  lands; a crash inside the epoch claim leaves the previous manifest
  readable; WAL records stamp the writer's epoch and recovery truncates
  at an epoch REGRESSION (a fenced zombie's write that raced the check);
* **promotion** — ``PersistenceDriver.promote`` drops the dead primary's
  torn final commit (records past the last complete tick), bumps the
  epoch at least to the router's election hint, and never reuses a torn
  tick number; runtime-level promotion is idempotent (a duplicate
  promote frame is a no-op);
* **router election** — write paths route to the primary only and 503
  with an honest ``Retry-After`` during an election; primary death
  (control EOF or heartbeat staleness) elects the most-caught-up
  replica; a candidate dying mid-promotion re-elects the next survivor;
  the first primary-role heartbeat completes the election and re-anchors
  surviving replicas on the promoted timeline;
* **control partition** — the ``router.control.partition`` fault point
  silently drops frames in both directions (the staleness detector, not
  EOF, must notice);
* **durable acks** — ``rest_connector(durable_ack=True)`` parks each
  response until the commit watermark covers its tick, drops waiterless
  rows (a replica applying the tailed write stream), and refuses to run
  without a persistence root.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.multiproc import (control_authkey, hmac_handshake,
                                          recv_control_frame,
                                          send_control_frame)
from pathway_tpu.engine.persistence import (FencedPrimaryError,
                                            PersistenceDriver, SnapshotLog,
                                            record_epoch)
from pathway_tpu.engine.router import QueryRouter
from pathway_tpu.internals import dtype as dt  # noqa: F401 — schema idiom
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.http import PathwayWebserver, RestSource, rest_connector
from pathway_tpu.testing import faults


@pytest.fixture(autouse=True)
def fresh_state():
    G.clear()
    faults.reset()
    yield
    G.clear()
    faults.reset()
    from pathway_tpu.engine import streaming as _streaming

    _streaming.stop_all()


def _fs_config(root):
    return pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(str(root)))


def _row(k):
    return (f"k{k}", ("row",), 1, None)


# ---------------------------------------------------------------------------
# epoch fencing (persistence)
# ---------------------------------------------------------------------------

def test_stale_writer_fenced_by_name(tmp_path):
    """The split-brain gate in miniature: writer A holds epoch 0, a
    promotion claims epoch 1 on the same root, and A's next commit
    raises FencedPrimaryError naming BOTH epochs — before appending."""
    a = PersistenceDriver(_fs_config(tmp_path))
    log = a._log_for("src")
    log.append(1, [_row(1)])
    log.close()
    assert a.fencing_epoch == 0

    b = PersistenceDriver(_fs_config(tmp_path))
    assert b.claim_epoch("rescuer") == 1
    with pytest.raises(FencedPrimaryError) as ei:
        a.commit(2)
    assert ei.value.held_epoch == 0 and ei.value.root_epoch == 1
    assert "epoch 0" in str(ei.value) and "epoch 1" in str(ei.value)
    assert a.fenced_writes == 1
    with pytest.raises(FencedPrimaryError):
        a.write_snapshot(2, {"nodes": {}})
    assert a.fenced_writes == 2
    # the WAL is untouched by the fenced attempts
    assert [t for t, _ in a._log_for("src").read_all()] == [1]
    # the NEW holder commits freely
    b.commit(2)
    assert b.fenced_writes == 0


def test_epoch_adopted_at_open_and_env_override(tmp_path, monkeypatch):
    """A writable driver ADOPTS the root's existing epoch at open (a
    restart of the promoted primary is not a zombie), and
    PATHWAY_FLEET_EPOCH_PATH relocates the manifest."""
    d1 = PersistenceDriver(_fs_config(tmp_path))
    d1.claim_epoch("p1")
    d1.claim_epoch("p1")
    d2 = PersistenceDriver(_fs_config(tmp_path))
    assert d2.fencing_epoch == 2
    d2.commit(1)  # adopted epoch: not fenced
    # manifest override: a fresh root reads epoch 0 until the override
    # path carries one, then every driver on that root sees it
    alt = tmp_path / "elsewhere" / "fleet-epoch.json"
    alt.parent.mkdir()
    monkeypatch.setenv("PATHWAY_FLEET_EPOCH_PATH", str(alt))
    d3 = PersistenceDriver(_fs_config(tmp_path / "other-root"))
    assert d3.epoch_path() == str(alt)
    assert d3.fencing_epoch == 0
    assert d3.claim_epoch("p3", min_epoch=7) == 7
    assert alt.exists()
    assert json.loads(alt.read_text())["holder"] == "p3"


def test_epoch_claim_crash_leaves_previous_manifest(tmp_path):
    """A candidate dying INSIDE claim_epoch (fault points
    ``persistence.epoch.claim`` and ``persistence.atomic.replace``)
    leaves the previous epoch manifest intact and readable — a torn
    claim never bricks or regresses the root."""
    d = PersistenceDriver(_fs_config(tmp_path))
    d.claim_epoch("p")
    assert d.read_epoch() == 1
    for point in ("persistence.epoch.claim", "persistence.atomic.replace"):
        with faults.arm(point, faults.FailNTimes(1)):
            with pytest.raises(faults.InjectedFault):
                d.claim_epoch("crasher")
        assert d.read_epoch() == 1, point
        # the driver did not adopt the unclaimed epoch either
        assert d.fencing_epoch == 1, point
        d.commit(1)  # still the holder: not fenced
    # the next (healthy) claim proceeds from the surviving manifest
    assert d.claim_epoch("rescuer") == 2


def test_wal_stamps_epoch_and_truncates_regression(tmp_path):
    """Records carry the writer's fencing epoch (only when nonzero —
    pre-failover logs stay byte-identical) and recovery truncates at an
    epoch REGRESSION: a fenced zombie's append that raced the check must
    not splice a second timeline behind the promoted primary's."""
    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [_row(1)])               # epoch 0: legacy 2-tuple
    log.append(2, [_row(2)], epoch=3)
    log.append(3, [_row(3)], epoch=3)
    log.close()
    recs = SnapshotLog(path).read_all()
    assert [record_epoch(r) for r in recs] == [0, 3, 3]
    # a zombie (epoch 1 < 3) appends after the promoted primary: the
    # scan truncates at the regression, keeping the single timeline
    zombie = SnapshotLog(path)
    zombie.append(4, [_row(4)], epoch=1)
    zombie.append(5, [_row(5)], epoch=3)   # even later good data is cut
    zombie.close()
    recs = SnapshotLog(path).read_all()
    assert [r[0] for r in recs] == [1, 2, 3]


def test_promote_drops_torn_suffix_and_bumps_epoch(tmp_path):
    """Driver-level promotion: the dead primary's final commit landed in
    log A but not log B (death mid-commit). Promotion at the last
    COMPLETE tick truncates the torn suffix from every log, claims at
    least the router's epoch hint, and returns the pre-cut max tick so
    the torn tick number is never reused."""
    p = PersistenceDriver(_fs_config(tmp_path))
    la, lb = p._log_for("a"), p._log_for("b")
    for t in (1, 2, 3):
        la.append(t, [_row(t)])
        lb.append(t, [_row(t)])
    la.append(4, [_row(4)])  # the torn tick: present in a, absent in b
    la.close()
    lb.close()

    r = PersistenceDriver(_fs_config(tmp_path), read_only=True)
    max_tick, epoch = r.promote("r1", complete_tick=3, min_epoch=5)
    assert (max_tick, epoch) == (4, 5)
    assert not r.read_only and r.fencing_epoch == 5
    assert [t for t, _ in r._log_for("a").read_all()] == [1, 2, 3]
    assert [t for t, _ in r._log_for("b").read_all()] == [1, 2, 3]
    # the fenced ex-primary can no longer write
    with pytest.raises(FencedPrimaryError):
        p.commit(5)
    # the root stays loadable as ONE timeline for the next hydration
    fresh = PersistenceDriver(_fs_config(tmp_path), read_only=True)
    assert fresh.restore_time() == 3


# ---------------------------------------------------------------------------
# control-plane partition fault
# ---------------------------------------------------------------------------

def test_control_partition_drops_frames_both_directions():
    a, b = socket.socketpair()
    try:
        with faults.arm("router.control.partition", faults.FailNTimes(2)):
            # send direction: the frame is dropped on the floor (0 bytes)
            assert send_control_frame(a, "hb", {"n": 1}) == 0
            # recv direction: the frame crosses the wire but the reader
            # discards it and keeps waiting for the NEXT one
            faults.reset()
            send_control_frame(a, "hb", {"n": 2})
            send_control_frame(a, "hb", {"n": 3})
            faults.arm_point("router.control.partition",
                             faults.FailNTimes(1))
            tag, payload = recv_control_frame(b)
        assert (tag, payload["n"]) == ("hb", 3)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# router election (socket-level, no real replicas)
# ---------------------------------------------------------------------------

class _FakeServingHTTP:
    """Minimal serving stand-in answering every POST with its name."""

    def __init__(self, name: str):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                body = json.dumps({"served_by": outer.name}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.name = name
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _join(router, rid, port, *, role="replica", applied_tick=7,
          fleet_epoch=0) -> socket.socket:
    """Speak the real control protocol: handshake, hello, one heartbeat;
    wait until the router registered the endpoint."""
    sock = socket.create_connection(("127.0.0.1", router.control_port),
                                    timeout=5)
    hmac_handshake(sock, control_authkey(), time.monotonic() + 5)
    sock.settimeout(None)
    send_control_frame(sock, "hello", {"replica": rid, "role": role,
                                       "host": "127.0.0.1", "port": port})
    send_control_frame(sock, "hb", {"replica": rid, "role": role,
                                    "applied_tick": applied_tick,
                                    "primary_watermark": applied_tick,
                                    "staleness_ticks": 0,
                                    "fleet_epoch": fleet_epoch})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        eps = {e.replica_id: e for e in router.endpoints()}
        if rid in eps and eps[rid].applied_tick == applied_tick:
            return sock
        time.sleep(0.02)
    raise TimeoutError(f"router never registered {rid}")


def _post(port, path, body=b"{}", timeout=15):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _wait(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise TimeoutError(f"{what} never held")


def test_router_election_write_503_promote_and_reanchor(monkeypatch):
    """The full orchestration arc at socket level: writes route to the
    primary only; its death elects the most-caught-up replica (promote
    frame with a strictly-higher epoch); the election window 503s writes
    with an honest Retry-After; the candidate's first primary-role
    heartbeat completes the election, re-anchors the OTHER replica on
    the promotion tick, and restores the write path — all pinned on the
    router's /metrics and /status surfaces."""
    monkeypatch.setenv("PATHWAY_ROUTER_ELECTION_TIMEOUT_MS", "60000")
    router = QueryRouter(write_paths=("/w",))
    router.start()
    primary_http = _FakeServingHTTP("p0")
    rescue_http = _FakeServingHTTP("r2")
    socks = []
    try:
        socks.append(_join(router, "p0", primary_http.port,
                           role="primary", applied_tick=9))
        r1_sock = _join(router, "r1", 1, applied_tick=5)
        r2_sock = _join(router, "r2", rescue_http.port, applied_tick=9)
        socks += [r1_sock, r2_sock]
        assert router.is_write_path("/w?x=1")
        assert not router.is_write_path("/q")
        # healthy write path: primary serves, reads go to replicas
        status, body, _h = _post(router.port, "/w")
        assert (status, body["served_by"]) == (200, "p0")
        assert router._write_primary_id == "p0"

        # primary dies (control EOF): election opens, the promote frame
        # goes to the most-caught-up replica (r2, tick 9 > r1's 5) with
        # an epoch strictly above everything the fleet reported
        socks[0].close()
        tag, payload = recv_control_frame(r2_sock)
        assert tag == "promote"
        assert payload["epoch"] == 1 and payload["dead"] == "p0"
        assert router._election is not None

        # the election window: writes 503 with an honest Retry-After,
        # reads keep flowing over the surviving replicas
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/w")
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert "elect" in ei.value.read().decode()
        status, body, _h = _post(router.port, "/q")
        assert status == 200

        # the promoted candidate heartbeats role=primary: election done
        send_control_frame(r2_sock, "hb", {
            "replica": "r2", "role": "primary", "applied_tick": 9,
            "primary_watermark": 9, "fleet_epoch": 1,
            "promotion_tick": 9})
        _wait(lambda: router.promotions_total == 1, what="election end")
        assert router._election is None
        assert router._write_primary_id == "r2"
        assert router.fleet_epoch == 1
        assert router.failover_seconds is not None
        # the surviving replica is re-anchored on the promoted timeline
        tag, payload = recv_control_frame(r1_sock)
        assert (tag, payload) == ("reanchor", {"epoch": 1, "tick": 9})
        # the write path is back, served by the NEW primary
        status, body, _h = _post(router.port, "/w")
        assert (status, body["served_by"]) == (200, "r2")

        # observability pins: the failover metric family trio + status
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/metrics",
            timeout=10).read().decode()
        assert "pathway_tpu_fleet_epoch 1" in metrics
        assert "pathway_tpu_promotions_total 1" in metrics
        assert "pathway_tpu_failover_seconds" in metrics
        status_doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/status", timeout=10).read())
        assert status_doc["write_primary"] == "r2"
        assert status_doc["promotions"] == 1
        assert status_doc["election"] is None
        fleet_doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/fleet/status",
            timeout=10).read())
        assert fleet_doc["fleet_epoch"] == 1
        assert fleet_doc["electing"] is False
    finally:
        for s in socks[1:]:
            s.close()
        primary_http.stop()
        rescue_http.stop()
        router.stop()


def test_router_reelects_when_candidate_dies(monkeypatch):
    """Crash-mid-promotion: the elected candidate dies before its first
    primary heartbeat — the election babysitter elects the next survivor
    (same election, same epoch floor)."""
    monkeypatch.setenv("PATHWAY_ROUTER_ELECTION_TIMEOUT_MS", "300")
    router = QueryRouter(write_paths=("/w",))
    router.start()
    socks = []
    try:
        p_sock = _join(router, "p0", 1, role="primary", applied_tick=9)
        r1_sock = _join(router, "r1", 1, applied_tick=9)
        r2_sock = _join(router, "r2", 1, applied_tick=3)
        socks += [r1_sock, r2_sock]
        p_sock.close()
        tag, payload = recv_control_frame(r1_sock)
        assert tag == "promote" and payload["epoch"] == 1
        # the candidate crashes mid-promotion (control EOF, never
        # heartbeated as primary): the next survivor gets the frame
        r1_sock.close()
        r2_sock.settimeout(10)
        tag, payload = recv_control_frame(r2_sock)
        assert tag == "promote" and payload["epoch"] == 1
        # the frame hits the socket before _elect records the target
        _wait(lambda: (router._election or {}).get("target") == "r2",
              what="election target switch to r2")
    finally:
        for s in socks[1:]:
            try:
                s.close()
            except OSError:
                pass
        router.stop()


def test_router_staleness_declares_silent_primary_dead(monkeypatch):
    """A SIGSTOPped/partitioned primary keeps its socket open but goes
    silent: the heartbeat-staleness detector (not EOF) must open the
    election. With no candidates the election stays open and writes 503
    honestly."""
    monkeypatch.setenv("PATHWAY_ROUTER_ELECTION_TIMEOUT_MS", "250")
    router = QueryRouter(write_paths=("/w",))
    router.start()
    try:
        p_sock = _join(router, "p0", 1, role="primary", applied_tick=9)
        assert router._write_primary_id == "p0"
        # the zombie goes silent (no heartbeats, socket alive)
        _wait(lambda: router._election is not None, timeout=15,
              what="staleness death declaration")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/w")
        assert ei.value.code == 503
        assert "Retry-After" in ei.value.headers
        p_sock.close()
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# durable acknowledgements (io/http)
# ---------------------------------------------------------------------------

def test_rest_source_durable_ack_released_by_watermark():
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    src = RestSource(ws, "/w", ("POST",),
                     sch.schema_from_types(a=int),
                     delete_completed_queries=False, durable_ack=True)
    # a durable-ack route is primary state: replicas must TAIL it
    assert src.replica_serve_live is False
    loop = asyncio.new_event_loop()
    try:
        key = Pointer(1)
        event = asyncio.Event()
        slot: list = [None]
        src.pending[key] = (loop, event, slot)
        src.buffer_ack(3, key, {"ok": 1})
        # waiterless row: a REPLICA applying the primary's tailed write
        # stream computes responses too — dropped, never leaked
        src.buffer_ack(3, Pointer(2), {"ok": 2})
        assert [len(v) for v in src._unacked.values()] == [1]
        src.on_commit_watermark(2)  # WAL does not cover tick 3 yet
        assert slot[0] is None and key in src.pending
        src.on_commit_watermark(3)  # durable: the ack is released
        assert slot[0] == {"ok": 1}
        assert key not in src.pending and not src._unacked
    finally:
        loop.close()


def test_durable_ack_requires_persistence_root():
    """A 200 from a durable-ack route PROMISES the write is fsynced;
    without a WAL the promise is a lie — refused at runtime init."""
    ws = PathwayWebserver(host="127.0.0.1", port=0)
    table, writer = rest_connector(
        webserver=ws, route="/w",
        schema=sch.schema_from_types(a=int), methods=("POST",),
        persistent_id="writes", durable_ack=True)
    writer(table.select(ok=table.a))
    with pytest.raises(ValueError, match="durable_ack"):
        pw.run()
