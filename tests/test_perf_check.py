"""Device-discipline analyzer (static_check/perf_check.py): one
true-positive and one true-negative per PWT401–PWT408 code, the waiver
mechanism, the warmup-registry parser, the jit/hot-unit inventory, the
four-directory dogfood gate, the PWT105→PWT402 deference, and the CLI
front doors (``--perf``, ``--all`` bit 16) — mirrors
tests/test_durability_check.py for the PWT3xx family."""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
import textwrap

from pathway_tpu.internals.static_check import (check_perf, perf_inventory,
                                                scan_waivers)
from pathway_tpu.internals.static_check.perf_check import \
    load_warmup_registry
from pathway_tpu.internals.trace import Trace


def run_check(tmp_path, source: str, registry=None):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent(source))
    return check_perf([str(f)], warmup_registry=registry)


def codes(diags):
    return sorted(d.code for d in diags)


def only(diags, code):
    return [d for d in diags if d.code == code]


# ---------------------------------------------------------------------------
# PWT401 — unbucketed data-dependent jit dispatch
# ---------------------------------------------------------------------------

def test_pwt401_data_dependent_dispatch_is_error(tmp_path):
    diags = only(run_check(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x * 2

        def score_batch(rows):
            out = np.empty((len(rows), 4), np.float32)
            return kernel(out)
    """), "PWT401")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "data-dependent shape" in diags[0].message
    assert "bucket" in diags[0].message


def test_pwt401_negative_bucketing_evidence(tmp_path):
    diags = run_check(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x * 2

        def _round_up_pow2(n):
            return 1 << (n - 1).bit_length()

        def score_batch(rows):
            n = _round_up_pow2(len(rows))
            out = np.empty((n, 4), np.float32)
            return kernel(out)
    """)
    assert only(diags, "PWT401") == []


def test_pwt401_negative_cold_function(tmp_path):
    # shape zoo during construction is warmup's problem, not a tick's
    diags = run_check(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x * 2

        def rebuild_index(rows):
            out = np.empty((len(rows), 4), np.float32)
            return kernel(out)
    """)
    assert only(diags, "PWT401") == []


# ---------------------------------------------------------------------------
# PWT402 — host-device sync point on a per-batch path
# ---------------------------------------------------------------------------

def test_pwt402_tolist_and_cast_on_device_value(tmp_path):
    diags = only(run_check(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return x * 2

        def search(q):
            dev = jnp.asarray(q)
            r = kernel(dev)
            top = float(r.sum())
            return top, r.tolist()
    """), "PWT402")
    assert len(diags) == 2
    assert all(d.is_error for d in diags)
    msgs = " ".join(d.message for d in diags)
    assert ".tolist()" in msgs
    assert "Python float" in msgs  # the cast form PWT105's old list missed


def test_pwt402_block_until_ready(tmp_path):
    diags = only(run_check(tmp_path, """
        import jax.numpy as jnp

        def drain_queue(pending):
            out = jnp.stack(pending)
            out.block_until_ready()
            return out
    """), "PWT402")
    assert len(diags) == 1
    assert "host idles" in diags[0].message


def test_pwt402_negative_host_only_value(tmp_path):
    # .tolist() on plain numpy bookkeeping is free — no device round-trip
    diags = run_check(tmp_path, """
        import numpy as np

        def search(q):
            slots = np.nonzero(q)[0]
            return slots.tolist(), float(slots.sum())
    """)
    assert only(diags, "PWT402") == []


def test_pwt402_negative_instrumentation_function(tmp_path):
    diags = run_check(tmp_path, """
        import jax.numpy as jnp

        def dump_metrics_batch(vals):
            dev = jnp.asarray(vals)
            return dev.tolist()
    """)
    assert only(diags, "PWT402") == []


def test_pwt402_waived_with_justification(tmp_path):
    diags = run_check(tmp_path, """
        import jax.numpy as jnp

        def drain_queue(pending):
            out = jnp.stack(pending)
            # pwt-ok: PWT402 — deliberate materialization barrier
            out.block_until_ready()
            return out
    """)
    assert only(diags, "PWT402") == []


# ---------------------------------------------------------------------------
# PWT403 — per-row device dispatch in a loop with a batched kernel around
# ---------------------------------------------------------------------------

_LOOP_DISPATCH = """
    import jax

    @jax.jit
    def kernel(x):
        return x * 2

    def kernel_batch(xs):
        return [kernel(x) for x in xs]

    def drain(rows):
        out = []
        for r in rows:
            out.append(kernel(r))
        return out
"""


def test_pwt403_loop_dispatch_is_warning(tmp_path):
    diags = only(run_check(tmp_path, _LOOP_DISPATCH), "PWT403")
    # fires in drain's loop (kernel_batch itself is the batched kernel,
    # but its comprehension also dispatches per row — both are findings)
    assert diags
    assert not diags[0].is_error
    assert "per row inside a Python loop" in diags[0].message


def test_pwt403_negative_no_batched_kernel_in_module(tmp_path):
    # nothing batched exists yet: flagging the loop would just be noise
    diags = run_check(tmp_path, """
        import jax

        @jax.jit
        def kernel(x):
            return x * 2

        def drain(rows):
            return [kernel(r) for r in rows]
    """)
    assert only(diags, "PWT403") == []


# ---------------------------------------------------------------------------
# PWT404 — numpy operand fed to jit with no device residency
# ---------------------------------------------------------------------------

def test_pwt404_host_operand_every_tick(tmp_path):
    diags = only(run_check(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x * 2

        def ingest(rows):
            padded = np.zeros((32, 4), np.float32)
            return kernel(padded)
    """), "PWT404")
    assert len(diags) == 1
    assert not diags[0].is_error
    assert "implicit host" in diags[0].message
    assert "device_put" in diags[0].message


def test_pwt404_negative_device_put_in_unit(tmp_path):
    diags = run_check(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x * 2

        def ingest(rows):
            padded = np.zeros((32, 4), np.float32)
            dev = jax.device_put(padded)
            return kernel(dev)
    """)
    assert only(diags, "PWT404") == []


# ---------------------------------------------------------------------------
# PWT405 — float64 reaching kernel code
# ---------------------------------------------------------------------------

def test_pwt405_float64_near_device_code(tmp_path):
    diags = only(run_check(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        def make_table(n):
            return jnp.zeros((n, 4), dtype=np.float64)
    """), "PWT405")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "float32" in diags[0].message


def test_pwt405_negative_float32(tmp_path):
    diags = run_check(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        def make_table(n):
            return jnp.zeros((n, 4), dtype=np.float32)
    """)
    assert only(diags, "PWT405") == []


def test_pwt405_negative_no_device_code(tmp_path):
    # the string alone, far from any array constructor, is not a finding
    diags = run_check(tmp_path, """
        def describe_dtype():
            return "float64"
    """)
    assert only(diags, "PWT405") == []


# ---------------------------------------------------------------------------
# PWT406 — donated buffer read after donation
# ---------------------------------------------------------------------------

def test_pwt406_read_after_donation(tmp_path):
    diags = only(run_check(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def fused(buf, upd):
            return buf + upd

        def apply_update(buf, upd):
            out = fused(buf, upd)
            return buf.sum()
    """), "PWT406")
    assert len(diags) == 1
    assert diags[0].is_error
    assert "after donating" in diags[0].message


def test_pwt406_negative_result_rebound_over_donated_name(tmp_path):
    diags = run_check(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def fused(buf, upd):
            return buf + upd

        def apply_update(buf, upd):
            buf = fused(buf, upd)
            return buf.sum()
    """)
    assert only(diags, "PWT406") == []


def test_pwt406_negative_no_read_after(tmp_path):
    diags = run_check(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def fused(buf, upd):
            return buf + upd

        def apply_update(buf, upd):
            return fused(buf, upd)
    """)
    assert only(diags, "PWT406") == []


# ---------------------------------------------------------------------------
# PWT407 — jitted serving entry point absent from the warmup registry
# ---------------------------------------------------------------------------

_JIT_ENTRY = """
    import jax

    def search(q):
        return q * 2

    search_jit = jax.jit(search)
"""


def test_pwt407_unregistered_entry_point(tmp_path):
    diags = only(run_check(tmp_path, _JIT_ENTRY, registry=set()),
                 "PWT407")
    assert len(diags) == 1
    assert not diags[0].is_error
    assert "search_jit" in diags[0].message
    assert "WARMED_ENTRY_POINTS" in diags[0].message


def test_pwt407_negative_registered_under_either_name(tmp_path):
    # registering the wrapper or the wrapped fn both count
    assert only(run_check(tmp_path, _JIT_ENTRY,
                          registry={"search_jit"}), "PWT407") == []
    assert only(run_check(tmp_path, _JIT_ENTRY,
                          registry={"search"}), "PWT407") == []


def test_pwt407_negative_non_serving_name(tmp_path):
    diags = run_check(tmp_path, """
        import jax

        def helper(q):
            return q * 2

        helper_jit = jax.jit(helper)
    """, registry=set())
    assert only(diags, "PWT407") == []


def test_pwt407_silent_without_a_registry(tmp_path):
    # no warmup.py reachable from tmp_path → autodiscovery returns None
    # and the check stays silent rather than flagging every jit
    diags = run_check(tmp_path, _JIT_ENTRY)
    assert only(diags, "PWT407") == []


def test_warmup_registry_autodiscovered_next_to_sources(tmp_path):
    (tmp_path / "warmup.py").write_text(textwrap.dedent("""
        WARMED_ENTRY_POINTS = frozenset({"search_jit", "encode_jit"})
    """))
    assert load_warmup_registry([str(tmp_path)]) == \
        {"search_jit", "encode_jit"}
    # the checker picks it up: the registered entry point passes clean
    diags = run_check(tmp_path, _JIT_ENTRY)
    assert only(diags, "PWT407") == []


def test_warmup_registry_of_real_package_lists_encoder():
    assert "encode_jit" in load_warmup_registry(["pathway_tpu/models"])


# ---------------------------------------------------------------------------
# PWT408 — blocking host I/O inside a device-leg function
# ---------------------------------------------------------------------------

def test_pwt408_print_in_dispatching_function(tmp_path):
    diags = only(run_check(tmp_path, """
        import jax

        @jax.jit
        def kernel(x):
            return x * 2

        def drain_tick(x):
            print("tick", x.shape)
            return kernel(x)
    """), "PWT408")
    assert len(diags) == 1
    assert not diags[0].is_error
    assert "blocking host I/O" in diags[0].message


def test_pwt408_negative_no_device_dispatch(tmp_path):
    # printing in a host-only function is nobody's business
    diags = run_check(tmp_path, """
        def drain_tick(x):
            print("tick", x)
            return x
    """)
    assert only(diags, "PWT408") == []


def test_pwt408_negative_instrumentation_function(tmp_path):
    diags = run_check(tmp_path, """
        import jax.numpy as jnp

        def trace_dispatch(x):
            print("probe", x)
            return jnp.asarray(x)
    """)
    assert only(diags, "PWT408") == []


# ---------------------------------------------------------------------------
# waivers integrate with the shared audit
# ---------------------------------------------------------------------------

def test_perf_waivers_show_up_in_scan(tmp_path):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def drain_queue(pending):
            out = jnp.stack(pending)
            # pwt-ok: PWT402 — deliberate barrier, bench stamps after it
            out.block_until_ready()
            return out
    """))
    waivers = scan_waivers([str(f)])
    assert [w["codes"] for w in waivers] == [["PWT402"]]
    assert "deliberate barrier" in waivers[0]["comment"]


# ---------------------------------------------------------------------------
# inventory
# ---------------------------------------------------------------------------

def test_inventory_jits_hot_units_and_registry(tmp_path):
    f = tmp_path / "mod_under_test.py"
    f.write_text(textwrap.dedent("""
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def fused(buf, upd):
            return buf + upd

        def ingest(self, rows):
            return fused(rows, rows)

        def _describe():
            return "cold"
    """))
    (tmp_path / "warmup.py").write_text(
        "WARMED_ENTRY_POINTS = frozenset({'fused'})\n")
    inv = perf_inventory([str(f)])
    by_name = {j["name"]: j for j in inv["jit_entry_points"]}
    assert by_name["fused"]["donate_argnums"] == [0]
    assert "mod_under_test:ingest" in inv["hot_units"]
    assert "mod_under_test:_describe" not in inv["hot_units"]
    assert inv["warmup_registry"] == ["fused"]


def test_inventory_of_real_corpus_sees_encoder_jit():
    inv = perf_inventory(["pathway_tpu/models"])
    names = {j["name"] for j in inv["jit_entry_points"]}
    assert "encode_jit" in names
    assert "encode_jit" in inv["warmup_registry"]


# ---------------------------------------------------------------------------
# dogfood gates — the four device-leg directories must pass their own lint
# ---------------------------------------------------------------------------

def test_engine_source_is_perf_clean():
    assert check_perf(["pathway_tpu/engine"]) == []


def test_ops_source_is_perf_clean():
    assert check_perf(["pathway_tpu/ops"]) == []


def test_models_source_is_perf_clean():
    assert check_perf(["pathway_tpu/models"]) == []


def test_parallel_source_is_perf_clean():
    assert check_perf(["pathway_tpu/parallel"]) == []


def test_seeded_negative_example_trips_the_gate():
    diags = check_perf(["tests/perf_negative_example.py"],
                       warmup_registry=set())
    seen = set(codes(diags))
    assert {"PWT401", "PWT402", "PWT403", "PWT404", "PWT405", "PWT406",
            "PWT407", "PWT408"} <= seen
    assert any(d.code == "PWT402" and d.is_error for d in diags)


# ---------------------------------------------------------------------------
# PWT105 → PWT402 deference (satellite: the old sync list folds in)
# ---------------------------------------------------------------------------

def test_classify_udf_counts_cast_as_sync_point():
    # the form PWT105's old list missed: int()/float() on a device value
    from pathway_tpu.internals.static_check.shard_check import classify_udf

    def _casty(x):
        return float(x) * 2.0

    cls = classify_udf(_casty)
    assert any("implicit .item()" in s for s in cls.sync_points)


def test_classify_udf_constant_cast_is_not_sync():
    from pathway_tpu.internals.static_check.shard_check import classify_udf

    def _const(x):
        return x * float(2)

    assert classify_udf(_const).sync_points == ()


def _pwt105(related_file):
    from pathway_tpu.internals.static_check.diagnostics import Diagnostic
    related = (Trace(related_file, 3, "_udf", ""),) if related_file else ()
    return Diagnostic(code="PWT105", message="sync point",
                      related=related)


def test_defer_pwt105_drops_findings_covered_by_perf_trees(tmp_path):
    from pathway_tpu.cli import _defer_pwt105

    inside = str(tmp_path / "udfs.py")
    outside = "/somewhere/else/udfs.py"
    kept = _defer_pwt105(
        [_pwt105(inside), _pwt105(outside), _pwt105(None)],
        [str(tmp_path)])
    # only the UDF defined under the scanned tree defers to PWT402
    assert [d.related[0].file_name if d.related else None for d in kept] \
        == [outside, None]


def test_defer_pwt105_keeps_everything_without_trees(tmp_path):
    from pathway_tpu.cli import _defer_pwt105

    diags = [_pwt105(str(tmp_path / "udfs.py"))]
    assert _defer_pwt105(diags, []) == diags


# ---------------------------------------------------------------------------
# CLI front doors
# ---------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu", "check", *args],
        capture_output=True, text=True, env=None)


def test_cli_perf_clean_and_json():
    proc = _run_cli("--perf", "--json", "pathway_tpu/engine",
                    "pathway_tpu/ops", "pathway_tpu/models",
                    "pathway_tpu/parallel")
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["diagnostics"] == []
    names = {j["name"] for j in payload["inventory"]["jit_entry_points"]}
    assert "encode_jit" in names
    assert "encode_jit" in payload["inventory"]["warmup_registry"]


def test_cli_perf_seeded_negative_fails():
    proc = _run_cli("--perf", "tests/perf_negative_example.py")
    assert proc.returncode == 1
    assert "PWT402" in proc.stdout


def test_cli_all_exit_code_carries_perf_bit(tmp_path):
    tree = tmp_path / "src"
    tree.mkdir()
    shutil.copy("tests/perf_negative_example.py", tree / "negative.py")
    proc = _run_cli("--all", "--json", str(tree))
    assert proc.returncode == 16, proc.stderr  # perf bit only
    payload = json.loads(proc.stdout)
    assert payload["exit_code"] == 16
    fam_codes = [d["code"] for d in payload["families"]["perf"]]
    assert "PWT402" in fam_codes


def test_cli_perf_is_mutually_exclusive_with_other_modes():
    proc = _run_cli("--perf", "--durability", "pathway_tpu/engine")
    assert proc.returncode != 0
    assert "mutually exclusive" in proc.stderr


def test_cli_list_waivers_covers_perf_family():
    proc = _run_cli("--list-waivers", "--json", "pathway_tpu/ops")
    assert proc.returncode == 0, proc.stderr
    waivers = json.loads(proc.stdout)
    knn = [w for w in waivers if w["file"].endswith("knn.py")
           and "PWT402" in w["codes"]]
    assert knn  # the audited consolidation-read waivers
    assert all(w["comment"] for w in knn)
