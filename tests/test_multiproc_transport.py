"""Transport-layer units for the cluster exchange plane
(engine/multiproc.py): bounded connect/handshake (the accept-loop hang
fix), the shared-memory slab ring, and the socket framing path."""

from __future__ import annotations

import socket
import threading
import time

import pytest

from pathway_tpu.engine import wire
from pathway_tpu.engine.multiproc import (Cluster, ClusterConnectError,
                                          _Peer, _ShmRing)


def test_connect_times_out_with_named_error_when_peer_never_dials():
    """Process 0 of 2 listens; nobody dials. The old accept loop joined a
    thread stuck in Listener.accept() and raised a generic TimeoutError
    only if the join noticed; a missing peer must now surface as
    ClusterConnectError within the deadline."""
    cl = Cluster(2, 0, 19810, run_id="hangfix")
    t0 = time.monotonic()
    with pytest.raises(ClusterConnectError):
        cl.connect(timeout_s=1.0)
    assert time.monotonic() - t0 < 5.0
    cl.close()


def test_connect_survives_dialer_dying_mid_handshake():
    """A dialer that connects and then goes silent (dies mid-handshake)
    used to wedge the accept loop forever inside conn.recv(); now the
    handshake recv is deadline-bounded, the bad dialer is logged and
    dropped, and connect() still fails *named* (no real peer ever
    arrived) instead of hanging."""
    cl = Cluster(2, 0, 19815, run_id="midhs")

    def half_dial():
        # connect, send one junk byte instead of the HMAC handshake, then
        # hold the socket open silently (the mid-handshake death)
        time.sleep(0.2)
        s = socket.create_connection(("127.0.0.1", 19815), timeout=2)
        s.sendall(b"z")
        time.sleep(3.0)
        s.close()

    t = threading.Thread(target=half_dial, daemon=True)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(ClusterConnectError):
        cl.connect(timeout_s=2.0)
    assert time.monotonic() - t0 < 8.0
    cl.close()


def test_cross_endian_peer_is_refused_by_name():
    """The codec's bulk buffers are native-endian; a peer advertising a
    different native layout must be refused with the named diagnosis (not
    silently decoded byte-swapped)."""
    from pathway_tpu.engine.multiproc import _wire_compat, _wire_compat_error

    assert _wire_compat_error(_wire_compat(), 1) is None
    assert _wire_compat_error(None, 1) is None  # pre-field peers pass
    err = _wire_compat_error(("big", 4, 4, 8, 8), 1)
    assert err is not None and "incompatible native wire layout" in err


def test_connect_rejects_wrong_authkey():
    """Mismatched PATHWAY_RUN_ID (authkey) must fail the handshake on
    both sides, not connect two unrelated runs together."""
    results: dict = {}

    def listener():
        cl = Cluster(2, 0, 19820, run_id="run-A")
        try:
            cl.connect(timeout_s=2.5)
            results["listener"] = "connected"
        except ClusterConnectError as e:
            results["listener"] = e
        finally:
            cl.close()

    def dialer():
        cl = Cluster(2, 1, 19820, run_id="run-B")
        try:
            cl.connect(timeout_s=2.5)
            results["dialer"] = "connected"
        except ClusterConnectError as e:
            results["dialer"] = e
        finally:
            cl.close()

    th = [threading.Thread(target=listener, daemon=True),
          threading.Thread(target=dialer, daemon=True)]
    for t in th:
        t.start()
    for t in th:
        t.join(timeout=10)
        assert not t.is_alive(), "connect() wedged on authkey mismatch"
    assert isinstance(results["listener"], ClusterConnectError)
    assert isinstance(results["dialer"], ClusterConnectError)


# -- shm ring ----------------------------------------------------------------

def test_shm_ring_roundtrip_and_slot_reuse():
    ring = _ShmRing(nslots=2, slot_bytes=256)
    try:
        peer = _ShmRing(name=ring.name)  # attach
        deadline = time.monotonic() + 2
        for i in range(7):  # > 2 cycles through both slots
            blob = bytes([i]) * (50 + i)
            slot = ring.write([blob[:10], blob[10:]], len(blob), deadline)
            assert slot == i % 2
            view = peer.read_view(slot, len(blob))
            assert bytes(view) == blob
            view.release()  # a live slot view would block the mmap close
            peer.release(slot)
        peer.close()
    finally:
        ring.close()


def test_attach_rings_verifies_shared_memory_via_token():
    """Hostname equality lies on cloned VMs: the dialer must prove the
    attached ring is the SAME memory via the handshake token, refusing by
    name (not retrying into a timeout) on mismatch or missing segment."""
    import os as _os

    cl = Cluster(2, 1, 19890, run_id="tok")
    l2d = _ShmRing(nslots=2, slot_bytes=128)
    d2l = _ShmRing(nslots=2, slot_bytes=128)
    try:
        token = _os.urandom(16)
        l2d.poke_token(token)
        tx, rx = cl._attach_rings({"l2d": l2d.name, "d2l": d2l.name,
                                   "token": token.hex()})
        assert rx.peek_token(16) == token
        tx.close()
        rx.close()
        with pytest.raises(ClusterConnectError, match="token"):
            cl._attach_rings({"l2d": l2d.name, "d2l": d2l.name,
                              "token": _os.urandom(16).hex()})
        with pytest.raises(ClusterConnectError, match="cannot attach"):
            cl._attach_rings({"l2d": "psm_does_not_exist_pw",
                              "d2l": d2l.name, "token": token.hex()})
    finally:
        l2d.close()
        d2l.close()


def test_listener_requires_shm_attach_ack(monkeypatch):
    """The shm handshake ends with a dialer->listener ack sent only after
    the rings are attached and the token verified. A dialer that dies (or
    refuses the rings) after receiving the ring names must fail the
    listener's handshake by name — before the ack barrier the listener's
    connect() returned a live peer whose first exchange could overwrite
    the slot-0 token under the dialer's feet (spurious cloned-hostname
    refusal) or wedge for the full recv timeout against a dialer that
    bailed."""
    from pathway_tpu.engine.multiproc import (_recv_hello, _send_hello,
                                              _wire_compat)

    monkeypatch.setenv("PATHWAY_EXCHANGE_TRANSPORT", "shm")
    port = 19895
    listener = Cluster(2, 0, port, run_id="ackbar")
    saw: dict = {}

    def dialer_no_ack():
        time.sleep(0.2)
        s = socket.create_connection(("127.0.0.1", port), timeout=2)
        try:
            dial = Cluster(2, 1, port, run_id="ackbar")
            dial._auth(s, time.monotonic() + 2)
            _send_hello(s, {"proc": 1, "host": socket.gethostname(),
                            "wire": _wire_compat(), "shm": True})
            saw["reply"] = _recv_hello(s)
        finally:
            s.close()  # dies without sending the attach ack

    t = threading.Thread(target=dialer_no_ack, daemon=True)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(ClusterConnectError):
        listener.connect(timeout_s=2.0)
    assert time.monotonic() - t0 < 8.0
    listener.close()
    t.join(timeout=5)
    # the handshake really reached the shm stage before the dialer bailed
    assert saw["reply"].get("shm") is not None


def test_get_cluster_not_published_on_failed_connect(monkeypatch):
    """A connect() failure must leave the module global unset: a published
    dead (close()d, peerless) cluster would make every later get_cluster()
    return it, and exchange() with no peers silently computes only the
    local shard — divergent results instead of a named error."""
    import pathway_tpu.engine.multiproc as mp

    mp.reset_cluster()
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    monkeypatch.setenv("PATHWAY_FIRST_PORT", "19893")

    def boom(self, timeout_s=30.0):
        raise ClusterConnectError("boom")

    monkeypatch.setattr(mp.Cluster, "connect", boom)
    with pytest.raises(ClusterConnectError):
        mp.get_cluster()
    assert mp._CLUSTER is None


def test_shm_ring_close_unlinks_despite_exported_views():
    """A propagating traceback can pin a slot view past close(); the
    creator must still unlink the segment NAME (the mapping dies with the
    process either way, but the swallowed BufferError used to leak the
    /dev/shm file forever)."""
    from multiprocessing import shared_memory

    ring = _ShmRing(nslots=2, slot_bytes=128)
    view = ring._slot_view(0)  # simulates a view held by a raised frame
    ring.close()
    with pytest.raises(FileNotFoundError):
        shared_memory.SharedMemory(name=ring.name)
    view.release()


def test_shm_capacity_guard_degrades_auto_to_tcp(monkeypatch):
    """tmpfs ftruncate is sparse, so an over-capacity ring 'creates'
    fine and SIGBUSes on the first slot write (Docker's default /dev/shm
    is 64 MiB). A too-small /dev/shm must degrade the link to tcp in
    auto mode and refuse BY NAME under forced shm — never bring up a
    ring that cannot hold its own slots."""
    import pathway_tpu.engine.multiproc as mp

    monkeypatch.setattr(mp, "_shm_headroom", lambda: 1024)
    monkeypatch.setenv("PATHWAY_EXCHANGE_TRANSPORT", "shm")
    cl = Cluster(2, 0, 19897, run_id="cap")
    with pytest.raises(ClusterConnectError, match="/dev/shm"):
        cl._create_rings(1)
    cl.close()

    # auto mode: full 2-process connect completes over sockets instead
    monkeypatch.setenv("PATHWAY_EXCHANGE_TRANSPORT", "auto")
    results: dict = {}

    def side(pid):
        c = Cluster(2, pid, 19898, run_id="cap2")
        try:
            c.connect(timeout_s=5.0)
            results[pid] = c.transport_counts()
        finally:
            c.close()

    th = [threading.Thread(target=side, args=(p,), daemon=True)
          for p in (0, 1)]
    for t in th:
        t.start()
    for t in th:
        t.join(timeout=15)
        assert not t.is_alive()
    assert results[0] == {"tcp": 1}
    assert results[1] == {"tcp": 1}


def test_shm_ring_oversized_frame_returns_none():
    ring = _ShmRing(nslots=2, slot_bytes=64)
    try:
        assert ring.write([b"x" * 100], 100, time.monotonic() + 1) is None
    finally:
        ring.close()


def test_shm_ring_full_slot_times_out_loudly():
    ring = _ShmRing(nslots=1, slot_bytes=64)
    try:
        assert ring.write([b"a"], 1, time.monotonic() + 1) == 0
        # never released: the next write of the same slot must time out
        # with a diagnosis, not overwrite unread data
        with pytest.raises(TimeoutError, match="not released"):
            ring.write([b"b"], 1, time.monotonic() + 0.3)
    finally:
        ring.close()


# -- socket framing ----------------------------------------------------------

def _peer_pair() -> tuple[_Peer, _Peer]:
    a, b = socket.socketpair()
    return _Peer(a), _Peer(b)


def test_inline_frame_roundtrip_reuses_recv_buffer():
    pa, pb = _peer_pair()
    try:
        payload = {"rows": {0: {0: [(7, ("x", 1), 1)]}}, "wm": None,
                   "bcast": None}
        chunks, total, _ = wire.encode_frame(("x", 1, 0), payload)
        pa.send_frame(chunks, total, time.monotonic() + 2)
        assert pb.wait_readable(2.0)
        view, release, _bytes = pb.recv_frame()
        tag, out, _ = wire.decode_frame(view)
        release()
        assert tag == ("x", 1, 0)
        buf_before = id(pb._rbuf)
        # a second, equal-sized frame must reuse the same buffer
        pa.send_frame(chunks, total, time.monotonic() + 2)
        view, release, _bytes = pb.recv_frame()
        wire.decode_frame(view)
        release()
        assert id(pb._rbuf) == buf_before
    finally:
        pa.close()
        pb.close()


def test_shm_frame_rides_ring_with_socket_doorbell():
    tx = _ShmRing(nslots=2, slot_bytes=4096)
    rx_attached = _ShmRing(name=tx.name)
    a, b = socket.socketpair()
    pa = _Peer(a, "shm", tx_ring=tx)
    pb = _Peer(b, "shm", rx_ring=rx_attached)
    try:
        chunks, total, _ = wire.encode_frame("t", {"rows": None, "any": True})
        sock_bytes = pa.send_frame(chunks, total, time.monotonic() + 2)
        assert sock_bytes == 13  # the doorbell, not the frame
        view, release, _b = pb.recv_frame()
        tag, out, _ = wire.decode_frame(view)
        release()
        assert tag == "t" and out == {"rows": None, "any": True}
        # oversized frame falls back to the inline socket path
        big = [b"y" * 8192]
        sock_bytes = pa.send_frame(big, 8192, time.monotonic() + 2)
        assert sock_bytes > 8192
        view, release, _b = pb.recv_frame()
        assert bytes(view) == big[0]
        release()
    finally:
        pa.close()
        pb.close()


def test_peer_death_surfaces_as_eoferror():
    pa, pb = _peer_pair()
    pa.close()
    try:
        assert pb.wait_readable(2.0)
        with pytest.raises(EOFError):
            pb.recv_frame()
    finally:
        pb.close()
