"""Universe solver (reference: internals/universe_solver.py — SAT-based;
here a relation graph with query-time closure deciding the same subset/
equality/disjointness entailments)."""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.universe import Universe


def setup_function(_):
    G.clear()


def teardown_function(_):
    G.clear()


def test_transitive_subset():
    a = Universe()
    b = a.subuniverse()
    c = b.subuniverse()
    assert c.is_subset_of(a)
    assert not a.is_subset_of(c)


def test_late_promise_propagates_to_existing_children():
    """The regression the solver fixes: the old eager-snapshot design
    copied supersets at subuniverse() time, so a promise recorded on the
    parent AFTERWARD never reached existing children."""
    parent = Universe()
    child = parent.subuniverse()  # created BEFORE the promise
    target = Universe()
    parent.promise_is_subset_of(target)
    assert parent.is_subset_of(target)
    assert child.is_subset_of(target)  # entailed through the parent


def test_equality_both_ways():
    a, b = Universe(), Universe()
    a.promise_is_subset_of(b)
    assert not a.is_equal_to(b)
    b.promise_is_subset_of(a)
    assert a.is_equal_to(b) and b.is_equal_to(a)


def test_disjointness_inherited_downward():
    a, b = Universe(), Universe()
    a.promise_is_disjoint_from(b)
    sa, sb = a.subuniverse(), b.subuniverse()
    assert sa.is_disjoint_from(sb)
    assert sb.is_disjoint_from(sa)
    assert not sa.is_disjoint_from(a.subuniverse())


def test_table_operations_register_relations():
    t = pw.debug.table_from_markdown("""
    a
    1
    2
    3
    """)
    f = t.filter(t.a > 1)
    assert f._universe.is_subset_of(t._universe)
    u = t.concat_reindex(t)  # fresh keys: no relation claimed
    c = f.concat(t.filter(t.a <= 1))
    # union result: both inputs are subsets of it, and it stays a
    # subset-of-t entailment-free (c may equal t but is not proven to)
    assert f._universe.is_subset_of(c._universe)
    assert not c._universe.is_subset_of(t._universe)
    d = t.promise_universes_are_disjoint(u)
    assert t._universe.is_disjoint_from(u._universe)


def test_prune_preserves_live_entailments():
    """Garbage-collected universes splice out of the relation graph while
    subset AND disjointness entailments between live universes survive."""
    import gc

    from pathway_tpu.internals.universe_solver import GLOBAL_SOLVER

    root = Universe()
    mid = root.subuniverse()       # will die
    leaf = mid.subuniverse()
    other = Universe()
    mid2 = other.subuniverse()     # will die, carries a disjoint pair
    leaf2 = mid2.subuniverse()
    mid.promise_is_disjoint_from(mid2)
    assert leaf.is_subset_of(root)
    assert leaf.is_disjoint_from(leaf2)

    del mid, mid2
    gc.collect()
    GLOBAL_SOLVER._prune()

    assert leaf.is_subset_of(root), "subset lost through dead intermediate"
    assert leaf.is_disjoint_from(leaf2), \
        "disjointness lost through dead intermediate"
    dead_ids = set(GLOBAL_SOLVER._supersets) - set(
        GLOBAL_SOLVER._registry.keys())
    # no dead node keeps outgoing edges after the sweep
    assert not dead_ids


def test_prune_triggers_automatically():
    from pathway_tpu.internals import universe_solver as us

    GLOBAL = us.GLOBAL_SOLVER
    GLOBAL.reset()
    old = us._PRUNE_EVERY
    us._PRUNE_EVERY = 64
    try:
        keep = Universe()
        for _ in range(100):  # churn: dead chains force automatic sweeps
            u = keep.subuniverse()
            for _ in range(3):
                u = u.subuniverse()
        import gc

        gc.collect()
        keep.subuniverse()  # one more add past the threshold
        assert len(GLOBAL._supersets) < 100
    finally:
        us._PRUNE_EVERY = old
