"""Top-level API parity with the reference's __all__ (python/pathway/
__init__.py): every exported name resolves, and the compat helpers
behave (internals/compat.py)."""

from __future__ import annotations

import os

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from tests.utils import T, rows_of


@pytest.fixture(autouse=True)
def _clear():
    G.clear()
    yield
    G.clear()


_REFERENCE_ALL = None

# parity tests compare against the reference checkout; skip cleanly in
# containers that ship only this repo
needs_reference = pytest.mark.skipif(
    not os.path.isdir("/root/reference/python/pathway"),
    reason="reference checkout /root/reference not present")


def _reference_names():
    global _REFERENCE_ALL
    if _REFERENCE_ALL is None:
        import re

        src = open("/root/reference/python/pathway/__init__.py").read()
        m = re.search(r"__all__ = \[(.*?)\]", src, re.DOTALL)
        _REFERENCE_ALL = re.findall(r'"([A-Za-z_][A-Za-z0-9_]*)"',
                                    m.group(1))
    return _REFERENCE_ALL


@needs_reference
def test_every_reference_export_resolves():
    missing = [n for n in _reference_names() if not hasattr(pw, n)]
    assert missing == [], missing


def test_free_function_joins():
    l = T("""
    k | v
    a | 1
    b | 2
    """)
    r = T("""
    k | w
    a | 9
    """)
    out = pw.join_inner(l, r, l.k == r.k).select(l.k, l.v, r.w)
    assert sorted(rows_of(out)) == [("a", 1, 9)]
    out2 = pw.join(l, r, l.k == r.k, how="left").select(l.k, r.w)
    assert sorted(rows_of(out2)) == [("a", 9), ("b", None)]


def test_assert_table_has_schema():
    t = T("""
    name | qty
    bolt | 3
    """)

    class Good(pw.Schema):
        name: str
        qty: int

    class Bad(pw.Schema):
        name: str
        missing_col: int

    pw.assert_table_has_schema(t, Good)
    with pytest.raises(AssertionError, match="missing_col"):
        pw.assert_table_has_schema(t, Bad)


def test_wrap_py_object_roundtrip():
    class Thing:
        pass

    w = pw.wrap_py_object({"a": 1})
    assert w.value == {"a": 1}
    assert isinstance(w, pw.PyObjectWrapper)
    import pickle

    assert pickle.loads(w.dumps()) == {"a": 1}


def test_local_error_log_scopes_by_construction():
    """The scope captures errors of operators BUILT inside it; ambient
    logging outside any operator step still goes to the global log
    (reference semantics: error-log tables attach to the build scope)."""
    from pathway_tpu.internals.error import global_error_log

    with pw.local_error_log() as log:
        global_error_log().log("ambient", "op")
    assert log.entries == []  # nothing was built, nothing captured
    assert any(e["message"] == "ambient"
               for e in global_error_log().entries)


def test_type_facade_and_schema_properties():
    assert pw.Type.STRING is not None and pw.Type.INT is not None
    opt = pw.Type.optional(pw.Type.INT)
    assert "int" in str(opt)
    schema = pw.schema_builder(
        {"a": pw.column_definition(dtype=int)},
        properties=pw.SchemaProperties(append_only=True))
    assert schema.properties().append_only is True


def test_joinable_isinstance_contract():
    l = T("""
    k | v
    a | 1
    """)
    r = T("""
    k | w
    a | 9
    """)
    assert isinstance(l, pw.Joinable) and isinstance(l, pw.TableLike)
    jr = l.join(r, l.k == r.k)
    assert isinstance(jr, pw.Joinable)
    assert isinstance(l.groupby(l.k), pw.TableLike)


def test_iterate_universe_accepted():
    t = T("""
    v
    1
    5
    """)

    def step(t):
        capped = t.select(v=pw.if_else(t.v > 3, 3, t.v))
        return capped

    out = pw.iterate(step, t=pw.iterate_universe(t))
    assert sorted(rows_of(out)) == [(1,), (3,)]


def test_udf_async_with_retry_kwargs():
    from pathway_tpu.internals.udfs import FixedDelayRetryStrategy

    calls = []

    @pw.udf_async(retry_strategy=FixedDelayRetryStrategy(
        max_retries=3, delay_ms=1))
    async def flaky(x: int) -> int:
        calls.append(x)
        if len(calls) < 2:
            raise RuntimeError("transient")
        return x * 10

    t = T("""
    x
    4
    """)
    out = t.select(y=flaky(t.x))
    assert rows_of(out) == [(40,)]
    assert len(calls) >= 2  # the retry actually ran


def test_local_error_log_captures_runtime_errors():
    """Errors raised while operators built in the scope STEP (not just
    while the block is open) land in the scoped log."""
    from pathway_tpu.internals.error import global_error_log

    t = T("""
    v
    1
    """)
    with pw.local_error_log() as log:
        bad = t.select(y=t.v // 0)
    from tests.utils import rows_of as _r

    _r(bad)  # run AFTER the block closed
    assert any("failed" in e["message"] or "division" in e["message"]
               for e in log.entries), log.entries


def test_table_live_view():
    t = T("""
    v
    7
    """)
    live = t.live()
    assert isinstance(live, pw.LiveTable)
    snap = live.snapshot()
    assert list(snap["v"]) == [7]


@needs_reference
def test_submodule_export_parity():
    """Key submodule surfaces resolve every reference __all__ name."""
    import re

    def ref_names(path):
        src = open(path).read()
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.DOTALL)
        return re.findall(r'"([A-Za-z_][A-Za-z0-9_]*)"',
                          m.group(1)) if m else []

    from pathway_tpu.internals import udfs

    cases = {
        "io": (pw.io, "/root/reference/python/pathway/io/__init__.py"),
        "udfs": (udfs,
                 "/root/reference/python/pathway/internals/udfs/__init__.py"),
        "temporal": (pw.temporal,
                     "/root/reference/python/pathway/stdlib/temporal/"
                     "__init__.py"),
        "indexing": (pw.indexing,
                     "/root/reference/python/pathway/stdlib/indexing/"
                     "__init__.py"),
    }
    problems = {}
    for label, (mod, path) in cases.items():
        missing = [n for n in ref_names(path) if not hasattr(mod, n)]
        if missing:
            problems[label] = missing
    assert problems == {}, problems


def test_async_options_and_with_helpers_execute():
    import asyncio

    from pathway_tpu.internals.udfs import (FixedDelayRetryStrategy,
                                            async_options,
                                            with_retry_strategy)

    calls = []

    @async_options(retry_strategy=FixedDelayRetryStrategy(
        max_retries=3, delay_ms=1))
    async def flaky(x):
        calls.append(x)
        if len(calls) < 2:
            raise RuntimeError("transient")
        return x + 1

    assert asyncio.run(flaky(1)) == 2
    assert len(calls) == 2

    async def plain(x):
        return x * 2

    wrapped = with_retry_strategy(plain, FixedDelayRetryStrategy(
        max_retries=1, delay_ms=1))
    assert asyncio.run(wrapped(3)) == 6


def test_remove_errors_and_eval_type(capsys):
    t = T("""
    a | b
    3 | 3
    4 | 0
    5 | 5
    """)
    safe = t.select(t.a, ratio=t.a // t.b).remove_errors()
    got = sorted(rows_of(safe))
    assert got == [(3, 1), (5, 1)]  # the 4//0 row dropped
    assert "int" in str(t.eval_type(t.a + t.b))
    assert t.update_id_type(pw.Pointer) is t
    t.debug("probe")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    out = capsys.readouterr().out
    assert "[debug probe]" in out


def test_debug_parquet_and_dicts(tmp_path):
    t = T("""
    name | qty
    bolt | 3
    nut  | 5
    """)
    keys, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["qty"].values()) == [3, 5]
    assert set(cols) == {"name", "qty"}
    path = tmp_path / "t.parquet"
    pw.debug.table_to_parquet(t, path)
    G.clear()
    back = pw.debug.table_from_parquet(path)
    assert sorted(rows_of(back)) == [("bolt", 3), ("nut", 5)]


def test_top_level_shim_modules_importable():
    """Reference users write ``import pathway.udfs`` / ``from
    pathway.schema import ...`` — the same module paths must resolve here
    (reference top-level shims: udfs.py, reducers.py, asynchronous.py,
    universes.py, schema.py, optional_import.py)."""
    import importlib
    import sys

    import pathway_tpu as pw

    for name in ("udfs", "reducers", "asynchronous", "universes"):
        mod = importlib.import_module(f"pathway_tpu.{name}")
        assert mod is getattr(pw, name)  # no default: attr must exist
        assert mod is sys.modules[f"pathway_tpu.{name}"]
    from pathway_tpu.xpacks import llm

    assert llm.constants.DEFAULT_VISION_MODEL
    from pathway_tpu.optional_import import optional_imports
    from pathway_tpu.schema import Schema, schema_from_types

    assert schema_from_types(x=int).column_names() == ["x"]
    import pytest as _pytest

    with _pytest.raises(ImportError, match="pathway-tpu"):
        with optional_imports("xpack-llm"):
            raise ImportError("no such module")
    assert Schema is not None
