"""Flagship encoder model + tokenizer + training step tests (CPU backend)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pathway_tpu.models.encoder import EncoderConfig, encode, init_params
from pathway_tpu.models.tokenizer import HashTokenizer
from pathway_tpu.models.train import (
    contrastive_train_step,
    init_train_state,
    make_optimizer,
)


@pytest.fixture(scope="module")
def tiny():
    config = EncoderConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), config)
    return config, params


def _batch(config, n=4, s=12, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, config.vocab_size, (n, s)).astype(np.int32)
    mask = np.ones((n, s), dtype=bool)
    return ids, mask


def test_encode_shape_and_norm(tiny):
    config, params = tiny
    ids, mask = _batch(config)
    out = encode(params, ids, mask, config=config)
    assert out.shape == (4, config.hidden)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=1),
                               1.0, atol=1e-3)


def test_encode_deterministic(tiny):
    config, params = tiny
    ids, mask = _batch(config)
    a = np.asarray(encode(params, ids, mask, config=config))
    b = np.asarray(encode(params, ids, mask, config=config))
    np.testing.assert_array_equal(a, b)


def test_encode_padding_invariance(tiny):
    """Padding tokens must not change the (mean-pooled) embedding."""
    config = EncoderConfig.tiny(pooling="mean")
    params = init_params(jax.random.PRNGKey(0), config)
    ids, mask = _batch(config, n=2, s=8)
    padded_ids = np.concatenate([ids, np.zeros((2, 8), np.int32)], axis=1)
    padded_mask = np.concatenate([mask, np.zeros((2, 8), bool)], axis=1)
    a = np.asarray(encode(params, ids, mask, config=config))
    b = np.asarray(encode(params, padded_ids, padded_mask, config=config))
    np.testing.assert_allclose(a, b, atol=2e-2)


def test_moe_encode_runs():
    config = EncoderConfig.tiny(num_experts=4)
    params = init_params(jax.random.PRNGKey(1), config)
    ids, mask = _batch(config)
    out = np.asarray(encode(params, ids, mask, config=config))
    assert np.isfinite(out).all()


def test_tokenizer_stable_and_padded():
    tok = HashTokenizer(vocab_size=1024, max_len=16)
    a = tok.encode("hello world")
    b = tok.encode("hello world")
    assert a == b
    assert a[0] == 101 and a[-1] == 102
    ids, mask = tok.batch(["one two three", "one"], pad_to=8)
    assert ids.shape == (2, 8)
    assert mask[0].sum() == 5 and mask[1].sum() == 3  # CLS + words + SEP
    # same word → same id across instances (cache-independent)
    tok2 = HashTokenizer(vocab_size=1024)
    assert tok2.encode("hello world") == a


def test_train_step_reduces_loss(tiny):
    config, _ = tiny
    opt = make_optimizer(learning_rate=1e-3)
    state = init_train_state(jax.random.PRNGKey(0), config, opt)
    rng = np.random.default_rng(0)
    batch = {
        "q_ids": rng.integers(0, config.vocab_size, (8, 10)).astype(np.int32),
        "q_mask": np.ones((8, 10), bool),
        "d_ids": rng.integers(0, config.vocab_size, (8, 10)).astype(np.int32),
        "d_mask": np.ones((8, 10), bool),
    }
    step = jax.jit(lambda s, b: contrastive_train_step(
        s, b, config=config, optimizer=opt))
    losses = []
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5


def test_knn_add_batch_matches_add():
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(40, 8)).astype(np.float32)
    a = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)
    b = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)
    for i in range(40):
        a.add(Pointer(i), vecs[i])
    b.add_batch([Pointer(i) for i in range(40)], vecs)
    q = [(Pointer(99), vecs[7], 5, None)]
    assert a.search(q) == b.search(q)
    # overwrite semantics: re-adding a key replaces its vector
    b.add_batch([Pointer(7)], vecs[8:9])
    res = b.search([(Pointer(99), vecs[8], 1, None)])
    assert res[0][0][0] in (Pointer(7), Pointer(8))


def test_sharded_knn_add_batch_grow_remap():
    """Regression: a grow mid-batch remaps slots; every row must stay findable."""
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh
    from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex

    mesh = make_mesh(MeshConfig(data=2, model=1))
    index = ShardedKnnIndex(4, mesh=mesh)  # cap 128/shard → 256 total
    rng = np.random.default_rng(0)
    n = 300  # forces a grow inside one add_batch
    vecs = rng.normal(size=(n, 4)).astype(np.float32)
    index.add_batch([Pointer(i) for i in range(n)], vecs)
    assert len(index) == n
    for probe in (0, 127, 128, 255, 256, 299):
        res = index.search([(Pointer(10**6), vecs[probe], 1, None)])
        assert res[0] and res[0][0][0] == Pointer(probe), (probe, res)


def test_knn_add_batch_duplicates_and_filter():
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex

    index = BruteForceKnnIndex(4)
    vecs = np.eye(4, dtype=np.float32)
    # duplicate key in one batch: last write wins, no spurious grow
    index.add_batch([Pointer(1), Pointer(1)], vecs[:2],
                    filter_data=[{"tag": "a"}, {"tag": "b"}])
    assert len(index) == 1 and index.capacity == 1024
    res = index.search([(Pointer(9), vecs[1], 1, lambda d: d["tag"] == "b")])
    assert res[0] and res[0][0][0] == Pointer(1)
    index.add_batch([], np.zeros((0, 4), np.float32))  # no-op
    with pytest.raises(ValueError):
        index.add_batch([Pointer(2)], vecs[:2])


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 8


def test_graft_entry_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
