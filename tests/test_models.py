"""Flagship encoder model + tokenizer + training step tests (CPU backend)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pathway_tpu.models.encoder import EncoderConfig, encode, init_params
from pathway_tpu.models.tokenizer import HashTokenizer
from pathway_tpu.models.train import (
    contrastive_train_step,
    init_train_state,
    make_optimizer,
)


@pytest.fixture(scope="module")
def tiny():
    config = EncoderConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), config)
    return config, params


def _batch(config, n=4, s=12, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, config.vocab_size, (n, s)).astype(np.int32)
    mask = np.ones((n, s), dtype=bool)
    return ids, mask


def test_encode_shape_and_norm(tiny):
    config, params = tiny
    ids, mask = _batch(config)
    out = encode(params, ids, mask, config=config)
    assert out.shape == (4, config.hidden)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=1),
                               1.0, atol=1e-3)


def test_encode_deterministic(tiny):
    config, params = tiny
    ids, mask = _batch(config)
    a = np.asarray(encode(params, ids, mask, config=config))
    b = np.asarray(encode(params, ids, mask, config=config))
    np.testing.assert_array_equal(a, b)


def test_gelu_mode_selection_and_accuracy(tiny):
    """bf16 compute auto-selects tanh-gelu (the fusion-friendly fast path,
    see EncoderConfig.gelu); the swap must stay below bf16 quantization
    noise, and f32 compute must keep BERT's exact erf (auto ≡ erf there,
    pinning checkpoint-golden parity)."""
    import dataclasses

    config, params = tiny
    ids, mask = _batch(config)
    auto_bf = np.asarray(encode(params, ids, mask, config=config))
    erf_bf = np.asarray(encode(
        params, ids, mask, config=dataclasses.replace(config, gelu="erf")))
    tanh_bf = np.asarray(encode(
        params, ids, mask, config=dataclasses.replace(config, gelu="tanh")))
    np.testing.assert_array_equal(auto_bf, tanh_bf)  # auto == tanh in bf16
    cos = np.sum(erf_bf * tanh_bf, axis=1)  # outputs are L2-normalized
    assert cos.min() > 0.9999, f"tanh-gelu swap drifted: cos={cos.min()}"

    f32 = dataclasses.replace(config, compute_dtype=jnp.float32)
    auto_f32 = np.asarray(encode(params, ids, mask, config=f32))
    erf_f32 = np.asarray(encode(
        params, ids, mask, config=dataclasses.replace(f32, gelu="erf")))
    np.testing.assert_array_equal(auto_f32, erf_f32)  # auto == erf in f32


def test_encode_padding_invariance(tiny):
    """Padding tokens must not change the (mean-pooled) embedding."""
    config = EncoderConfig.tiny(pooling="mean")
    params = init_params(jax.random.PRNGKey(0), config)
    ids, mask = _batch(config, n=2, s=8)
    padded_ids = np.concatenate([ids, np.zeros((2, 8), np.int32)], axis=1)
    padded_mask = np.concatenate([mask, np.zeros((2, 8), bool)], axis=1)
    a = np.asarray(encode(params, ids, mask, config=config))
    b = np.asarray(encode(params, padded_ids, padded_mask, config=config))
    np.testing.assert_allclose(a, b, atol=2e-2)


def test_moe_encode_runs():
    config = EncoderConfig.tiny(num_experts=4)
    params = init_params(jax.random.PRNGKey(1), config)
    ids, mask = _batch(config)
    out = np.asarray(encode(params, ids, mask, config=config))
    assert np.isfinite(out).all()


def test_tokenizer_stable_and_padded():
    tok = HashTokenizer(vocab_size=1024, max_len=16)
    a = tok.encode("hello world")
    b = tok.encode("hello world")
    assert a == b
    assert a[0] == 101 and a[-1] == 102
    ids, mask = tok.batch(["one two three", "one"], pad_to=8)
    assert ids.shape == (2, 8)
    assert mask[0].sum() == 5 and mask[1].sum() == 3  # CLS + words + SEP
    # same word → same id across instances (cache-independent)
    tok2 = HashTokenizer(vocab_size=1024)
    assert tok2.encode("hello world") == a


def test_train_step_reduces_loss(tiny):
    config, _ = tiny
    opt = make_optimizer(learning_rate=1e-3)
    state = init_train_state(jax.random.PRNGKey(0), config, opt)
    rng = np.random.default_rng(0)
    batch = {
        "q_ids": rng.integers(0, config.vocab_size, (8, 10)).astype(np.int32),
        "q_mask": np.ones((8, 10), bool),
        "d_ids": rng.integers(0, config.vocab_size, (8, 10)).astype(np.int32),
        "d_mask": np.ones((8, 10), bool),
    }
    step = jax.jit(lambda s, b: contrastive_train_step(
        s, b, config=config, optimizer=opt))
    losses = []
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5


def test_knn_add_batch_matches_add():
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(40, 8)).astype(np.float32)
    a = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)
    b = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)
    for i in range(40):
        a.add(Pointer(i), vecs[i])
    b.add_batch([Pointer(i) for i in range(40)], vecs)
    q = [(Pointer(99), vecs[7], 5, None)]
    assert a.search(q) == b.search(q)
    # overwrite semantics: re-adding a key replaces its vector
    b.add_batch([Pointer(7)], vecs[8:9])
    res = b.search([(Pointer(99), vecs[8], 1, None)])
    assert res[0][0][0] in (Pointer(7), Pointer(8))


def test_sharded_knn_add_batch_grow_remap():
    """Regression: a grow mid-batch remaps slots; every row must stay findable."""
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh
    from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex

    mesh = make_mesh(MeshConfig(data=2, model=1))
    index = ShardedKnnIndex(4, mesh=mesh)  # cap 128/shard → 256 total
    rng = np.random.default_rng(0)
    n = 300  # forces a grow inside one add_batch
    vecs = rng.normal(size=(n, 4)).astype(np.float32)
    index.add_batch([Pointer(i) for i in range(n)], vecs)
    assert len(index) == n
    for probe in (0, 127, 128, 255, 256, 299):
        res = index.search([(Pointer(10**6), vecs[probe], 1, None)])
        assert res[0] and res[0][0][0] == Pointer(probe), (probe, res)


def test_knn_add_batch_duplicates_and_filter():
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex

    index = BruteForceKnnIndex(4)
    vecs = np.eye(4, dtype=np.float32)
    # duplicate key in one batch: last write wins, no spurious grow
    index.add_batch([Pointer(1), Pointer(1)], vecs[:2],
                    filter_data=[{"tag": "a"}, {"tag": "b"}])
    assert len(index) == 1 and index.capacity == 1024
    res = index.search([(Pointer(9), vecs[1], 1, lambda d: d["tag"] == "b")])
    assert res[0] and res[0][0][0] == Pointer(1)
    index.add_batch([], np.zeros((0, 4), np.float32))  # no-op
    with pytest.raises(ValueError):
        index.add_batch([Pointer(2)], vecs[:2])


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 8


def test_graft_entry_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_knn_bf16_recall_parity_with_f32():
    """bf16 slab (the 10M-fit dtype): top-10 must agree with f32 within
    normal low-precision slack on well-separated random data."""
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(1)
    n, d = 2048, 64
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    queries = rng.normal(size=(8, d)).astype(np.float32)
    for metric in (KnnMetric.L2SQ, KnnMetric.COS):
        f32 = BruteForceKnnIndex(d, metric=metric, reserved_space=n)
        b16 = BruteForceKnnIndex(d, metric=metric, reserved_space=n,
                                 dtype="bfloat16")
        keys = [Pointer(i) for i in range(n)]
        f32.add_batch(keys, vecs)
        b16.add_batch(keys, vecs)
        q = [(Pointer(10_000 + i), queries[i], 10, None) for i in range(8)]
        rf = f32.search(q)
        rb = b16.search(q)
        for got_f, got_b in zip(rf, rb):
            exact = {k for k, _ in got_f}
            approx = {k for k, _ in got_b}
            recall = len(exact & approx) / len(exact)
            assert recall >= 0.8, (metric, recall)
        # top-1 must match exactly on this well-separated data
        assert all(rb[i][0][0] == rf[i][0][0] for i in range(8))


def test_knn_int8_recall_parity_with_f32():
    """int8 slab (half of bf16's bytes; per-row symmetric quantization in
    the device scatter): top-10 must agree with f32 within quantization
    slack, and top-1 exactly, on well-separated random data — for both
    metrics (COS needs no scales in-kernel, L2SQ folds them in)."""
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(3)
    n, d = 2048, 64
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    queries = rng.normal(size=(8, d)).astype(np.float32)
    for metric in (KnnMetric.L2SQ, KnnMetric.COS):
        f32 = BruteForceKnnIndex(d, metric=metric, reserved_space=n)
        i8 = BruteForceKnnIndex(d, metric=metric, reserved_space=n,
                                dtype="int8")
        keys = [Pointer(i) for i in range(n)]
        f32.add_batch(keys, vecs)
        i8.add_batch(keys, vecs)
        q = [(Pointer(10_000 + i), queries[i], 10, None) for i in range(8)]
        rf = f32.search(q)
        ri = i8.search(q)
        for got_f, got_i in zip(rf, ri):
            exact = {k for k, _ in got_f}
            approx = {k for k, _ in got_i}
            recall = len(exact & approx) / len(exact)
            assert recall >= 0.8, (metric, recall)
        # top-1 mostly agrees; an exact all-8 assert would hinge on
        # neighbor gaps exceeding ~1e-2 quantization error for this seed
        agree = sum(ri[i][0][0] == rf[i][0][0] for i in range(8))
        assert agree >= 6, (metric, agree)


def test_knn_int8_update_remove_and_mirror_sync():
    """int8 index lifecycle: updates overwrite (new quantized row wins),
    removes drop rows from results, and the device→host mirror sync
    dequantizes (add_batch_device rows read back within quantization
    error)."""
    import jax.numpy as jnp

    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    d = 16
    idx = BruteForceKnnIndex(d, metric=KnnMetric.COS, reserved_space=64,
                             dtype="int8")
    e = np.eye(d, dtype=np.float32)
    idx.add(Pointer(0), e[0])
    idx.add(Pointer(1), e[1])
    (res,) = idx.search([(Pointer(99), e[0], 1, None)])
    assert res[0][0] == Pointer(0)
    idx.add(Pointer(0), e[2])  # update: row 0 now points along axis 2
    (res,) = idx.search([(Pointer(99), e[2], 1, None)])
    assert res[0][0] == Pointer(0)
    (res,) = idx.search([(Pointer(99), e[0], 2, None)])
    assert all(score > 0.5 for _, score in res)  # nothing near e0 now
    idx.remove(Pointer(1))
    (res,) = idx.search([(Pointer(99), e[1], 2, None)])
    assert Pointer(1) not in {k for k, _ in res}

    # device-born rows: mirror sync must dequantize
    rows = np.stack([e[5] * 3.0, e[6] * 0.25]).astype(np.float32)
    idx.add_batch_device([Pointer(5), Pointer(6)], jnp.asarray(rows))
    idx._sync_mirror()
    got = idx._host_vectors[[idx._key_to_slot[Pointer(5)],
                             idx._key_to_slot[Pointer(6)]]]
    np.testing.assert_allclose(got, rows, rtol=0.02, atol=1e-6)

    # fused ingest (producer output quantized in the same donated
    # dispatch): a produced row must retrieve itself
    fused = BruteForceKnnIndex(d, metric=KnnMetric.COS, reserved_space=64,
                               dtype="int8")
    ingest = fused.make_fused_ingest(lambda x: x * 2.0 + 0.1)
    base = np.stack([e[1], e[3]]).astype(np.float32)
    ingest([Pointer(1), Pointer(3)], jnp.asarray(base))
    (res,) = fused.search([(Pointer(99), base[1] * 2.0 + 0.1, 1, None)])
    assert res[0][0] == Pointer(3)


def test_knn_int8_grow_requantizes_from_mirror():
    """Host-path growth past reserved capacity: the f32 mirror is
    authoritative, the device slab (incl. scales/vsq) is rebuilt by
    re-quantization, and search still finds exact self-neighbors."""
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(11)
    d = 8
    idx = BruteForceKnnIndex(d, metric=KnnMetric.COS, reserved_space=32,
                             dtype="int8")
    base_cap = idx.capacity
    n = base_cap + 500  # force at least one doubling
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    idx.add_batch([Pointer(i) for i in range(n)], vecs)
    assert idx.capacity > base_cap
    for probe_i in (0, base_cap, n - 1):  # rows from before AND after grow
        (res,) = idx.search([(Pointer(10**6), vecs[probe_i], 1, None)])
        assert res[0][0] == Pointer(probe_i), probe_i


def test_knn_chunked_scan_matches_single_shot(monkeypatch):
    """Force the chunked lax.scan path with a tiny chunk size: results
    must be identical to the single-matmul path (it is exact, not
    approximate)."""
    import pathway_tpu.ops.knn as knn_mod
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(2)
    n, d = 700, 16
    vecs = rng.normal(size=(n, d)).astype(np.float32)
    queries = rng.normal(size=(5, d)).astype(np.float32)

    plain = BruteForceKnnIndex(d, metric=KnnMetric.L2SQ, reserved_space=1024)
    monkeypatch.setattr(knn_mod, "_CHUNK_ROWS", 256)
    chunked = BruteForceKnnIndex(d, metric=KnnMetric.L2SQ,
                                 reserved_space=1024)
    assert chunked.capacity % 256 == 0 and chunked.capacity > 256

    keys = [Pointer(i) for i in range(n)]
    plain.add_batch(keys, vecs)
    chunked.add_batch(keys, vecs)
    # remove some rows so validity masking crosses chunk boundaries
    for i in range(0, n, 7):
        plain.remove(Pointer(i))
        chunked.remove(Pointer(i))
    q = [(Pointer(10_000 + i), queries[i], 12, None) for i in range(5)]
    res_p = plain.search(q)
    res_c = chunked.search(q)
    for a, b in zip(res_p, res_c):
        assert [k for k, _ in a] == [k for k, _ in b]
        assert np.allclose([s for _, s in a], [s for _, s in b],
                           rtol=1e-4, atol=1e-4)


def test_knn_grow_after_flush_keeps_old_rows():
    """Regression: _grow() after a flush must re-ship every occupied slot —
    the zero-slab+scatter flush path only uploads dirty rows."""
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    idx = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)  # capacity 1024
    rng = np.random.default_rng(3)
    vecs = rng.normal(size=(1024, 8)).astype(np.float32)
    idx.add_batch([Pointer(i) for i in range(1024)], vecs)
    res = idx.search([(Pointer(10**9), vecs[0], 1, None)])
    assert res[0][0][0] == Pointer(0)  # flush happened
    more = rng.normal(size=(10, 8)).astype(np.float32)
    idx.add_batch([Pointer(2000 + i) for i in range(10)], more)  # grows
    assert idx.capacity > 1024
    res = idx.search([(Pointer(10**9), vecs[0], 1, None)])
    assert res[0][0][0] == Pointer(0), "pre-grow row lost from device slab"
    res2 = idx.search([(Pointer(10**9), more[3], 1, None)])
    assert res2[0][0][0] == Pointer(2003)


def test_knn_selective_filter_beyond_chunk_cap(monkeypatch):
    """A filter rejecting every top candidate up to the chunk cap must
    still return the matching rows (host-side exhaustive fallback)."""
    import pathway_tpu.ops.knn as knn_mod
    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    monkeypatch.setattr(knn_mod, "_CHUNK_ROWS", 128)
    idx = BruteForceKnnIndex(4, metric=KnnMetric.L2SQ, reserved_space=1024)
    rng = np.random.default_rng(4)
    n = 700
    vecs = rng.normal(size=(n, 4)).astype(np.float32)
    # only the 3 FARTHEST rows from the query pass the filter
    q = vecs[0] + 100.0
    dists = np.sum((vecs - q) ** 2, axis=1)
    allowed = set(np.argsort(dists)[-3:].tolist())
    idx.add_batch([Pointer(i) for i in range(n)], vecs,
                  filter_data=[{"ok": i in allowed} for i in range(n)])
    res = idx.search([(Pointer(10**9), q, 3,
                       lambda d: bool(d and d["ok"]))])[0]
    assert {int(k) for k, _ in res} == allowed


def test_knn_add_batch_device_matches_host_path():
    """Device-to-device adds must be search-equivalent to host adds, and
    the lazy mirror must survive grow + host-side exact reads."""
    import jax.numpy as jnp

    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric

    rng = np.random.default_rng(5)
    vecs = rng.normal(size=(300, 8)).astype(np.float32)
    host = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)
    dev = BruteForceKnnIndex(8, metric=KnnMetric.L2SQ)
    keys = [Pointer(i) for i in range(300)]
    host.add_batch(keys, vecs)
    dev.add_batch_device(keys, jnp.asarray(vecs))
    q = [(Pointer(900 + i), vecs[i * 7], 5, None) for i in range(4)]
    assert host.search(q) == dev.search(q)
    # grow after device adds: stale rows must be synced, not lost
    more = rng.normal(size=(800, 8)).astype(np.float32)
    dev.add_batch_device([Pointer(1000 + i) for i in range(800)],
                         jnp.asarray(more))
    assert dev.capacity > 1024
    res = dev.search([(Pointer(999), vecs[3], 1, None)])
    assert res[0][0][0] == Pointer(3)
    res2 = dev.search([(Pointer(999), more[11], 1, None)])
    assert res2[0][0][0] == Pointer(1011)
    # host-side exact read (filtered fallback) sees device-written rows
    dev2 = BruteForceKnnIndex(4, metric=KnnMetric.L2SQ)
    eye = np.eye(4, dtype=np.float32)
    dev2.add_batch_device([Pointer(i) for i in range(4)], jnp.asarray(eye))
    for i in range(4):
        dev2._filter_data[Pointer(i)] = {"ok": i == 2}
    got = dev2._exhaustive_filtered_search(eye[2], 1,
                                           lambda d: bool(d and d["ok"]))
    assert got[0][0] == Pointer(2)


@pytest.mark.parametrize("dim", [2048, 4096])
def test_quantize_i8_vsq_exact_past_dim_1040(dim):
    """vsq must equal the int-domain squared norm (rounded to float32 at
    most once) well past dim ~1040, where a sequential float32 accumulator
    starts rounding partial sums. dim 4096 breaks even numpy's pairwise
    float32 summation, so this pins int accumulation on every backend."""
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import _quantize_i8, _quantize_i8_np

    rng = np.random.default_rng(7)
    # adversarial magnitudes: every |q| near 127 maximizes the partial sums
    vecs = rng.uniform(0.9, 1.0, size=(16, dim)).astype(np.float32)
    vecs *= rng.choice([-1.0, 1.0], size=vecs.shape).astype(np.float32)

    for q, _, vsq in (_quantize_i8_np(vecs),
                      tuple(np.asarray(x) for x in
                            _quantize_i8(jnp.asarray(vecs)))):
        exact = np.sum(q.astype(np.int64) ** 2, axis=1)
        np.testing.assert_array_equal(vsq, exact.astype(np.float32))
