"""Bounded-time crash recovery: operator-state snapshots + WAL compaction
(engine/persistence.py snapshot tier, engine/graph.py scheduler hooks,
engine/streaming.py snapshot pass).

Proves the PR-10 acceptance contract:
- a run restored from snapshot + WAL-suffix replay produces output
  byte-identical to full-WAL replay and to a clean synchronous run, at
  random crash points including the NEW snapshot/compaction boundaries
  (``persistence.snapshot.write``, ``persistence.compact.truncate``);
- a corrupt newest snapshot falls back one generation (the WAL keeps the
  suffix back to the oldest retained generation);
- compaction truncates exactly the covered prefix (``MockLog`` grows the
  same truncate API so this is unit-testable without a filesystem);
- a mid-log corrupt record (not just a torn tail) is detected by the
  per-record CRC and truncated at, loudly;
- clean shutdown of an idle stream writes no empty generations.
"""

from __future__ import annotations

import glob
import json
import os
import random
import shutil

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import faults
from pathway_tpu.testing.faults import InjectedFault, flaky_subject

WORDS = ["a", "b", "a", "c", "b", "a"]


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    faults.reset()
    yield
    G.clear()
    faults.reset()


def _rows(words):
    return [{"word": w} for w in words]


def _run_counts_with_device_leg(subject, *, inflight, monkeypatch,
                                backend=None, **run_kwargs):
    """Word-count pipeline with a traceable device UDF, so the snapshot
    pass exercises the watermark wait against a real bridge."""
    import numpy as np

    monkeypatch.setenv("PATHWAY_DEVICE_INFLIGHT", str(inflight))
    G.clear()

    @pw.udf(batch=True, device=True, deterministic=True, return_type=int)
    def dev_len(ws):
        import jax.numpy as jnp

        arr = jnp.asarray(np.asarray([len(w) for w in ws], np.int32))
        return [int(v) for v in np.asarray(arr)]

    t = pw.io.python.read(
        subject, schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=10, persistent_id="snap-words")
    t = t.select(word=t.word, wl=dev_len(t.word))
    counts = t.groupby(t.word).reduce(word=t.word, c=pw.reducers.count())
    state: dict[str, int] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            state[row["word"]] = row["c"]
        elif state.get(row["word"]) == row["c"]:
            del state[row["word"]]

    pw.io.subscribe(counts, on_change)
    cfg = None
    if backend is not None:
        cfg = pw.persistence.Config.simple_config(backend)
    pw.run(persistence_config=cfg, **run_kwargs)
    return state


def _as_bytes(state: dict) -> bytes:
    return json.dumps(sorted(state.items())).encode()


# ---------------------------------------------------------------------------
# log-level units: truncation + per-record CRC
# ---------------------------------------------------------------------------

def test_mocklog_truncate_drops_covered_records_in_place():
    from pathway_tpu.engine.persistence import MockLog

    store: dict = {}
    log = MockLog(store, "s")
    log.append(1, [("k1", ("a",), 1, None)])
    log.append(3, [("k2", ("b",), 1, None), ("k3", ("c",), 1, None)])
    log.append(5, [("k4", ("d",), 1, None)])
    alias = store["s"]  # other holders of the list must see the compaction
    assert log.truncate_to(3) == 3
    assert [t for t, _ in store["s"]] == [5]
    assert alias is store["s"]
    assert log.truncate_to(3) == 0  # idempotent: nothing left to drop


def test_snapshotlog_truncate_keeps_suffix_and_appends_continue(tmp_path):
    from pathway_tpu.engine.persistence import SnapshotLog

    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    log.append(4, [("k2", ("b",), 1, None)])
    log.append(6, [("k3", ("c",), 1, None)])
    assert log.truncate_to(4) == 2
    assert [t for t, _ in SnapshotLog(path).read_all()] == [6]
    # the log stays appendable after the atomic rewrite
    log.append(8, [("k4", ("d",), 1, None)])
    log.close()
    assert [t for t, _ in SnapshotLog(path).read_all()] == [6, 8]


def test_midlog_corruption_truncates_at_first_bad_record_loudly(
        tmp_path, caplog):
    """A corrupted record WITH records behind it is mid-log corruption:
    per-record CRC catches it before the unpickler, recovery truncates at
    the first bad record and says so at ERROR level (a torn tail stays a
    quiet warning)."""
    from pathway_tpu.engine.persistence import _HDR, _MAGIC, SnapshotLog

    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    log.append(2, [("k2", ("b",), 1, None)])
    log.append(3, [("k3", ("c",), 1, None)])
    log.close()
    with open(path, "rb") as f:
        data = bytearray(f.read())
    # flip a payload byte of the SECOND record
    pos = len(_MAGIC)
    length, _crc = _HDR.unpack_from(data, pos)
    second = pos + _HDR.size + length
    data[second + _HDR.size] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(data))
    import logging

    with caplog.at_level(logging.ERROR,
                         logger="pathway_tpu.engine.persistence"):
        records = SnapshotLog(path).read_all()
    assert [t for t, _ in records] == [1]  # truncated at the bad record
    assert any("mid-log" in r.message for r in caplog.records)


def test_append_corrupt_fault_point_writes_detectable_corruption(tmp_path):
    from pathway_tpu.engine.persistence import SnapshotLog

    path = str(tmp_path / "s.snap")
    log = SnapshotLog(path)
    log.append(1, [("k1", ("a",), 1, None)])
    action = faults.CorruptPayload(k=1)
    with faults.arm("persistence.append.corrupt", action):
        log.append(2, [("k2", ("b",), 1, None)])
    log.append(3, [("k3", ("c",), 1, None)])
    log.close()
    assert action.corrupted == 1
    # the corrupt record (and, mid-log, everything after it) is dropped —
    # never fed to the unpickler
    assert [t for t, _ in SnapshotLog(path).read_all()] == [1]


# ---------------------------------------------------------------------------
# driver-level: snapshot write, compaction, retention (mock backend —
# no filesystem needed, per the MockLog satellite)
# ---------------------------------------------------------------------------

def _driver_with_source(backend):
    from pathway_tpu.engine.persistence import PersistenceDriver
    from pathway_tpu.io._datasource import CallbackSource, Session

    driver = PersistenceDriver(pw.persistence.Config.simple_config(backend))
    src = CallbackSource(lambda: iter(()), pw.schema_from_types(x=int))
    src.persistent_id = "snap-unit"
    rec = driver.attach_source(src, Session())
    return driver, rec


def test_driver_snapshot_compacts_wal_and_manifests_coverage(monkeypatch):
    monkeypatch.setenv("PATHWAY_SNAPSHOT_KEEP_GENERATIONS", "1")
    backend = pw.persistence.Backend.mock()
    driver, rec = _driver_with_source(backend)
    for tick in (1, 2, 3):
        rec.push(f"k{tick}", (tick,), 1)
        driver.seal(tick)
        driver.commit(tick, watermark=tick)
    assert driver.wal_replayable_entries == 3
    assert driver.write_snapshot(3, {"nodes": {}}) is True
    # WAL truncated to the suffix past the (only) generation's tick
    assert backend._mock_store["snap-unit"] == []
    assert driver.wal_replayable_entries == 0
    assert driver.compactions_total == 1
    meta = backend._mock_snapshots[-1]
    assert meta["snapshot_tick"] == 3
    assert meta["sources"]["snap-unit"]["covered"] == 3
    # no-churn guard: the watermark did not advance -> no new generation
    assert driver.write_snapshot(3, {"nodes": {}}) is False
    assert len(backend._mock_snapshots) == 1
    # a fresh driver restores the snapshot tick as its durability frontier
    from pathway_tpu.engine.persistence import PersistenceDriver

    assert PersistenceDriver(
        pw.persistence.Config.simple_config(backend)).restore_time() == 3


def test_retention_truncates_only_to_oldest_kept_generation(monkeypatch):
    """KEEP_GENERATIONS=2: after generation N lands, the WAL keeps the
    suffix past generation N-1's tick — corrupt-N fallback to N-1 always
    finds its records."""
    monkeypatch.setenv("PATHWAY_SNAPSHOT_KEEP_GENERATIONS", "2")
    backend = pw.persistence.Backend.mock()
    driver, rec = _driver_with_source(backend)
    for tick in (1, 2, 3):
        rec.push(f"k{tick}", (tick,), 1)
        driver.seal(tick)
        driver.commit(tick, watermark=tick)
        assert driver.write_snapshot(tick, {"nodes": {}}) is True
    gens = [m["generation"] for m in backend._mock_snapshots]
    assert len(gens) == 2  # oldest pruned
    # WAL truncated to the OLDEST KEPT generation's tick (2), not 3 —
    # the tick-3 record is physically retained for gen-2 fallback, but a
    # normal-path restart (gen 3) replays nothing
    assert [t for t, _ in backend._mock_store["snap-unit"]] == [3]
    assert driver.wal_replayable_entries == 0


def test_corrupt_generation_never_occupies_a_retention_slot(monkeypatch):
    """A corrupt generation must not count toward KEEP_GENERATIONS: it
    would prune the valid fallback and truncate the WAL to a tick only
    the corrupt generation covers."""
    monkeypatch.setenv("PATHWAY_SNAPSHOT_KEEP_GENERATIONS", "2")
    backend = pw.persistence.Backend.mock()
    driver, rec = _driver_with_source(backend)
    for tick in (1, 2):
        rec.push(f"k{tick}", (tick,), 1)
        driver.seal(tick)
        driver.commit(tick, watermark=tick)
        assert driver.write_snapshot(tick, {"nodes": {}}) is True
    # corrupt generation 2's state blob in place (bit rot at rest)
    meta2 = backend._mock_snapshots[-1]
    assert meta2["generation"] == 2
    meta2["state"] = meta2["state"][:-1] + bytes(
        [meta2["state"][-1] ^ 0xFF])
    # a FRESH driver (no validity cache) writes generation 3
    from pathway_tpu.engine.persistence import PersistenceDriver

    d2 = PersistenceDriver(pw.persistence.Config.simple_config(backend))
    from pathway_tpu.io._datasource import CallbackSource, Session

    src = CallbackSource(lambda: iter(()), pw.schema_from_types(x=int))
    src.persistent_id = "snap-unit"
    rec2 = d2.attach_source(src, Session())
    # the prefix-replay protocol expects the reader to re-emit the two
    # covered entries first (skipped), then the genuinely new row
    rec2.push("k1", (1,), 1)
    rec2.push("k2", (2,), 1)
    rec2.push("k3", (3,), 1)
    d2.seal(3)
    d2.commit(3, watermark=3)
    assert d2.write_snapshot(3, {"nodes": {}}) is True
    kept = [m["generation"] for m in backend._mock_snapshots]
    assert kept == [1, 3]  # corrupt 2 pruned, VALID 1 kept as fallback
    # WAL truncated only to gen 1's tick: gen-1 fallback keeps records
    # (1, 3] — including tick 2, which only the corrupt gen covered
    assert [t for t, _ in backend._mock_store["snap-unit"]] == [2, 3]


def test_snapshot_skipped_cleanly_on_object_store_backends():
    """S3/azure backends keep WAL-only recovery: write_snapshot is a
    loud no-op, never an exception in the commit loop."""
    from pathway_tpu.engine.persistence import PersistenceDriver

    driver = PersistenceDriver.__new__(PersistenceDriver)
    driver.kind = "s3"
    driver.snapshots_supported = False
    driver._snapshot_warned = False
    driver.last_snapshot_tick = 0
    assert driver.write_snapshot(5, {"nodes": {}}) is False
    assert driver._snapshot_warned


# ---------------------------------------------------------------------------
# streaming-level recovery equivalence
# ---------------------------------------------------------------------------

def test_streaming_snapshot_restart_byte_identical(monkeypatch, tmp_path):
    """Restart restored from snapshot + suffix replay serializes to the
    identical subscriber state as the no-persistence baseline."""
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=2, monkeypatch=monkeypatch)
    assert baseline == {"a": 3, "b": 2, "c": 1}
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    first = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                      delay_s=0.02),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    assert _as_bytes(first) == _as_bytes(baseline)
    snaps = glob.glob(str(tmp_path / "p" / "snapshots" / "*.json"))
    assert snaps, "no snapshot generation was written"
    state = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    assert _as_bytes(state) == _as_bytes(baseline)


# every watermark/snapshot/compaction boundary the recovery path crosses
_SNAP_POINTS = ("bridge.leg.exec", "persistence.commit",
                "persistence.fsync", "persistence.snapshot.write",
                "persistence.compact.truncate")


def test_property_random_crash_points_snapshot_recovery(monkeypatch,
                                                        tmp_path):
    """Property test (seeded): for random crash points across the
    watermark AND snapshot/compaction boundaries, snapshot+suffix-replay
    recovery is byte-identical to the clean baseline — including crashes
    landing between snapshot-durable and WAL-truncate."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=1, monkeypatch=monkeypatch)
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    rng = random.Random(int(os.environ.get("SNAPSHOT_SWEEP_SEED", "7")))
    for round_i in range(5):
        backend = pw.persistence.Backend.filesystem(
            str(tmp_path / f"p{round_i}"))
        point = rng.choice(_SNAP_POINTS)
        k = rng.randint(1, 6)
        with faults.arm(point, faults.FailOnHit(k)):
            try:
                _run_counts_with_device_leg(
                    flaky_subject(_rows(WORDS), fail_after=0,
                                  fail_attempts=0, delay_s=0.02),
                    inflight=4, monkeypatch=monkeypatch, backend=backend,
                    terminate_on_error=True)
            except InjectedFault:
                pass  # the seeded crash
        faults.reset()
        state = _run_counts_with_device_leg(
            flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
            inflight=4, monkeypatch=monkeypatch, backend=backend)
        assert _as_bytes(state) == _as_bytes(baseline), \
            f"round {round_i}: {point!r} hit {k}"


@pytest.mark.parametrize("inflight", [1, 2, 4])
@pytest.mark.parametrize("point", ["persistence.snapshot.write",
                                   "persistence.compact.truncate"])
def test_crash_sweep_snapshot_points_byte_identical(point, inflight,
                                                    monkeypatch, tmp_path):
    """The PR-8 crash sweep extended to the snapshot tier: a crash at
    either snapshot/compaction boundary, at any in-flight depth, recovers
    byte-identical exactly-once. (snapshot.write: the generation does not
    exist yet — previous generation + full WAL recover; compact.truncate:
    the generation exists and covered WAL records are ignored.)"""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=1, monkeypatch=monkeypatch)
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    k = 1 + (len(point) + inflight) % 2
    with faults.arm(point, faults.FailOnHit(k)):
        try:
            _run_counts_with_device_leg(
                flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                              delay_s=0.02),
                inflight=inflight, monkeypatch=monkeypatch,
                backend=backend, terminate_on_error=True)
        except InjectedFault:
            pass  # the crash (the point may not fire on quiet pacing)
    faults.reset()
    state = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=inflight, monkeypatch=monkeypatch, backend=backend)
    assert _as_bytes(state) == _as_bytes(baseline)


def test_crash_between_snapshot_durable_and_wal_truncate(monkeypatch,
                                                         tmp_path):
    """The compaction edge: generation N is durable but the WAL still
    holds covered records. Restart must load N and IGNORE them (replaying
    them on top of restored state would double-count)."""
    monkeypatch.setenv("PATHWAY_PERSISTENCE_WRITE_RETRIES", "0")
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=1, monkeypatch=monkeypatch)
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    with faults.arm("persistence.compact.truncate", faults.FailOnHit(1)):
        try:
            _run_counts_with_device_leg(
                flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                              delay_s=0.02),
                inflight=2, monkeypatch=monkeypatch, backend=backend,
                terminate_on_error=True)
        except InjectedFault:
            pass
    faults.reset()
    state = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    assert _as_bytes(state) == _as_bytes(baseline)


def test_corrupt_newest_snapshot_falls_back_one_generation(monkeypatch,
                                                           tmp_path,
                                                           caplog):
    """Checksum-verified load: a corrupt newest generation falls back to
    N-1 (whose WAL suffix the retention window preserved) and recovers
    byte-identically, logging the fallback."""
    baseline = _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=1, monkeypatch=monkeypatch)
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    monkeypatch.setenv("PATHWAY_SNAPSHOT_KEEP_GENERATIONS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                      delay_s=0.02),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    states = sorted(glob.glob(str(tmp_path / "p" / "snapshots" / "*.state")))
    assert len(states) >= 2, "test needs at least two generations"
    with open(states[-1], "r+b") as f:
        f.seek(16)
        b = f.read(1)
        f.seek(16)
        f.write(bytes([b[0] ^ 0xFF]))
    import logging

    with caplog.at_level(logging.ERROR,
                         logger="pathway_tpu.engine.persistence"):
        state = _run_counts_with_device_leg(
            flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
            inflight=2, monkeypatch=monkeypatch, backend=backend)
    assert _as_bytes(state) == _as_bytes(baseline)
    assert any("falling back one generation" in r.message
               for r in caplog.records)


def test_snapshot_suffix_replay_equals_full_wal_replay(monkeypatch,
                                                       tmp_path):
    """With compaction off, the same persistence root recovers two ways —
    snapshot+suffix vs full-WAL (PATHWAY_SNAPSHOT_RESTORE=0) — and the
    serialized states are byte-identical."""
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    monkeypatch.setenv("PATHWAY_SNAPSHOT_COMPACT", "0")
    root = tmp_path / "p"
    backend = pw.persistence.Backend.filesystem(str(root))
    _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                      delay_s=0.02),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    assert glob.glob(str(root / "snapshots" / "*.json"))
    root2 = tmp_path / "p2"
    shutil.copytree(root, root2)
    empty = flaky_subject([], fail_after=99, fail_attempts=0)
    via_snapshot = _run_counts_with_device_leg(
        empty, inflight=2, monkeypatch=monkeypatch,
        backend=pw.persistence.Backend.filesystem(str(root)))
    monkeypatch.setenv("PATHWAY_SNAPSHOT_RESTORE", "0")
    empty2 = flaky_subject([], fail_after=99, fail_attempts=0)
    via_wal = _run_counts_with_device_leg(
        empty2, inflight=2, monkeypatch=monkeypatch,
        backend=pw.persistence.Backend.filesystem(str(root2)))
    assert _as_bytes(via_snapshot) == _as_bytes(via_wal)
    assert via_snapshot == {"a": 3, "b": 2, "c": 1}


def test_idle_shutdown_writes_no_empty_generation(monkeypatch, tmp_path):
    """Clean shutdown with no new durable data since the last snapshot
    must not churn a new generation (PersistenceDriver close-path
    guard)."""
    monkeypatch.setenv("PATHWAY_SNAPSHOT_EVERY_TICKS", "2")
    backend = pw.persistence.Backend.filesystem(str(tmp_path / "p"))
    _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0,
                      delay_s=0.02),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    before = sorted(glob.glob(str(tmp_path / "p" / "snapshots" / "*.json")))
    assert before
    # rerun: the reader re-emits the identical prefix, all skipped — no
    # new durable entries, so no new generation
    _run_counts_with_device_leg(
        flaky_subject(_rows(WORDS), fail_after=0, fail_attempts=0),
        inflight=2, monkeypatch=monkeypatch, backend=backend)
    after = sorted(glob.glob(str(tmp_path / "p" / "snapshots" / "*.json")))
    assert after == before


# ---------------------------------------------------------------------------
# operator/index capture units
# ---------------------------------------------------------------------------

def test_multiset_reducer_state_rekeys_fingerprints_on_load():
    """Fingerprint-keyed reducer state must re-key on restore: string
    hash() varies with the process hash seed, so a snapshot restored in a
    new interpreter would otherwise never match later retractions. The
    fake foreign fingerprints below stand in for another process's."""
    from pathway_tpu.engine.delta import row_fingerprint
    from pathway_tpu.engine.reducers import _MaxState

    st = _MaxState()
    st.add(("apple",), 1)
    st.add(("pear",), 1)
    dumped = st.state_dict()
    # simulate a foreign hash seed: shift every stored fingerprint
    dumped["counts"] = {fp + 1: c for fp, c in dumped["counts"].items()}
    dumped["values"] = {fp + 1: v for fp, v in dumped["values"].items()}
    fresh = _MaxState()
    fresh.load_state(dumped)
    assert set(fresh.values) == {row_fingerprint(("apple",)),
                                 row_fingerprint(("pear",))}
    fresh.add(("pear",), -1)  # the retraction must find its entry
    assert fresh.emit() == "apple"


def test_buffer_operator_rekeys_held_rows_on_restore():
    from pathway_tpu.engine.delta import Delta, row_fingerprint
    from pathway_tpu.engine.temporal_ops import BufferOperator

    op = BufferOperator(threshold_fn=lambda k, r: 100,
                        time_fn=lambda k, r: 1)
    op.step(1, [Delta([("k1", ("x", 100), 1)])])  # held: threshold ahead
    assert op.held
    dumped = op.snapshot_state()
    dumped["held"] = {(k, fp + 1): v
                      for (k, fp), v in dumped["held"].items()}
    fresh = BufferOperator(threshold_fn=lambda k, r: 100,
                           time_fn=lambda k, r: 1)
    fresh.restore_state(dumped)
    assert set(fresh.held) == {("k1", row_fingerprint(("x", 100)))}
    # a retraction of the held row cancels it instead of leaking
    out = fresh.step(2, [Delta([("k1", ("x", 100), -1)])])
    assert not out.entries
    assert not fresh.held


def test_knn_index_snapshot_restores_search_identical():
    import numpy as np

    from pathway_tpu.internals.keys import Pointer
    from pathway_tpu.ops.knn import BruteForceKnnIndex

    rng = np.random.default_rng(3)
    idx = BruteForceKnnIndex(dimensions=16, reserved_space=64)
    keys = [Pointer(i) for i in range(40)]
    vecs = rng.standard_normal((40, 16)).astype(np.float32)
    idx.add_batch(keys, vecs, [{"tag": i % 2} for i in range(40)])
    queries = [(Pointer(1000 + i),
                rng.standard_normal(16).astype(np.float32), 3, None)
               for i in range(4)]
    want = idx.search(queries)
    state = idx.snapshot_state()
    fresh = BruteForceKnnIndex(dimensions=16, reserved_space=8)
    fresh.restore_state(state)
    got = fresh.search(queries)
    assert [tuple(r) for r in got] == [tuple(r) for r in want]
    assert fresh._filter_data[Pointer(3)] == {"tag": 1}


def test_unsupported_index_raises_snapshot_unsupported():
    from pathway_tpu.engine.index_ops import ExternalIndexOperator
    from pathway_tpu.engine.operators import SnapshotUnsupported

    class _NoHooks:
        def add(self, *a): ...

        def remove(self, *a): ...

        def search(self, *a):
            return []

    op = ExternalIndexOperator(_NoHooks(), data_vec_pos=0,
                               data_filter_pos=None, query_vec_pos=0,
                               query_limit_pos=None, query_filter_pos=None)
    with pytest.raises(SnapshotUnsupported):
        op.snapshot_state()


def test_stats_and_metrics_expose_snapshot_tier(monkeypatch):
    backend = pw.persistence.Backend.mock()
    driver, rec = _driver_with_source(backend)
    rec.push("k1", (1,), 1)
    driver.seal(1)
    driver.commit(1, watermark=1)
    driver.write_snapshot(1, {"nodes": {}})
    st = driver.stats()
    assert st["snapshot_tick"] == 1
    assert st["snapshot_generation"] == 1
    assert st["snapshots_total"] == 1
    assert st["snapshot_age_ticks"] == 0
    assert st["wal_replayable_entries"] == 0
    assert st["compactions_total"] == 1
