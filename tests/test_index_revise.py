"""DataIndex.query revise-on-update semantics vs query_as_of_now
(reference: stdlib/indexing/data_index.py — query revises, as_of_now
freezes; engine/index_ops.py revise flag)."""

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from tests.utils import T, rows_of


def _setup():
    docs = T("""
    text            | __time__
    alpha_one       | 2
    beta_two        | 2
    alpha_three     | 6
    """).select(text=pw.apply(lambda s: s.replace("_", " "), pw.this.text))
    queries = T("""
    q     | k | __time__
    alpha | 2 | 4
    """).select(q=pw.this.q, k=pw.this.k)
    return docs, queries


def test_query_revises_when_data_changes():
    docs, queries = _setup()
    index = DataIndex(docs, TantivyBM25(docs.text))
    res = index.query(queries.q, number_of_matches=queries.k)
    out = res.select(hit=res.text)
    # final state: the query (arrived t=4) sees the doc added at t=6 too
    [(hits,)] = rows_of(out)
    assert set(hits) == {"alpha one", "alpha three"}


def test_query_as_of_now_freezes():
    docs, queries = _setup()
    index = DataIndex(docs, TantivyBM25(docs.text))
    res = index.query_as_of_now(queries.q, number_of_matches=queries.k)
    out = res.select(hit=res.text)
    # answered at t=4: only the docs existing then; never revised at t=6
    [(hits,)] = rows_of(out)
    assert set(hits) == {"alpha one"}


def test_query_revision_emits_retractions():
    from pathway_tpu.internals.runner import run_tables

    docs, queries = _setup()
    index = DataIndex(docs, TantivyBM25(docs.text))
    res = index.query(queries.q, number_of_matches=queries.k)
    out = res.select(hit=res.text)
    [cap] = run_tables(out)
    events = cap.consolidated_events()
    # at t=6 the old single-hit reply row set must be revised incrementally
    times = sorted({t for _, _, t, _ in events})
    assert 6 in times
    retractions = [e for e in events if e[3] < 0]
    assert retractions, "data change must retract superseded reply rows"
