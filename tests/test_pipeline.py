"""Pipeline parallelism (parallel/pipeline.py): GPipe schedule over a
``pipe`` mesh axis must reproduce sequential layer application exactly."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pathway_tpu.models.encoder import EncoderConfig, init_params
from pathway_tpu.parallel.pipeline import (pipeline_encoder_blocks,
                                           sequential_encoder_blocks,
                                           stack_stage_params)


def _pipe_mesh(n: int):
    devices = jax.devices()[:n]
    if len(devices) < n:
        pytest.skip(f"needs {n} virtual devices")
    return jax.sharding.Mesh(np.asarray(devices), ("pipe",))


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4), (4, 7)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    config = EncoderConfig.tiny(layers=4, heads=4)
    params = init_params(jax.random.PRNGKey(0), config)
    mesh = _pipe_mesh(n_stages)
    run = pipeline_encoder_blocks(mesh, config)
    stacked = stack_stage_params(params["layers"])

    mb, seq, hidden = 2, 8, config.hidden
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_micro, mb, seq, hidden)),
                    jnp.float32)
    mask = jnp.ones((mb, seq), bool)

    got = run(stacked, x, mask)
    assert got.shape == x.shape
    want = jnp.stack([
        sequential_encoder_blocks(params["layers"], x[i], mask, config)
        for i in range(n_micro)
    ])
    # blocks compute in bf16 and the pipelined schedule reduces in a
    # different order than the sequential loop; across XLA versions the
    # worst element lands ~4 bf16 ulps apart, so allow 3% not 2%
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-2, rtol=3e-2)


def test_pipeline_requires_even_layer_split():
    config = EncoderConfig.tiny(layers=3, heads=4)
    params = init_params(jax.random.PRNGKey(0), config)
    mesh = _pipe_mesh(2)
    run = pipeline_encoder_blocks(mesh, config)
    stacked = stack_stage_params(params["layers"])
    x = jnp.zeros((2, 1, 4, config.hidden), jnp.float32)
    mask = jnp.ones((1, 4), bool)
    with pytest.raises(Exception):
        run(stacked, x, mask)
