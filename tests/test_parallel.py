"""Multi-chip sharding tests on the 8-device virtual CPU mesh
(SURVEY §4: stand-in for the reference's fork-based multi-process tests)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pathway_tpu.internals.keys import Pointer
from pathway_tpu.ops.knn import KnnMetric
from pathway_tpu.parallel import (
    MeshConfig,
    ShardedKnnIndex,
    make_mesh,
    ring_attention,
    ulysses_attention,
    use_mesh,
)
from pathway_tpu.parallel.ring_attention import reference_attention


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(MeshConfig(data=8, model=1))


@pytest.fixture(scope="module")
def mesh42():
    return make_mesh(MeshConfig(data=4, model=2))


def test_mesh_shapes(mesh8, mesh42):
    assert mesh8.shape["data"] == 8 and mesh8.shape["model"] == 1
    assert mesh42.shape["data"] == 4 and mesh42.shape["model"] == 2


# ---------------------------------------------------------------------------
# shard_map version shim: BOTH branches must keep working so a jax upgrade
# cannot silently break the fallback (new jax: top-level jax.shard_map with
# check_vma; old jax: jax.experimental.shard_map with check_rep)
# ---------------------------------------------------------------------------

def test_shard_map_shim_new_api_branch(monkeypatch, mesh8):
    from jax.sharding import PartitionSpec as P

    import pathway_tpu.parallel.mesh as mesh_mod

    seen = {}

    def fake_shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        seen["kwargs"] = kwargs
        seen["mesh"] = mesh
        return f

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    marker = lambda x: x  # noqa: E731
    out = mesh_mod.shard_map(marker, mesh=mesh8, in_specs=(P("data"),),
                             out_specs=P("data"), check_vma=False)
    assert out is marker
    assert seen["kwargs"] == {"check_vma": False}
    assert seen["mesh"] is mesh8


def test_shard_map_shim_fallback_branch(monkeypatch, mesh8):
    import sys
    import types

    from jax.sharding import PartitionSpec as P

    import pathway_tpu.parallel.mesh as mesh_mod

    seen = {}

    def fake_shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        seen["kwargs"] = kwargs
        return f

    # force hasattr(jax, "shard_map") False so the shim takes the legacy
    # path, and resolve jax.experimental.shard_map to a recorder module
    # regardless of what the installed jax ships
    monkeypatch.delattr(jax, "shard_map", raising=False)
    stub = types.ModuleType("jax.experimental.shard_map")
    stub.shard_map = fake_shard_map
    monkeypatch.setitem(sys.modules, "jax.experimental.shard_map", stub)
    marker = lambda x: x  # noqa: E731
    out = mesh_mod.shard_map(marker, mesh=mesh8, in_specs=(P("data"),),
                             out_specs=P("data"), check_vma=True)
    assert out is marker
    # the flag must arrive under its legacy spelling
    assert seen["kwargs"] == {"check_rep": True}


def _brute_force_knn(vectors, keys, query, k):
    d = ((vectors - query[None, :]) ** 2).sum(axis=1)
    order = np.argsort(d, kind="stable")[:k]
    return [(keys[i], float(d[i])) for i in order]


def test_sharded_knn_matches_exact(mesh8):
    rng = np.random.default_rng(0)
    n, dim = 500, 16
    vectors = rng.normal(size=(n, dim)).astype(np.float32)
    keys = [Pointer(i) for i in range(n)]
    with use_mesh(mesh8):
        idx = ShardedKnnIndex(dim, mesh=mesh8, reserved_space=n)
        for key, vec in zip(keys, vectors):
            idx.add(key, vec)
        q = rng.normal(size=(dim,)).astype(np.float32)
        (result,) = idx.search([(Pointer(999), q, 5, None)])
        expected = _brute_force_knn(vectors, keys, q, 5)
        assert [k for k, _ in result] == [k for k, _ in expected]
        for (_, got), (_, want) in zip(result, expected):
            assert got == pytest.approx(want, rel=1e-4, abs=1e-4)


@pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
def test_sharded_knn_low_precision_slabs(mesh8, dtype):
    """Per-shard bf16/int8 slabs: top-k over the mesh must agree with the
    f32 sharded index within low-precision slack (top-1 exactly on this
    well-separated data), for both metrics — incl. after updates (dirty
    rows re-quantize on flush) and grow."""
    rng = np.random.default_rng(7)
    n, dim = 400, 16
    vectors = rng.normal(size=(n, dim)).astype(np.float32)
    keys = [Pointer(i) for i in range(n)]
    queries = rng.normal(size=(6, dim)).astype(np.float32)
    for metric in (KnnMetric.L2SQ, KnnMetric.COS):
        with use_mesh(mesh8):
            ref = ShardedKnnIndex(dim, mesh=mesh8, reserved_space=n,
                                  metric=metric)
            low = ShardedKnnIndex(dim, mesh=mesh8, reserved_space=n,
                                  metric=metric, dtype=dtype)
            ref.add_batch(keys, vectors)
            low.add_batch(keys, vectors)
            q = [(Pointer(10_000 + i), queries[i], 10, None)
                 for i in range(6)]
            rf, rl = ref.search(q), low.search(q)
            for got_f, got_l in zip(rf, rl):
                overlap = len({k for k, _ in got_f} & {k for k, _ in got_l})
                assert overlap >= 8, (metric, dtype, overlap)
                assert got_l[0][0] == got_f[0][0]
            # update + re-search: the dirty row re-quantizes on flush
            low.add(keys[0], vectors[1])
            ref.add(keys[0], vectors[1])
            (r2,) = low.search([(Pointer(11_000), vectors[1], 2, None)])
            assert {k for k, _ in r2} == {keys[0], keys[1]}


def test_sharded_knn_remove_and_grow(mesh8):
    rng = np.random.default_rng(1)
    dim = 8
    with use_mesh(mesh8):
        idx = ShardedKnnIndex(dim, mesh=mesh8, reserved_space=8)
        base_cap = idx.total_capacity
        n = base_cap + 100  # force growth
        vectors = rng.normal(size=(n, dim)).astype(np.float32)
        for i in range(n):
            idx.add(Pointer(i), vectors[i])
        assert idx.total_capacity > base_cap
        assert len(idx) == n
        # remove half, searches must never return removed keys
        for i in range(0, n, 2):
            idx.remove(Pointer(i))
        (res,) = idx.search([(Pointer(-1), vectors[3], 10, None)])
        assert res, "expected matches"
        for key, _ in res:
            assert int(key) % 2 == 1
        assert res[0][0] == Pointer(3)


def test_sharded_knn_cosine_and_filter(mesh8):
    dim = 4
    with use_mesh(mesh8):
        idx = ShardedKnnIndex(dim, mesh=mesh8, metric="cos")
        idx.add(Pointer(1), [1, 0, 0, 0], {"path": "a.txt"})
        idx.add(Pointer(2), [0.9, 0.1, 0, 0], {"path": "b.md"})
        idx.add(Pointer(3), [0, 1, 0, 0], {"path": "c.md"})
        (res,) = idx.search(
            [(Pointer(0), [1, 0, 0, 0], 2,
              lambda meta: meta["path"].endswith(".md"))])
        assert [k for k, _ in res] == [Pointer(2), Pointer(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh8, causal):
    rng = np.random.default_rng(2)
    B, S, H, D = 2, 32, 4, 8  # S sharded 8-way → 4 per chip
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    want = reference_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh=mesh8, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(mesh8, causal):
    rng = np.random.default_rng(3)
    B, S, H, D = 2, 32, 8, 4  # heads divisible by 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    want = reference_attention(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, mesh=mesh8, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_on_submesh(mesh42):
    rng = np.random.default_rng(4)
    B, S, H, D = 1, 16, 2, 4
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
    want = reference_attention(q, k, v)
    got = ring_attention(q, k, v, mesh=mesh42)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_document_index_mesh_sharded_end_to_end():
    """default_brute_force_knn_document_index(mesh='auto') builds the
    mesh-sharded index and serves correct as-of-now queries through the
    engine (VERDICT weak #10: the index now scales over devices, the
    TPU-native axis, instead of gathering everything onto one worker)."""
    import numpy as np

    import pathway_tpu as pw
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner
    from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex
    from pathway_tpu.stdlib.indexing import (
        default_brute_force_knn_document_index)

    G.clear()
    try:
        rng = np.random.default_rng(0)
        vecs = rng.normal(size=(32, 8)).astype(np.float32)

        class D(pw.Schema):
            doc: str

        docs = pw.debug.table_from_rows(D, [(f"d{i}",) for i in range(32)])
        data = docs.select(
            doc=docs.doc,
            vec=pw.apply(lambda d: vecs[int(d[1:])], docs.doc))
        index = default_brute_force_knn_document_index(
            data.vec, data, dimensions=8, mesh="auto")
        # the factory must have chosen the sharded index on the 8-device
        # CPU test mesh
        built = index.inner_index.factory().build()
        assert isinstance(built, ShardedKnnIndex)
        assert built.n_shards > 1

        class Q(pw.Schema):
            qvec: str

        queries = pw.debug.table_from_rows(Q, [("7",), ("19",)])
        qv = queries.select(
            v=pw.apply(lambda i: vecs[int(i)], queries.qvec))
        hits = index.query_as_of_now(qv.v, number_of_matches=1)
        res = qv.select(
            q=queries.restrict(qv).qvec,
            hit=pw.apply(lambda t: t[0] if t else None,
                         hits._pw_index_reply_id))
        runner = GraphRunner()
        cap = runner.capture(res)
        data_cap = runner.capture(data)
        runner.run_batch()
        # the hit must be EXACTLY the matching corpus row's key: queries
        # are vecs[7]/vecs[19], both present verbatim in the index —
        # catches cross-shard slot-globalization bugs, not just liveness
        doc_key = {row[0]: key for key, row in data_cap.snapshot().items()}
        got = {row[0]: row[1] for row in cap.snapshot().values()}
        assert got == {"7": doc_key["d7"], "19": doc_key["d19"]}
    finally:
        G.clear()


# ---------------------------------------------------------------------------
# cluster-level kill-and-recover (reference:
# integration_tests/wordcount/test_recovery.py:25 — real processes killed
# mid-stream, restart must produce exact final counts from persistence)
# ---------------------------------------------------------------------------

_CLUSTER_WORDCOUNT = __import__("textwrap").dedent("""
    import os
    import pathway_tpu as pw

    inp, pdir = os.environ["TEST_IN"], os.environ["TEST_PDIR"]
    out = os.environ["TEST_OUT"] + os.environ.get("PATHWAY_PROCESS_ID", "?")
    t = pw.io.fs.read(inp, format="plaintext", mode="streaming",
                      autocommit_duration_ms=40, persistent_id="words")
    counts = t.groupby(t.data).reduce(word=t.data, c=pw.reducers.count())
    pw.io.fs.write(counts, out, format="csv")
    pw.run(persistence_config=pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(pdir)))
""")


def _shard_counts(out_base) -> dict[str, int]:
    import csv

    state: dict[str, int] = {}
    for pid in range(2):
        try:
            with open(f"{out_base}{pid}", newline="") as f:
                for row in csv.DictReader(f):
                    w, c, d = row["word"], int(row["c"]), int(row["diff"])
                    if d > 0:
                        state[w] = c
                    elif state.get(w) == c:
                        del state[w]
        except (FileNotFoundError, KeyError, ValueError):
            continue
    return state


def _child_pids(pid: int) -> list[int]:
    import glob

    out = []
    for path in glob.glob(f"/proc/{pid}/task/*/children"):
        try:
            with open(path) as f:
                out.extend(int(p) for p in f.read().split())
        except OSError:
            continue
    return out


@pytest.mark.slow
def test_cluster_kill_one_process_and_recover(tmp_path):
    """Spawn a REAL 2-process cluster (cli spawn -n 2, TCP exchange),
    SIGKILL one worker process mid-stream, verify the peer detects the
    death and the cluster exits, then restart the cluster on the same
    persistence dir and assert exact final counts — exactly-once across
    a process crash at cluster level."""
    import os
    import signal
    import subprocess
    import sys
    import time

    inp = tmp_path / "in"
    inp.mkdir()
    script = tmp_path / "wc.py"
    script.write_text(_CLUSTER_WORDCOUNT)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo",
               TEST_IN=str(inp), TEST_PDIR=str(tmp_path / "pstate"),
               TEST_OUT=str(tmp_path / "out"),
               PATHWAY_FIRST_PORT=str(21700 + os.getpid() % 500))

    expected: dict[str, int] = {}

    def add_file(i: int, mod: int):
        words = [f"w{j % mod}" for j in range(25)]
        (inp / f"{i:03d}.txt").write_text("\n".join(words) + "\n")
        for w in words:
            expected[w] = expected.get(w, 0) + 1

    for i in range(3):
        add_file(i, 7)

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-m", "pathway_tpu", "spawn", "-n", "2",
             sys.executable, str(script)],
            env=env, cwd="/root/repo", start_new_session=True)

    proc = spawn()
    try:
        from tests.utils import wait_result_with_checker

        wait_result_with_checker(
            lambda: _shard_counts(str(tmp_path / "out")), 90)
        assert _shard_counts(str(tmp_path / "out")), "no output before kill"

        workers = _child_pids(proc.pid)
        assert len(workers) == 2, f"expected 2 worker processes: {workers}"
        os.kill(workers[1], signal.SIGKILL)  # crash ONE process mid-stream

        # failure detection: the surviving peer must notice the death and
        # the whole cluster must come down (spawn reaps + terminates)
        assert proc.wait(timeout=90) is not None

        for i in range(3, 6):  # more input arrives while the cluster is down
            add_file(i, 5)

        proc = spawn()
        wait_result_with_checker(
            lambda: _shard_counts(str(tmp_path / "out")) == expected, 120,
            step=0.2)
        assert _shard_counts(str(tmp_path / "out")) == expected
    finally:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
