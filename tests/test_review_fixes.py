"""Regression tests for the round-1 code-review findings (temporal joins,
null join keys, markdown ids, buffer flush, async UDF kwargs, dedup errors)."""

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.internals import expression as ex
from tests.utils import T, rows_of


def _rows(table):
    """Run and return the live rows (ignoring ids), repr-sorted."""
    return rows_of(table)


def _expect(rows):
    return sorted(rows, key=repr)


# ---------------------------------------------------------------------------
# 1. markdown implicit-id format
# ---------------------------------------------------------------------------

def test_markdown_leading_empty_id_cell():
    t = pw.debug.table_from_markdown(
        """
          | owner | pet
        1 | Alice | dog
        2 | Bob   | cat
        """
    )
    assert set(t.column_names()) == {"owner", "pet"}
    assert _rows(t) == _expect([("Alice", "dog"), ("Bob", "cat")])


def test_markdown_explicit_id_header_unchanged():
    t = pw.debug.table_from_markdown(
        """
        id | v
        1  | 10
        2  | 20
        """
    )
    assert t.column_names() == ["v"]
    assert _rows(t) == _expect([(10,), (20,)])


def test_markdown_same_id_same_key():
    a = pw.debug.table_from_markdown("""
          | v
        7 | 1
    """)
    b = pw.debug.table_from_markdown("""
          | w
        7 | 2
    """)
    # same explicit id → same key → zip via with_universe_of works
    joined = a.with_columns(w=b.with_universe_of(a).w)
    assert _rows(joined) == _expect([(1, 2)])


# ---------------------------------------------------------------------------
# 2. interval_join
# ---------------------------------------------------------------------------

def test_interval_join_inner_matches():
    left = T("""
        a | t
        1 | 0
        2 | 10
    """)
    right = T("""
        b | t
        9 | 1
        8 | 30
    """)
    res = pw.temporal.interval_join(
        left, right, left.t, right.t, pw.temporal.interval(-2, 2)
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, 9)])


def test_interval_join_left_pads_unmatched():
    left = T("""
        a | t
        1 | 0
        2 | 100
    """)
    right = T("""
        b | t
        9 | 1
    """)
    res = pw.temporal.interval_join_left(
        left, right, left.t, right.t, pw.temporal.interval(-2, 2)
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, 9), (2, None)])


def test_interval_join_right_pads_unmatched():
    left = T("""
        a | t
        1 | 0
    """)
    right = T("""
        b | t
        9 | 1
        8 | 50
    """)
    res = pw.temporal.interval_join_right(
        left, right, left.t, right.t, pw.temporal.interval(-2, 2)
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, 9), (None, 8)])


def test_interval_join_outer():
    left = T("""
        a | t
        1 | 0
        2 | 100
    """)
    right = T("""
        b | t
        9 | 1
        8 | 50
    """)
    res = pw.temporal.interval_join_outer(
        left, right, left.t, right.t, pw.temporal.interval(-2, 2)
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, 9), (2, None), (None, 8)])


def test_interval_join_datetimes():
    df_l = pd.DataFrame({"t": pd.to_datetime(["2024-01-01 00:00:00",
                                              "2024-01-01 04:00:00"]),
                         "a": [1, 2]})
    df_r = pd.DataFrame({"t": pd.to_datetime(["2024-01-01 00:30:00"]),
                         "b": [9]})
    left = pw.debug.table_from_pandas(df_l)
    right = pw.debug.table_from_pandas(df_r)
    res = pw.temporal.interval_join(
        left, right, left.t, right.t,
        pw.temporal.interval(pd.Timedelta("-1h"), pd.Timedelta("1h")),
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, 9)])


# ---------------------------------------------------------------------------
# 3. asof_join
# ---------------------------------------------------------------------------

def test_asof_join_inner_backward():
    left = T("""
        a | t
        1 | 1
        2 | 5
    """)
    right = T("""
        b | t
        7 | 3
    """)
    res = pw.temporal.asof_join(
        left, right, left.t, right.t
    ).select(a=left.a, b=right.b)
    # t=1 has no right row <= 1 → dropped in inner mode
    assert _rows(res) == _expect([(2, 7)])


def test_asof_join_left_keeps_unmatched():
    left = T("""
        a | t
        1 | 1
        2 | 5
    """)
    right = T("""
        b | t
        7 | 3
    """)
    res = pw.temporal.asof_join_left(
        left, right, left.t, right.t
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, None), (2, 7)])


def test_asof_join_left_defaults():
    left = T("""
        a | t
        1 | 1
    """)
    right = T("""
        b | t
        7 | 3
    """)
    res = pw.temporal.asof_join_left(
        left, right, left.t, right.t, defaults={"b": -1}
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, -1)])


def test_asof_join_right_pads_unchosen():
    left = T("""
        a | t
        1 | 5
    """)
    right = T("""
        b | t
        7 | 3
        8 | 4
        9 | 50
    """)
    res = pw.temporal.asof_join_right(
        left, right, left.t, right.t
    ).select(a=left.a, b=right.b)
    # best match for t=5 is b=8; b=7 and b=9 never chosen → padded
    assert _rows(res) == _expect([(1, 8), (None, 7), (None, 9)])


def test_asof_join_outer():
    left = T("""
        a | t
        1 | 1
        2 | 5
    """)
    right = T("""
        b | t
        7 | 3
        9 | 50
    """)
    res = pw.temporal.asof_join_outer(
        left, right, left.t, right.t
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, None), (2, 7), (None, 9)])


def test_asof_join_forward():
    left = T("""
        a | t
        1 | 1
    """)
    right = T("""
        b | t
        7 | 3
        8 | 10
    """)
    res = pw.temporal.asof_join(
        left, right, left.t, right.t, direction="forward"
    ).select(a=left.a, b=right.b)
    assert _rows(res) == _expect([(1, 7)])


# ---------------------------------------------------------------------------
# 4. None join keys in left/outer joins
# ---------------------------------------------------------------------------

def test_left_join_keeps_none_key_rows():
    left = T("""
        k | v
        1 | 10
        None | 20
    """)
    right = T("""
        k | w
        1 | 100
    """)
    res = left.join(right, left.k == right.k, how="left").select(
        v=left.v, w=right.w)
    assert _rows(res) == _expect([(10, 100), (20, None)])


def test_outer_join_none_keys_never_match_each_other():
    left = T("""
        k | v
        None | 1
    """)
    right = T("""
        k | w
        None | 2
    """)
    res = left.join(right, left.k == right.k, how="outer").select(
        v=left.v, w=right.w)
    assert _rows(res) == _expect([(1, None), (None, 2)])


def test_inner_join_drops_none_keys():
    left = T("""
        k | v
        None | 1
        2 | 3
    """)
    right = T("""
        k | w
        2 | 4
    """)
    res = left.join(right, left.k == right.k).select(v=left.v, w=right.w)
    assert _rows(res) == _expect([(3, 4)])


# ---------------------------------------------------------------------------
# 5. buffer flush at end of stream
# ---------------------------------------------------------------------------

def test_windowby_delay_flushes_at_end():
    t = T("""
        v | t
        1 | 0
        2 | 4
        3 | 10
    """)
    res = pw.temporal.windowby(
        t, t.t, window=pw.temporal.tumbling(4),
        behavior=pw.temporal.common_behavior(delay=5),
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    # the [8,12) window's threshold (13) exceeds the final watermark (10) —
    # it must still be emitted by the end-of-stream flush
    assert _rows(res) == _expect([(0, 1), (4, 2), (8, 3)])


# ---------------------------------------------------------------------------
# 7. async UDF kwarg propagation
# ---------------------------------------------------------------------------

def test_async_udf_propagates_error_kwargs():
    @pw.udf
    async def combine(*, x: int) -> int:
        assert not isinstance(x, object.__new__(type).__mro__[-1].__class__ )
        return x + 1

    t = T("""
        a | b
        1 | 0
    """)
    # a/b → error via division by zero, passed as KEYWORD arg
    bad = t.select(e=ex.fill_error(t.a // t.b, -7))
    assert _rows(bad) == _expect([(-7,)])

    seen = []

    @pw.udf
    async def probe(*, x) -> int:
        seen.append(x)
        return 0

    res = t.select(r=ex.fill_error(probe(x=t.a // t.b), -1))
    assert _rows(res) == _expect([(-1,)])
    assert seen == []  # coroutine never scheduled with the ERROR sentinel


def test_async_udf_propagates_none_kwargs():
    seen = []

    @pw.udf(propagate_none=True)
    async def probe(*, x) -> int:
        seen.append(x)
        return 1

    t = T("""
        a
        None
    """)
    res = t.select(r=probe(x=t.a))
    assert _rows(res) == _expect([(None,)])
    assert seen == []


# ---------------------------------------------------------------------------
# 8. deduplicate acceptor errors are logged, not swallowed silently
# ---------------------------------------------------------------------------

def test_deduplicate_acceptor_exception_logged():
    t = T("""
        v
        1
        2
    """)

    def acceptor(new, old):
        raise RuntimeError("boom")

    res = t.deduplicate(value=t.v, acceptor=acceptor)
    before = len(pw.global_error_log().entries)
    rows = _rows(res)
    assert rows == [(1,)] or rows == [(2,)]
    after = len(pw.global_error_log().entries)
    assert after > before
    assert any("boom" in e["message"]
               for e in pw.global_error_log().entries[before:])


def test_gradual_broadcast_values_and_throttling():
    """_gradual_broadcast (reference gradual_broadcast.rs): rows read
    `upper` when key < (value-lower)/(upper-lower) of keyspace, else
    `lower`; when the value moves, only keys between the old and new
    thresholds re-emit."""
    import pathway_tpu as pw
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals.runner import GraphRunner

    class R(pw.Schema):
        name: str

    class T_(pw.Schema):
        lo: float
        val: float
        hi: float

    rows = table_from_rows(R, [(f"r{i}",) for i in range(40)])
    # triplet stream: val starts at lo (nobody upgraded), then moves 40%
    # of the way to hi at t=2, then to 50% at t=4 (a small nudge)
    thr = table_from_rows(
        T_, [(0.0, 0.0, 10.0, 0, 1),
             (0.0, 0.0, 10.0, 2, -1), (0.0, 4.0, 10.0, 2, 1),
             (0.0, 4.0, 10.0, 4, -1), (0.0, 5.0, 10.0, 4, 1)],
        is_stream=True)
    out = rows._gradual_broadcast(thr, thr.lo, thr.val, thr.hi)
    runner = GraphRunner()
    cap = runner.capture(out)
    runner.run_batch()

    state = cap.snapshot()
    assert len(state) == 40
    # final: keys in the lowest 50% of keyspace read hi, others lo
    for key, row in state.items():
        expected = 10.0 if int(key) < (1 << 127) else 0.0
        assert row[-1] == expected, (key, row)
    # throttling: the t=4 nudge (40% -> 50%) must re-emit only the keys
    # inside the crossed 10% band, not all 40 rows
    t4_retractions = [e for e in cap.events if e[2] == 4 and e[3] < 0]
    frac = len(t4_retractions) / 40
    assert 0 < len(t4_retractions) <= 8, len(t4_retractions)
    # and at t=2 only ~40% flipped
    t2 = [e for e in cap.events if e[2] == 2 and e[3] < 0]
    assert 0 < len(t2) <= 24


def test_gradual_broadcast_none_apx_still_retracts():
    """A triplet containing None emits apx=None; deleting such a row must
    still retract it (regression: None was conflated with 'never
    emitted')."""
    from pathway_tpu.engine.delta import Delta
    from pathway_tpu.engine.operators import GradualBroadcastOperator
    from pathway_tpu.internals.keys import hash_values

    op = GradualBroadcastOperator()
    k = hash_values("r")
    out0 = op.step(0, [Delta([(k, ("x",), 1)]),
                       Delta([(hash_values("t"), (None, None, None), 1)])])
    assert [(key, row, d) for key, row, d in out0.entries] == [
        (k, ("x", None), 1)]
    out1 = op.step(1, [Delta([(k, ("x",), -1)]), Delta()])
    assert [(key, row, d) for key, row, d in out1.entries] == [
        (k, ("x", None), -1)]


# ---------------------------------------------------------------------------
# round-4 findings: columnar ETL fast paths must keep hash-equivalence
# semantics (equal ints/floats join and group together, at any worker count)
# ---------------------------------------------------------------------------

def test_join_int_column_to_float_column_matches():
    left = pw.debug.table_from_rows(
        pw.schema_from_types(a=int, x=str), [(1, "l1"), (3, "l3")])
    right = pw.debug.table_from_rows(
        pw.schema_from_types(b=float, y=str), [(1.0, "r1"), (2.5, "r2")])
    j = left.join(right, left.a == right.b).select(left.x, right.y)
    assert _rows(j) == _expect([("l1", "r1")])


def test_groupby_mixed_int_float_values_one_group_any_worker_count():
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.runner import GraphRunner

    def run(n_workers):
        G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(k=float, v=int),
            [(1, 10), (1.0, 20), (2.5, 5)])
        g = t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.v))
        runner = GraphRunner()
        cap = runner.capture(g)
        runner.run_batch(n_workers=n_workers)
        out = sorted((float(r[0]), r[1]) for r in cap.snapshot().values())
        G.clear()
        return out

    assert run(1) == [(1.0, 30), (2.5, 5)]
    assert run(8) == run(1)


def test_columnar_sum_exact_beyond_int64():
    big = 2**63 - 1
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=str, v=int),
        [("a", big), ("a", 5), ("b", 1)])
    g = t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.v))
    assert _rows(g) == _expect([("a", big + 5), ("b", 1)])


def test_columnar_sum_exact_beyond_int64_streaming_retraction():
    big = 2**62  # crosses the guard via accumulation, then retracts back
    t = pw.debug.table_from_rows(
        pw.schema_from_types(k=str, v=int),
        [("a", big - 1, 0, 1), ("a", big - 1, 2, 1), ("a", 7, 4, 1),
         ("a", big - 1, 6, -1)],
        is_stream=True)
    g = t.groupby(t.k).reduce(t.k, s=pw.reducers.sum(t.v))
    assert _rows(g) == _expect([("a", big - 1 + 7)])


def test_bool_join_key_does_not_match_int():
    left = pw.debug.table_from_rows(
        pw.schema_from_types(a=bool, x=str), [(True, "lt")])
    right = pw.debug.table_from_rows(
        pw.schema_from_types(b=int, y=str), [(1, "r1")])
    j = left.join(right, left.a == right.b).select(left.x, right.y)
    assert _rows(j) == []
