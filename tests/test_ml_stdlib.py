"""ML stdlib: HMM decoding, fuzzy joins, custom accumulators
(reference: stdlib/ml/hmm.py, stdlib/ml/smart_table_ops/_fuzzy_join.py)."""

from functools import partial

import numpy as np
import pytest

import pathway_tpu as pw
from tests.utils import T, rows_of


# ---------------------------------------------------------------------------
# BaseCustomAccumulator protocol
# ---------------------------------------------------------------------------

def test_udf_reducer_custom_accumulator():
    class SumSq(pw.BaseCustomAccumulator):
        def __init__(self, v):
            self.total = v * v

        @classmethod
        def from_row(cls, row):
            [v] = row
            return cls(v)

        def update(self, other):
            self.total += other.total

        def compute_result(self):
            return self.total

    sumsq = pw.reducers.udf_reducer(SumSq)
    t = T("""
    g | x
    a | 1
    a | 2
    b | 3
    """)
    r = t.groupby(t.g).reduce(g=t.g, s=sumsq(t.x))
    assert sorted(rows_of(r)) == [("a", 5), ("b", 9)]


# ---------------------------------------------------------------------------
# HMM (the reference's manul example, same graph/numbers)
# ---------------------------------------------------------------------------

def _manul_graph():
    import networkx as nx

    def emis(observation, state):
        table = {("HUNGRY", "GRUMPY"): 0.9, ("HUNGRY", "HAPPY"): 0.1,
                 ("FULL", "GRUMPY"): 0.7, ("FULL", "HAPPY"): 0.3}
        return np.log(table[(state, observation)])

    g = nx.DiGraph()
    g.add_node("HUNGRY", calc_emission_log_ppb=partial(emis, state="HUNGRY"))
    g.add_node("FULL", calc_emission_log_ppb=partial(emis, state="FULL"))
    g.add_edge("HUNGRY", "HUNGRY", log_transition_ppb=np.log(0.4))
    g.add_edge("HUNGRY", "FULL", log_transition_ppb=np.log(0.6))
    g.add_edge("FULL", "HUNGRY", log_transition_ppb=np.log(0.6))
    g.add_edge("FULL", "FULL", log_transition_ppb=np.log(0.4))
    g.graph["start_nodes"] = ["HUNGRY", "FULL"]
    return g


def test_hmm_viterbi_stream():
    obs = T("""
    observation | __time__
    HAPPY       | 1
    HAPPY       | 2
    GRUMPY      | 3
    GRUMPY      | 4
    HAPPY       | 5
    GRUMPY      | 6
    """)
    hmm_reducer = pw.reducers.udf_reducer(
        pw.stdlib.ml.hmm.create_hmm_reducer(_manul_graph(),
                                            num_results_kept=3))
    decoded = obs.reduce(decoded_state=hmm_reducer(obs.observation))
    # final state after all six observations (reference doctest's last row)
    assert rows_of(decoded) == [(("HUNGRY", "FULL", "HUNGRY"),)]


# ---------------------------------------------------------------------------
# fuzzy joins
# ---------------------------------------------------------------------------

def test_fuzzy_match_columns():
    left = T("""
    name
    Johnny Smith
    Alice Cooper
    Bob Marley
    """)
    right = T("""
    name
    smith john
    cooper alice
    marley bob
    """)
    res = pw.stdlib.ml.fuzzy_match(left.name, right.name)
    got = rows_of(res.select(
        l=pw.apply(lambda p: None, res.left), w=res.weight))
    assert len(got) == 3  # every row found its mutual-best partner

    # check an actual pairing via joined payloads
    joined = res.join(left, res.left == left.id).select(
        lname=left.name, right=res.right)
    joined = joined.join(right, joined.right == right.id).select(
        lname=joined.lname, rname=right.name)
    pairs = dict(rows_of(joined))
    assert pairs["Alice Cooper"] == "cooper alice"
    assert pairs["Bob Marley"] == "marley bob"


def test_fuzzy_match_tables_and_self_match():
    t1 = T("""
    a     | b
    apple | pie
    stock | market
    """)
    t2 = T("""
    c
    apple pie recipe
    stock market crash
    """)
    res = pw.stdlib.ml.fuzzy_match_tables(t1, t2)
    joined = res.join(t1, res.left == t1.id).select(a=t1.a, right=res.right)
    joined = joined.join(t2, joined.right == t2.id).select(
        a=joined.a, c=t2.c)
    pairs = dict(rows_of(joined))
    assert pairs == {"apple": "apple pie recipe",
                     "stock": "stock market crash"}

    t3 = T("""
    v
    hello world
    hello world
    something else
    """)
    selfm = pw.stdlib.ml.fuzzy_self_match(t3, t3.v)
    got = rows_of(selfm)
    assert len(got) == 1  # the two identical rows pair up once


def test_classifier_accuracy():
    predicted = T("""
    predicted_label
    cat
    dog
    cat
    """)
    exact = predicted.select(label=pw.apply(
        lambda p: "cat", predicted.predicted_label))
    acc = pw.stdlib.ml.utils.classifier_accuracy(predicted, exact)
    got = dict((v, c) for c, v in rows_of(acc))
    assert got == {True: 2, False: 1}


# ---------------------------------------------------------------------------
# LSH classifiers + clustering (reference: stdlib/ml/classifiers/_knn_lsh.py,
# _lsh.py, _clustering_via_lsh.py)
# ---------------------------------------------------------------------------

def _labeled_blobs(n_per=12, d=6, seed=3):
    """Three well-separated gaussian blobs with labels."""
    rng = np.random.default_rng(seed)
    centers = np.eye(3, d) * 10.0
    pts, labels = [], []
    for ci in range(3):
        pts.append(centers[ci] + rng.standard_normal((n_per, d)) * 0.3)
        labels += [f"c{ci}"] * n_per
    return np.concatenate(pts).astype(np.float64), labels


def _points_table(pts, labels=None):
    from pathway_tpu.debug import table_from_rows
    from pathway_tpu.internals import schema as sch

    if labels is None:
        schema = sch.schema_from_types(data=np.ndarray)
        return table_from_rows(schema, [(pts[i],) for i in range(len(pts))])
    schema = sch.schema_from_types(data=np.ndarray, label=str)
    return table_from_rows(
        schema, [(pts[i], labels[i]) for i in range(len(pts))])


def test_lsh_bucketed_classifier_votes_correctly():
    from pathway_tpu.stdlib.ml.classifiers import (
        knn_lsh_classify, knn_lsh_euclidean_classifier_train)

    pts, labels = _labeled_blobs()
    data = _points_table(pts, labels)
    classifier = knn_lsh_euclidean_classifier_train(
        data, d=6, M=4, L=12, A=2.0)
    qpts = np.array([[10.0, 0, 0, 0, 0, 0.2], [0, 9.7, 0.1, 0, 0, 0],
                     [0.1, 0, 10.2, 0, 0, 0]])
    queries = _points_table(qpts)
    res = knn_lsh_classify(classifier, queries, k=3)
    got = sorted(r[0] for r in rows_of(res))
    assert got == ["c0", "c1", "c2"], got


def test_lsh_classifier_rejects_unknown_params():
    from pathway_tpu.stdlib.ml.classifiers import knn_lsh_classifier_train

    pts, labels = _labeled_blobs(n_per=3)
    data = _points_table(pts, labels)
    with pytest.raises(TypeError, match="unsupported lsh_params"):
        knn_lsh_classifier_train(data, 5, "euclidean", bogus=1)


def test_clustering_via_lsh_separates_blobs():
    from pathway_tpu.stdlib.ml.classifiers import (
        clustering_via_lsh, generate_euclidean_lsh_bucketer)

    pts, true_labels = _labeled_blobs(n_per=15)
    data = _points_table(pts)
    bucketer = generate_euclidean_lsh_bucketer(6, M=3, L=8, A=4.0)
    res = clustering_via_lsh(data, bucketer, k=3)
    rows = rows_of(res)
    assert len(rows) == len(pts)
    # cluster ids are arbitrary; check the PARTITION matches the blobs:
    # run again keyed back to inputs via the table keys
    from pathway_tpu.internals.runner import run_tables

    [cap] = run_tables(clustering_via_lsh(
        _points_table(pts), generate_euclidean_lsh_bucketer(
            6, M=3, L=8, A=4.0), 3))
    snap = cap.snapshot()
    from pathway_tpu.internals.keys import hash_values  # noqa: F401

    labels_by_row = [lbl for (lbl,) in snap.values()]
    assert len(set(labels_by_row)) == 3


def test_digits_dataset_knn_classifier_end_to_end():
    """ml.datasets loader → exact TPU-slab kNN classifier → accuracy.
    Uses sklearn's BUNDLED digits set (offline), the round-5 replacement
    for the reference's network-only MNIST example."""
    pytest.importorskip("sklearn")
    from pathway_tpu.stdlib.ml.classifiers import (
        knn_lsh_classifier_train, knn_lsh_classify)
    from pathway_tpu.stdlib.ml.datasets.classification import (
        load_digits_sample)

    train, test, train_labels, test_labels = load_digits_sample(400)
    lbl = train_labels.ix(train.id, context=train)
    data = train.select(train.data, label=lbl.label)

    classifier = knn_lsh_classifier_train(data, n_dimensions=64)
    predicted = knn_lsh_classify(classifier, test, k=5)

    from pathway_tpu.internals.runner import run_tables

    cap_pred, cap_truth = run_tables(predicted, test_labels)
    pred = [row[0] for row in cap_pred.snapshot().values()]
    truth = [row[0] for row in cap_truth.snapshot().values()]
    assert len(pred) == len(truth) > 0
    acc = sum(p == t for p, t in zip(pred, truth)) / len(truth)
    assert acc >= 0.85, f"digits knn accuracy {acc:.2f}"
