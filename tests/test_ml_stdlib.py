"""ML stdlib: HMM decoding, fuzzy joins, custom accumulators
(reference: stdlib/ml/hmm.py, stdlib/ml/smart_table_ops/_fuzzy_join.py)."""

from functools import partial

import numpy as np
import pytest

import pathway_tpu as pw
from tests.utils import T, rows_of


# ---------------------------------------------------------------------------
# BaseCustomAccumulator protocol
# ---------------------------------------------------------------------------

def test_udf_reducer_custom_accumulator():
    class SumSq(pw.BaseCustomAccumulator):
        def __init__(self, v):
            self.total = v * v

        @classmethod
        def from_row(cls, row):
            [v] = row
            return cls(v)

        def update(self, other):
            self.total += other.total

        def compute_result(self):
            return self.total

    sumsq = pw.reducers.udf_reducer(SumSq)
    t = T("""
    g | x
    a | 1
    a | 2
    b | 3
    """)
    r = t.groupby(t.g).reduce(g=t.g, s=sumsq(t.x))
    assert sorted(rows_of(r)) == [("a", 5), ("b", 9)]


# ---------------------------------------------------------------------------
# HMM (the reference's manul example, same graph/numbers)
# ---------------------------------------------------------------------------

def _manul_graph():
    import networkx as nx

    def emis(observation, state):
        table = {("HUNGRY", "GRUMPY"): 0.9, ("HUNGRY", "HAPPY"): 0.1,
                 ("FULL", "GRUMPY"): 0.7, ("FULL", "HAPPY"): 0.3}
        return np.log(table[(state, observation)])

    g = nx.DiGraph()
    g.add_node("HUNGRY", calc_emission_log_ppb=partial(emis, state="HUNGRY"))
    g.add_node("FULL", calc_emission_log_ppb=partial(emis, state="FULL"))
    g.add_edge("HUNGRY", "HUNGRY", log_transition_ppb=np.log(0.4))
    g.add_edge("HUNGRY", "FULL", log_transition_ppb=np.log(0.6))
    g.add_edge("FULL", "HUNGRY", log_transition_ppb=np.log(0.6))
    g.add_edge("FULL", "FULL", log_transition_ppb=np.log(0.4))
    g.graph["start_nodes"] = ["HUNGRY", "FULL"]
    return g


def test_hmm_viterbi_stream():
    obs = T("""
    observation | __time__
    HAPPY       | 1
    HAPPY       | 2
    GRUMPY      | 3
    GRUMPY      | 4
    HAPPY       | 5
    GRUMPY      | 6
    """)
    hmm_reducer = pw.reducers.udf_reducer(
        pw.stdlib.ml.hmm.create_hmm_reducer(_manul_graph(),
                                            num_results_kept=3))
    decoded = obs.reduce(decoded_state=hmm_reducer(obs.observation))
    # final state after all six observations (reference doctest's last row)
    assert rows_of(decoded) == [(("HUNGRY", "FULL", "HUNGRY"),)]


# ---------------------------------------------------------------------------
# fuzzy joins
# ---------------------------------------------------------------------------

def test_fuzzy_match_columns():
    left = T("""
    name
    Johnny Smith
    Alice Cooper
    Bob Marley
    """)
    right = T("""
    name
    smith john
    cooper alice
    marley bob
    """)
    res = pw.stdlib.ml.fuzzy_match(left.name, right.name)
    got = rows_of(res.select(
        l=pw.apply(lambda p: None, res.left), w=res.weight))
    assert len(got) == 3  # every row found its mutual-best partner

    # check an actual pairing via joined payloads
    joined = res.join(left, res.left == left.id).select(
        lname=left.name, right=res.right)
    joined = joined.join(right, joined.right == right.id).select(
        lname=joined.lname, rname=right.name)
    pairs = dict(rows_of(joined))
    assert pairs["Alice Cooper"] == "cooper alice"
    assert pairs["Bob Marley"] == "marley bob"


def test_fuzzy_match_tables_and_self_match():
    t1 = T("""
    a     | b
    apple | pie
    stock | market
    """)
    t2 = T("""
    c
    apple pie recipe
    stock market crash
    """)
    res = pw.stdlib.ml.fuzzy_match_tables(t1, t2)
    joined = res.join(t1, res.left == t1.id).select(a=t1.a, right=res.right)
    joined = joined.join(t2, joined.right == t2.id).select(
        a=joined.a, c=t2.c)
    pairs = dict(rows_of(joined))
    assert pairs == {"apple": "apple pie recipe",
                     "stock": "stock market crash"}

    t3 = T("""
    v
    hello world
    hello world
    something else
    """)
    selfm = pw.stdlib.ml.fuzzy_self_match(t3, t3.v)
    got = rows_of(selfm)
    assert len(got) == 1  # the two identical rows pair up once


def test_classifier_accuracy():
    predicted = T("""
    predicted_label
    cat
    dog
    cat
    """)
    exact = predicted.select(label=pw.apply(
        lambda p: "cat", predicted.predicted_label))
    acc = pw.stdlib.ml.utils.classifier_accuracy(predicted, exact)
    got = dict((v, c) for c, v in rows_of(acc))
    assert got == {True: 2, False: 1}
