"""Paged HBM vector store (engine/paged_store.py + ops/knn.py PagedKnnIndex
+ parallel/sharded_knn.py PagedShardedKnnIndex) and ragged encoder batching.

The load-bearing contract: the paged store is BYTE-IDENTICAL to the
contiguous slab (PATHWAY_PAGED_STORE=0) across ingest/delete/grow/search
churn — same keys, same distances, bit for bit — while growth allocates
pages instead of re-uploading, fused donated ingest grows instead of
raising, and freed pages are reused (occupancy bounded).
"""

from __future__ import annotations

import numpy as np
import pytest

from pathway_tpu.engine.paged_store import (DevicePagePool, PageAllocator,
                                            PageQuotaExceeded,
                                            live_paged_stats, page_rows,
                                            paged_store_enabled)
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.ops.knn import BruteForceKnnIndex, KnnMetric, PagedKnnIndex


def _mk(n=None, **kw):
    # paged pinned explicitly: this suite must test the paged path even
    # on the CI matrix leg that flips the default to the slab
    kw.setdefault("metric", KnnMetric.L2SQ)
    kw.setdefault("paged", True)
    return BruteForceKnnIndex(8, **kw)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_page_rows_validation(monkeypatch):
    assert page_rows(1024) == 1024
    for bad in (100, 96, 1 << 20, 0):
        with pytest.raises(ValueError):
            page_rows(bad)
    monkeypatch.setenv("PATHWAY_PAGE_ROWS", "4096")
    assert page_rows() == 4096
    monkeypatch.setenv("PATHWAY_PAGE_ROWS", "100")
    with pytest.raises(ValueError):
        page_rows()


def test_paged_store_env_gate(monkeypatch):
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    assert paged_store_enabled()          # default ON
    assert not paged_store_enabled(False)  # explicit arg wins
    monkeypatch.setenv("PATHWAY_PAGED_STORE", "0")
    assert not paged_store_enabled()
    assert paged_store_enabled(True)


def test_allocator_alloc_free_reuse():
    a = PageAllocator(128)
    a.add_region(0, 0, 4)  # 4 pages, 512 slots
    slots = [a.take_slot() for _ in range(300)]
    assert len(set(slots)) == 300
    assert a.live_rows == 300
    st = a.stats()
    assert st["pages_total"] == 4 and st["pages_free"] == 1
    # free an entire page's worth: the drained page returns to the pool
    for s in slots:
        a.release_slot(s)
    st = a.stats()
    assert st["pages_free"] == 4 and st["live_rows"] == 0
    # reuse: no growth needed for a fresh fill
    again = [a.take_slot() for _ in range(512)]
    assert len(set(again)) == 512
    with pytest.raises(RuntimeError):
        a.take_slot()  # exhausted without ensure_free/grow


def test_allocator_partial_free_reopens_page():
    a = PageAllocator(128)
    a.add_region(0, 0, 1)
    slots = [a.take_slot() for _ in range(128)]  # page full
    a.release_slot(slots[7])
    assert a.take_slot() == slots[7]  # the freed slot is allocatable again


def test_allocator_tenant_quotas_and_regions():
    a = PageAllocator(128, tenant_quotas={"acme": 2})
    a.add_region(0, 0, 2)
    a.add_region(1, 256, 2)
    acme = [a.take_slot("acme") for _ in range(256)]  # exactly 2 pages
    assert a.tenant_pages["acme"] == 2
    with pytest.raises(PageQuotaExceeded):
        a.take_slot("acme")
    assert a.quota_capped_slots("acme") == 0
    # another tenant still allocates; regions restrict placement
    s = a.take_slot("globex", regions=[1])
    assert 256 <= s < 512
    # freeing acme's pages returns quota headroom
    for s in acme:
        a.release_slot(s)
    assert a.quota_remaining_pages("acme") == 2
    assert a.take_slot("acme") in set(acme) | set(range(512))


def test_pool_grow_appends_extent_without_touching_old():
    pool = DevicePagePool(8, reserved_space=1024, rows_per_page=1024)
    assert pool.capacity == 1024 and len(pool.extents) == 1
    first = pool.extents[0]
    pool.ensure_free(1500)
    assert pool.capacity >= 2048 and pool.extents[0] is first
    assert pool.grow_events >= 1
    # slot→extent mapping and page-aligned bases
    assert pool.extent_index_of(0) == 0
    assert pool.extent_index_of(1024) == 1


# ---------------------------------------------------------------------------
# paged index vs slab: byte-identical across churn
# ---------------------------------------------------------------------------

def test_default_is_paged_and_opt_out_works(monkeypatch):
    monkeypatch.delenv("PATHWAY_PAGED_STORE", raising=False)
    idx = BruteForceKnnIndex(8)
    assert isinstance(idx, PagedKnnIndex)
    slab = BruteForceKnnIndex(8, paged=False)
    assert type(slab) is BruteForceKnnIndex
    monkeypatch.setenv("PATHWAY_PAGED_STORE", "0")
    assert type(BruteForceKnnIndex(8)) is BruteForceKnnIndex


@pytest.mark.parametrize("metric", [KnnMetric.L2SQ, KnnMetric.COS])
def test_churn_byte_identical_vs_slab(metric):
    """The acceptance-pinned property: interleaved ingest/delete/grow/
    search — paged top-k == slab top-k, keys AND distances, byte for
    byte."""
    rng = np.random.default_rng(11)
    paged = BruteForceKnnIndex(16, metric=metric, paged=True)
    slab = BruteForceKnnIndex(16, metric=metric, paged=False)
    assert isinstance(paged, PagedKnnIndex)
    live: list[int] = []
    next_key = 0

    def step(op):
        nonlocal next_key
        if op == "ingest":
            n = int(rng.integers(50, 400))
            keys = [Pointer(next_key + i) for i in range(n)]
            vecs = rng.normal(size=(n, 16)).astype(np.float32)
            paged.add_batch(keys, vecs)
            slab.add_batch(keys, vecs)
            live.extend(range(next_key, next_key + n))
            next_key += n
        elif op == "delete" and live:
            kill = rng.choice(len(live),
                              size=min(len(live), 120), replace=False)
            for i in sorted(kill, reverse=True):
                k = live.pop(int(i))
                paged.remove(Pointer(k))
                slab.remove(Pointer(k))
        elif op == "update" and live:
            k = int(live[int(rng.integers(len(live)))])
            v = rng.normal(size=(1, 16)).astype(np.float32)
            paged.add_batch([Pointer(k)], v)
            slab.add_batch([Pointer(k)], v)

    ops = rng.choice(["ingest", "delete", "update", "search"], size=30)
    for op in ops:
        step(op)
        if op == "search" or op == ops[-1]:
            qs = [(Pointer(10**9 + i),
                   rng.normal(size=16).astype(np.float32),
                   int(rng.integers(1, 12)), None) for i in range(4)]
            assert paged.search(qs) == slab.search(qs)
    assert paged.capacity > 1024, "churn never grew the store"
    assert len(paged) == len(slab) == len(live)


@pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
def test_churn_low_precision_paged_matches_slab(dtype):
    rng = np.random.default_rng(3)
    paged = BruteForceKnnIndex(16, metric=KnnMetric.COS, dtype=dtype,
                               paged=True)
    slab = BruteForceKnnIndex(16, metric=KnnMetric.COS, dtype=dtype,
                              paged=False)
    keys = [Pointer(i) for i in range(1500)]  # grows past 1024
    vecs = rng.normal(size=(1500, 16)).astype(np.float32)
    paged.add_batch(keys, vecs)
    slab.add_batch(keys, vecs)
    for i in range(0, 600):
        paged.remove(Pointer(i))
        slab.remove(Pointer(i))
    qs = [(Pointer(10**9 + i), vecs[700 + 13 * i], 10, None)
          for i in range(4)]
    rp, rs = paged.search(qs), slab.search(qs)
    for a, b in zip(rp, rs):
        assert [k for k, _ in a] == [k for k, _ in b]
        np.testing.assert_allclose([d for _, d in a], [d for _, d in b],
                                   rtol=1e-5, atol=1e-5)


def test_filtered_search_and_exhaustive_fallback_paged(monkeypatch):
    import pathway_tpu.ops.knn as knn_mod

    monkeypatch.setattr(knn_mod, "_CHUNK_ROWS", 128)
    idx = _mk()
    rng = np.random.default_rng(4)
    n = 1400  # spans two extents after growth
    vecs = rng.normal(size=(n, 8)).astype(np.float32)
    q = vecs[0] + 100.0
    dists = np.sum((vecs - q) ** 2, axis=1)
    allowed = set(np.argsort(dists)[-3:].tolist())
    idx.add_batch([Pointer(i) for i in range(n)], vecs,
                  filter_data=[{"ok": i in allowed} for i in range(n)])
    res = idx.search([(Pointer(10**9), q, 3,
                       lambda d: bool(d and d["ok"]))])[0]
    assert {int(k) for k, _ in res} == allowed


# ---------------------------------------------------------------------------
# fused donated ingest: paged grows, slab still errors (regression)
# ---------------------------------------------------------------------------

def test_fused_ingest_grows_by_allocating_extent():
    import jax.numpy as jnp

    idx = _mk(metric=KnnMetric.COS, dtype="bfloat16")
    ingest = idx.make_fused_ingest(lambda x: x)
    rng = np.random.default_rng(5)
    vals = None
    for base in range(0, 3000, 500):
        vals = jnp.asarray(rng.normal(size=(500, 8)).astype(np.float32))
        ingest([Pointer(base + i) for i in range(500)], vals)
    assert idx.capacity >= 3000
    assert idx.page_stats()["grow_events"] >= 1
    res = idx.search([(Pointer(10**9), np.asarray(vals[17]), 1, None)])
    assert res[0][0][0] == Pointer(2500 + 17)


def test_fused_ingest_slab_still_errors_clearly():
    import jax.numpy as jnp

    slab = _mk(paged=False)
    ingest = slab.make_fused_ingest(lambda x: x)
    with pytest.raises(ValueError, match="cannot grow the slab"):
        ingest([Pointer(i) for i in range(2000)],
               jnp.zeros((2000, 8), jnp.float32))


def test_fused_ingest_quota_exceeded_is_not_swallowed():
    import jax.numpy as jnp

    idx = _mk(tenant="acme", tenant_quotas={"acme": 1024})
    ingest = idx.make_fused_ingest(lambda x: x)
    ingest([Pointer(i) for i in range(1024)], jnp.zeros((1024, 8)))
    with pytest.raises(PageQuotaExceeded):
        ingest([Pointer(5000)], jnp.zeros((1, 8)))


# ---------------------------------------------------------------------------
# page reuse: occupancy bounded under churn
# ---------------------------------------------------------------------------

def test_freed_pages_are_reused_occupancy_bounded():
    idx = _mk()
    rng = np.random.default_rng(6)
    key = 0
    for _round in range(8):
        keys = [Pointer(key + i) for i in range(1000)]
        idx.add_batch(keys, rng.normal(size=(1000, 8)).astype(np.float32))
        idx.search([(Pointer(10**9), np.zeros(8, np.float32), 3, None)])
        for k in keys:
            idx.remove(k)
        key += 1000
    st = idx.page_stats()
    # 8000 rows churned through a store that never needs more than ~2
    # extents: freed pages were reused, not leaked
    assert st["pages_total"] <= 4, st
    assert st["grow_events"] <= 2, st
    assert st["live_rows"] == 0


def test_tenant_quota_enforced_on_add_batch():
    idx = _mk(tenant="acme", tenant_quotas={"acme": 2048})
    rng = np.random.default_rng(7)
    idx.add_batch([Pointer(i) for i in range(2048)],
                  rng.normal(size=(2048, 8)).astype(np.float32))
    with pytest.raises(PageQuotaExceeded):
        idx.add_batch([Pointer(9000)],
                      rng.normal(size=(1, 8)).astype(np.float32))
    # freeing rows frees pages back under quota
    for i in range(2048):
        idx.remove(Pointer(i))
    idx.add_batch([Pointer(9000)],
                  rng.normal(size=(1, 8)).astype(np.float32))
    assert len(idx) == 1


# ---------------------------------------------------------------------------
# stats surfaces
# ---------------------------------------------------------------------------

def test_live_paged_stats_aggregates():
    idx = _mk(tenant="acme")
    idx.add_batch([Pointer(i) for i in range(10)],
                  np.zeros((10, 8), np.float32))
    st = live_paged_stats()
    assert st is not None
    assert st["pages_total"] >= 1
    assert st["page_rows"] == idx.page_stats()["page_rows"]
    assert "acme" in st["tenants"]


def test_add_batch_device_and_mirror_sync_across_extents():
    import jax.numpy as jnp

    rng = np.random.default_rng(8)
    vecs = rng.normal(size=(1500, 8)).astype(np.float32)
    host = _mk()
    dev = _mk()
    keys = [Pointer(i) for i in range(1500)]
    host.add_batch(keys, vecs)
    dev.add_batch_device(keys, jnp.asarray(vecs))
    q = [(Pointer(900 + i), vecs[i * 7], 5, None) for i in range(4)]
    assert host.search(q) == dev.search(q)
    # exact host-side read path syncs the stale mirror per extent
    got = dev._exhaustive_filtered_search(vecs[1400], 1, lambda d: True)
    assert got[0][0] == Pointer(1400)


def test_latency_probe_multi_extent():
    idx = _mk()
    rng = np.random.default_rng(9)
    idx.add_batch([Pointer(i) for i in range(1500)],
                  rng.normal(size=(1500, 8)).astype(np.float32))
    idx.search([(Pointer(10**9), np.zeros(8, np.float32), 3, None)])
    assert len(idx._pool.extents) >= 2
    ms = idx.latency_probe(batch_size=1, k=5, reps=4)
    assert ms > 0.0


# ---------------------------------------------------------------------------
# sharded paged store
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh4():
    import os

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    from pathway_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(data=4, model=1))


def test_sharded_paged_grow_without_remap(mesh4):
    from pathway_tpu.parallel.sharded_knn import (PagedShardedKnnIndex,
                                                  ShardedKnnIndex)

    idx = ShardedKnnIndex(8, mesh=mesh4, reserved_space=8, page_rows=128,
                          paged=True)
    assert isinstance(idx, PagedShardedKnnIndex)
    assert idx.cap_per_shard == 128  # page-aligned minimum
    rng = np.random.default_rng(10)
    n = idx.total_capacity + 200
    vecs = rng.normal(size=(n, 8)).astype(np.float32)
    keys = [Pointer(i) for i in range(n)]
    idx.add_batch(keys, vecs)
    slot_snapshot = dict(idx._key_to_slot)
    idx.add_batch([Pointer(n)],
                  rng.normal(size=(1, 8)).astype(np.float32))
    # online growth: NO slot was remapped (the slab path remaps them all)
    assert all(idx._key_to_slot[k] == s for k, s in slot_snapshot.items())
    for probe in (0, n // 2, n - 1):
        res = idx.search([(Pointer(10**6), vecs[probe], 1, None)])
        assert res[0] and res[0][0][0] == Pointer(probe)
    assert idx.page_stats()["grow_events"] >= 1


def test_sharded_paged_tenant_quota_enforced(mesh4):
    from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex

    idx = ShardedKnnIndex(8, mesh=mesh4, reserved_space=8, page_rows=128,
                          paged=True, tenant="acme",
                          tenant_quotas={"acme": 512})  # 4 pages
    rng = np.random.default_rng(13)
    idx.add_batch([Pointer(i) for i in range(512)],
                  rng.normal(size=(512, 8)).astype(np.float32))
    with pytest.raises(PageQuotaExceeded):
        idx.add_batch([Pointer(9000)],
                      rng.normal(size=(1, 8)).astype(np.float32))
    for i in range(512):
        idx.remove(Pointer(i))
    idx.add_batch([Pointer(9000)],
                  rng.normal(size=(1, 8)).astype(np.float32))
    assert len(idx) == 1


def test_sharded_paged_matches_contiguous(mesh4):
    from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex

    rng = np.random.default_rng(12)
    vecs = rng.normal(size=(700, 8)).astype(np.float32)
    keys = [Pointer(i) for i in range(700)]
    paged = ShardedKnnIndex(8, mesh=mesh4, reserved_space=8, page_rows=128,
                            paged=True)
    flat = ShardedKnnIndex(8, mesh=mesh4, reserved_space=700, paged=False)
    paged.add_batch(keys, vecs)
    flat.add_batch(keys, vecs)
    for i in range(0, 700, 2):
        paged.remove(Pointer(i))
        flat.remove(Pointer(i))
    qs = [(Pointer(10**6 + i), vecs[101 + 2 * i], 6, None)
          for i in range(3)]
    rp, rf = paged.search(qs), flat.search(qs)
    for a, b in zip(rp, rf):
        assert [k for k, _ in a] == [k for k, _ in b]
        np.testing.assert_allclose([d for _, d in a], [d for _, d in b],
                                   rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# ragged encoder batching
# ---------------------------------------------------------------------------

def _tiny_embedders(**kw):
    import jax.numpy as jnp

    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    cfg = EncoderConfig.tiny(compute_dtype=jnp.float32, **kw)
    return (JaxEncoderEmbedder(config=cfg, ragged=True, max_len=64),
            JaxEncoderEmbedder(config=cfg, ragged=False, max_len=64))


TEXTS = ["hello world foo", "a",
         "some much longer text with many more words than the others "
         "to span packing widths", "mid size text here ok"] * 9


@pytest.mark.parametrize("pooling", ["cls", "mean"])
def test_ragged_encode_matches_per_row(pooling):
    ragged, plain = _tiny_embedders(pooling=pooling)
    er = np.asarray(ragged.encode_batch_device(TEXTS))
    ep = np.asarray(plain.encode_batch_device(TEXTS))
    assert er.shape == ep.shape
    cos = np.sum(er * ep, axis=1)
    assert cos.min() > 0.9999, cos.min()


def test_ragged_packing_shapes_and_order():
    ragged, _ = _tiny_embedders()
    chunks = ragged.pack_ragged(TEXTS)
    n_docs = sum(c[1] for c in chunks)
    assert n_docs == len(TEXTS)
    for (ids, doc_map, pos, dseq, doff), n_real, n_pad in chunks:
        assert ids.shape == doc_map.shape == pos.shape
        assert ids.shape[0] in ragged.ragged_buckets()
        assert dseq.shape == doff.shape == (n_pad,)
        # docs numbered 0..n_real-1 in input order; padding rows -1
        assert set(np.unique(doc_map)) <= set(range(-1, n_real))


def test_ragged_fused_ingest_end_to_end():
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.ops.knn import DeviceEmbeddingKnnIndex
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    cfg = EncoderConfig.tiny()
    emb = JaxEncoderEmbedder(config=cfg, ragged=True, max_len=64)
    inner = BruteForceKnnIndex(cfg.hidden, metric=KnnMetric.COS,
                               dtype="bfloat16", paged=True)
    idx = DeviceEmbeddingKnnIndex(emb, inner)
    texts = [f"document number {i} with content {i * 7}" for i in range(150)]
    idx.add_batch([Pointer(i) for i in range(150)], texts)
    assert len(idx) == 150
    res = idx.search([(Pointer(10**9), texts[42], 1, None)])
    assert res[0][0][0] == Pointer(42)


def test_ragged_warmup_compile_count_under_six():
    import pathway_tpu as pw
    from pathway_tpu.models.encoder import EncoderConfig
    from pathway_tpu.ops.knn import DeviceEmbeddingKnnIndex
    from pathway_tpu.xpacks.llm.embedders import JaxEncoderEmbedder

    cfg = EncoderConfig.tiny(max_len=512)
    emb = JaxEncoderEmbedder(config=cfg, ragged=True, max_len=512)
    idx = DeviceEmbeddingKnnIndex(
        emb, BruteForceKnnIndex(cfg.hidden, metric=KnnMetric.COS,
                                paged=True))
    out = pw.warmup(emb, index=idx, cache=False)
    # leaked gc-pending fused programs from other tests may add autojit
    # entries — the ragged ladder is what this pin counts
    ladder = [e for e in out["compiled"] if e[0] != "autojit"]
    assert 0 < len(ladder) <= 6, out["compiled"]
    assert len(idx) == 0  # warmup scratch rows retracted
    # the width-bucket zoo this replaces is ~18 compiles
    assert len(emb.bucket_widths()) >= 15
